"""nidtlint core: findings, suppression pragmas, rule registry, drivers.

The linter is dependency-free (stdlib ``ast`` + ``tokenize`` only) so it
can run as a tier-1 gate in any environment the package itself runs in.

Suppression: append ``# nidt: allow[rule-id] -- one-line justification``
to any line of the offending simple statement (findings anchored on a
``class``/``def``/``with`` header take the pragma on exactly that line).
The justification is mandatory — a bare pragma is itself a finding (rule
``pragma``), so every suppressed invariant in the tree carries its parity
reason next to it. Multiple ids may be listed:
``allow[lock-send, determinism-global-random]``.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize
from typing import Iterable, Iterator

_PRAGMA_RE = re.compile(
    r"#\s*nidt:\s*allow\[(?P<ids>[^\]]*)\]\s*(?:(?:--+|[:–—])\s*"
    r"(?P<why>\S.*))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at ``path:line``."""

    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"

    def as_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Pragma:
    """A parsed ``# nidt: allow[...]`` comment."""

    line: int
    rule_ids: tuple[str, ...]
    justification: str


@dataclasses.dataclass
class ModuleInfo:
    """Everything a rule needs about one source file."""

    path: str
    source: str
    tree: ast.Module
    pragmas: dict[int, Pragma]
    aliases: dict[str, str]  # local name -> canonical dotted module path

    @property
    def path_parts(self) -> tuple[str, ...]:
        return tuple(os.path.normpath(self.path).split(os.sep))


class Rule:
    """Base class for a rule family. Subclasses are registered with
    :func:`register` and emit :class:`Finding` objects from ``check``."""

    #: every rule id this family can emit (used by --rules and --list-rules)
    rule_ids: tuple[str, ...] = ()
    description: str = ""

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        raise NotImplementedError


RULE_REGISTRY: dict[str, type[Rule]] = {}


def register(cls: type[Rule]) -> type[Rule]:
    """Class decorator: add a rule family to the registry (keyed by its
    first rule id; all ids must be unique across families)."""
    for rid in cls.rule_ids:
        for other in RULE_REGISTRY.values():
            if other is not cls and rid in other.rule_ids:
                raise ValueError(f"duplicate rule id {rid!r}")
    RULE_REGISTRY[cls.rule_ids[0]] = cls
    return cls


def all_rule_ids() -> list[str]:
    return sorted(rid for cls in RULE_REGISTRY.values() for rid in cls.rule_ids)


# ---------- dotted-name helpers shared by every rule ----------

def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def collect_aliases(tree: ast.Module) -> dict[str, str]:
    """Map local import names to canonical dotted paths, so rules can
    recognize ``np.random.seed`` however numpy was imported."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def normalize(name: str | None, aliases: dict[str, str]) -> str | None:
    """Rewrite the leading component of a dotted name through the module's
    import aliases (``np.random.seed`` -> ``numpy.random.seed``)."""
    if name is None:
        return None
    head, _, rest = name.partition(".")
    head = aliases.get(head, head)
    return f"{head}.{rest}" if rest else head


# ---------- pragma parsing ----------

def parse_pragmas(source: str) -> dict[int, Pragma]:
    pragmas: dict[int, Pragma] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(t.start[0], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, SyntaxError, IndentationError):
        comments = [(i + 1, line[line.index("#"):])
                    for i, line in enumerate(source.splitlines())
                    if "#" in line]
    for line, text in comments:
        m = _PRAGMA_RE.search(text)
        if not m:
            continue
        ids = tuple(s.strip() for s in m.group("ids").split(",") if s.strip())
        pragmas[line] = Pragma(line=line, rule_ids=ids,
                               justification=(m.group("why") or "").strip())
    return pragmas


class _PragmaRule(Rule):
    """Meta rule: every pragma must name known rule ids AND carry a
    one-line justification. Pragma findings are never suppressible —
    otherwise a pragma could excuse itself."""

    rule_ids = ("pragma",)
    description = ("`# nidt: allow[...]` pragmas must list known rule ids "
                   "and end with `-- <one-line justification>`")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        known = set(all_rule_ids())
        for p in mod.pragmas.values():
            if not p.rule_ids:
                yield Finding(mod.path, p.line, "pragma",
                              "empty allow[] — name the rule ids to suppress")
            for rid in p.rule_ids:
                if rid not in known:
                    yield Finding(mod.path, p.line, "pragma",
                                  f"unknown rule id {rid!r} in allow[]")
            if not p.justification:
                yield Finding(
                    mod.path, p.line, "pragma",
                    "missing justification — write `# nidt: allow[id] -- "
                    "why this violation is intentional`")


register(_PragmaRule)


# ---------- drivers ----------

def _selected_rules(rules: Iterable[str] | None) -> list[Rule]:
    if rules is None:
        return [cls() for cls in RULE_REGISTRY.values()]
    wanted = set(rules)
    unknown = wanted - set(all_rule_ids())
    if unknown:
        raise ValueError(f"unknown rule ids: {sorted(unknown)}")
    picked = [cls for cls in RULE_REGISTRY.values()
              if wanted & set(cls.rule_ids)]
    if _PragmaRule not in picked:
        picked.append(_PragmaRule)  # the meta rule always runs
    return [cls() for cls in picked]


def lint_source(source: str, path: str = "<string>",
                rules: Iterable[str] | None = None) -> list[Finding]:
    """Lint one source blob; ``path`` also drives path-scoped rules
    (``distributed/`` lock discipline, ``engines/`` contracts)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 1, "parse-error",
                        f"could not parse: {e.msg}")]
    mod = ModuleInfo(path=path, source=source, tree=tree,
                     pragmas=parse_pragmas(source),
                     aliases=collect_aliases(tree))
    findings: list[Finding] = []
    for rule in _selected_rules(rules):
        findings.extend(rule.check(mod))
    if rules is not None:
        # a family can emit several ids — honor the id-level selection
        # (the pragma meta rule always reports)
        wanted = set(rules) | {"pragma", "parse-error"}
        findings = [f for f in findings if f.rule in wanted]
    return sorted(_apply_suppressions(mod, findings),
                  key=lambda f: (f.line, f.rule, f.message))


#: compound statements own whole bodies — a pragma anywhere inside one
#: must NOT suppress a finding anchored on its header line
_COMPOUND = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.If,
             ast.For, ast.AsyncFor, ast.While, ast.With, ast.AsyncWith,
             ast.Try)


def _apply_suppressions(mod: ModuleInfo,
                        findings: list[Finding]) -> list[Finding]:
    """Drop findings covered by an allow pragma on any line of the SIMPLE
    statement containing them (so a pragma fits naturally on either the
    opening line or the close-paren line of a multi-line call). Findings
    anchored on a compound header (class/def/with/... line) accept a
    pragma on exactly that line — a pragma buried in the body must never
    excuse a class-level contract finding. Justified pragmas only in
    spirit: a bare pragma still suppresses, but the `pragma` meta finding
    it raised is never suppressible, so the tree cannot go green without
    the reason being recorded."""
    simple_spans: list[tuple[int, int]] = [
        (node.lineno, node.end_lineno)
        for node in ast.walk(mod.tree)
        if isinstance(node, ast.stmt)
        and not isinstance(node, _COMPOUND)
        and node.end_lineno is not None]
    out = []
    for f in findings:
        if f.rule == "pragma":
            out.append(f)
            continue
        containing = [s for s in simple_spans if s[0] <= f.line <= s[1]]
        if containing:
            start, end = min(containing, key=lambda s: s[1] - s[0])
            span = range(start, end + 1)
        else:  # compound header: the pragma must sit on the flagged line
            span = range(f.line, f.line + 1)
        if any(f.rule in mod.pragmas[ln].rule_ids
               for ln in span if ln in mod.pragmas):
            continue
        out.append(f)
    return out


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".")
                                 and d != "__pycache__")
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)
        else:
            raise FileNotFoundError(p)


_RULES_VERSION: str | None = None


def _rules_version() -> str:
    """Content hash of every module in analysis/ — a rule edit must
    invalidate the whole finding cache, not just rerun changed files."""
    global _RULES_VERSION
    if _RULES_VERSION is None:
        h = hashlib.sha256()
        here = os.path.dirname(os.path.abspath(__file__))
        for root, dirs, files in os.walk(here):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for fn in sorted(files):
                if fn.endswith(".py"):
                    with open(os.path.join(root, fn), "rb") as fh:
                        h.update(fn.encode())
                        h.update(fh.read())
        _RULES_VERSION = h.hexdigest()
    return _RULES_VERSION


def _cache_key(path: str, source: str,
               rules: Iterable[str] | None) -> str:
    h = hashlib.sha256()
    h.update(_rules_version().encode())
    h.update(b"\0")
    h.update((",".join(sorted(rules)) if rules is not None else "*")
             .encode())
    h.update(b"\0")
    h.update(path.encode())
    h.update(b"\0")
    h.update(source.encode())
    return h.hexdigest()


def lint_paths(paths: Iterable[str],
               rules: Iterable[str] | None = None,
               cache_dir: str | None = None) -> list[Finding]:
    """Lint every ``.py`` under ``paths``. With ``cache_dir``, per-file
    findings are memoized by content hash (key covers the source bytes,
    the rule selection AND a hash of analysis/ itself, so editing a rule
    invalidates everything); a hit skips the parse entirely. The cache
    holds FINDINGS, not verdicts — a hit replays identical output."""
    findings: list[Finding] = []
    for fp in iter_py_files(paths):
        with open(fp, encoding="utf-8") as fh:
            source = fh.read()
        if cache_dir is not None:
            key = _cache_key(fp, source, rules)
            cpath = os.path.join(cache_dir, key + ".json")
            try:
                with open(cpath, encoding="utf-8") as fh:
                    findings.extend(Finding(**d) for d in json.load(fh))
                continue
            except (OSError, json.JSONDecodeError, TypeError):
                pass  # miss or corrupt entry: lint and rewrite
        file_findings = lint_source(source, path=fp, rules=rules)
        findings.extend(file_findings)
        if cache_dir is not None:
            os.makedirs(cache_dir, exist_ok=True)
            tmp = cpath + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump([f.as_json() for f in file_findings], fh)
            os.replace(tmp, cpath)
    return findings
