"""Round-program-discipline rules: engines declare stages, the builder
owns the fused machinery (ISSUE 11).

The declarative round-program builder (engines/program.py) exists so the
fused ``lax.scan`` dispatch, cohort sharding, donation, defenses, and
codec knobs are written ONCE. Two lexical rules keep it that way:

- ``round-program-fused-body`` — no engine module may hand-roll a fused
  round body again: a ``lax.scan`` call lexically inside a
  ``*round*``/``*fused*``-named method of a ``FederatedEngine`` subclass
  (outside engines/program.py itself) is the copy-the-machinery-back
  regression this rule exists to stop. Engines express K-round windows
  by declaring :class:`RoundStages`; the builder scans.
- ``round-program-reason`` — fallback reasons come from the single
  source of truth: a ``*_fallback_key`` override must return ``None`` or
  a string literal that is a key of ``engines/program.py``'s ``REASONS``
  table (parsed from source, dependency-free). Ad-hoc reason strings
  resurrect the grep-only fallback reporting the structured
  ``nidt_fallback_total`` counter replaced.
"""

from __future__ import annotations

import ast
import functools
import os
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    normalize,
    register,
)
from neuroimagedisttraining_tpu.analysis.engine_contract import (
    ROOT_CLASS,
    _classes_of,
    _parse_file,
    _sibling_classes,
    EngineContractRule,
)

_PACKAGED_PROGRAM = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "engines", "program.py")

#: path suffixes allowed to contain scan-fused round bodies / reason
#: literals — suffix-matched, not basename-matched, so a future
#: pkg/<other>/program.py with a hand-rolled fused body is NOT exempt
_BUILDER_FILES = ("engines/program.py",)


def _is_builder_file(path: str) -> bool:
    norm = path.replace(os.sep, "/")
    return any(norm == b or norm.endswith("/" + b)
               for b in _BUILDER_FILES)

_SCAN_CALLS = ("jax.lax.scan", "lax.scan")
_KEY_METHODS = ("fused_fallback_key", "cohort_fallback_key")


@functools.lru_cache(maxsize=None)
def _reason_keys(path: str = _PACKAGED_PROGRAM) -> frozenset[str]:
    """The REASONS table's keys, parsed from engines/program.py source
    (the linter stays dependency-free — no runtime import of jax; the
    result is constant per process, so one parse serves every linted
    module)."""
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return frozenset()
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name) and node.target.id == "REASONS" \
                and isinstance(node.value, ast.Dict):
            return frozenset(
                k.value for k in node.value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str))
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "REASONS"
                for t in node.targets) and isinstance(node.value, ast.Dict):
            return frozenset(
                k.value for k in node.value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str))
    return frozenset()


def _scan_calls_in(fn: ast.AST, aliases: dict) -> Iterator[ast.Call]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = normalize(dotted_name(node.func), aliases)
            if name in _SCAN_CALLS:
                yield node


@register
class RoundProgramRule(Rule):
    rule_ids = ("round-program-fused-body", "round-program-reason")
    description = ("engines declare round stages through the builder "
                   "(engines/program.py): no hand-rolled lax.scan fused "
                   "round bodies in engine classes, and *_fallback_key "
                   "overrides return keys from the REASONS table")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if _is_builder_file(mod.path):
            return
        table = _sibling_classes(mod.path)
        table.update(_classes_of(mod.tree))
        if ROOT_CLASS not in table:
            from neuroimagedisttraining_tpu.analysis.engine_contract import (
                _PACKAGED_BASE,
            )
            table.update(_parse_file(_PACKAGED_BASE))
        engine_classes = set()
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            info = _classes_of(mod.tree).get(node.name)
            if info is None:
                continue
            chain = EngineContractRule._engine_ancestry(info, table)
            if chain is not None or node.name == ROOT_CLASS:
                engine_classes.add(node.name)
        if not engine_classes:
            return
        keys = _reason_keys()
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef) \
                    or node.name not in engine_classes:
                continue
            for stmt in node.body:
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                yield from self._check_method(mod, node, stmt, keys)

    def _check_method(self, mod: ModuleInfo, cls: ast.ClassDef,
                      fn: ast.FunctionDef, keys: frozenset[str]
                      ) -> Iterator[Finding]:
        name = fn.name.lower()
        if "round" in name or "fused" in name:
            for call in _scan_calls_in(fn, mod.aliases):
                yield Finding(
                    mod.path, call.lineno, "round-program-fused-body",
                    f"{cls.name}.{fn.name} hand-rolls a lax.scan fused "
                    "round body; engines declare RoundStages and the "
                    "builder (engines/program.py) owns the K-round scan "
                    "— hand-rolled copies drift from the "
                    "donation/sharding/window contracts")
        if fn.name in _KEY_METHODS and keys:
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str) \
                        and node.value.value not in keys:
                    yield Finding(
                        mod.path, node.lineno, "round-program-reason",
                        f"{cls.name}.{fn.name} returns "
                        f"{node.value.value!r}, which is not a key of "
                        "engines/program.py REASONS — fallback reasons "
                        "have ONE source of truth (the structured "
                        "nidt_fallback_total counter labels by key)")
