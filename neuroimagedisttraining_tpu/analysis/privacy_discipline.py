"""Privacy-plane key discipline: DP noise and share-mask randomness must
derive from config-threaded streams.

The privacy guarantees (privacy/accountant.py's epsilon report, the
secure_quant masking) are statements about WHERE randomness came from:
noise drawn from an ad-hoc PRNG root minted at the call site is
unauditable (the accountant charges for noise whose stream nothing
pins), and a numpy global-stream draw is order-dependent across threads
— the determinism family's objection, sharpened here because a
perturbed noise stream silently changes the privacy the run actually
delivered.

- ``dp-key-discipline`` — inside ``privacy/`` modules, constructing a
  jax PRNG root (``jax.random.key`` / ``jax.random.PRNGKey``) is
  flagged: keys must be threaded in as arguments by the caller, derived
  (``fold_in`` / ``split``) from the config seed. Repo-wide, calling
  ``add_weak_dp_noise`` (core/robust.py) with an INLINE-minted root as
  its rng argument is flagged for the same reason.

The determinism family already covers numpy global-stream draws
repo-wide (privacy/ included — nidtlint walks the whole package); this
family adds the jax-key provenance rule the DP paths need on top.
"""

from __future__ import annotations

import ast
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    normalize,
    register,
)

_KEY_ROOTS = {"jax.random.key", "jax.random.PRNGKey"}


def _is_key_root(node: ast.AST, aliases: dict[str, str]) -> bool:
    return (isinstance(node, ast.Call)
            and normalize(dotted_name(node.func), aliases) in _KEY_ROOTS)


@register
class PrivacyKeyDisciplineRule(Rule):
    rule_ids = ("dp-key-discipline",)
    description = ("privacy/ modules must not mint jax PRNG roots "
                   "(jax.random.key/PRNGKey) — noise/mask keys are "
                   "threaded in from config by the caller; repo-wide, "
                   "add_weak_dp_noise must not take an inline-minted "
                   "root as its rng")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        in_privacy = "privacy" in mod.path_parts
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if in_privacy and _is_key_root(node, mod.aliases):
                yield Finding(
                    mod.path, node.lineno, "dp-key-discipline",
                    "jax PRNG root minted inside privacy/ — thread a "
                    "config-derived key in as an argument instead (the "
                    "accountant's epsilon is only meaningful for noise "
                    "whose stream the config pins)")
                continue
            fname = dotted_name(node.func)
            if fname and fname.split(".")[-1] == "add_weak_dp_noise":
                args = list(node.args) + [kw.value for kw in node.keywords
                                          if kw.arg == "rng"]
                for a in args:
                    if _is_key_root(a, mod.aliases):
                        yield Finding(
                            mod.path, node.lineno, "dp-key-discipline",
                            "add_weak_dp_noise called with an inline "
                            "jax.random.key(...) root — fold the key "
                            "from the config seed (fold_in per "
                            "round/client) so the noise stream is "
                            "auditable and replayable")
