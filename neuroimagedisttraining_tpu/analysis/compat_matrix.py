"""Generated compatibility matrix — DO NOT EDIT BY HAND.

Extracted from the tree's startup-rejection sites (``parser.error`` /
``ap.error`` in the CLIs, ``raise ValueError`` in ctors) by the
contract checker (analysis/contracts.py). Each row names WHERE the
rejection lives, WHICH knobs its guard reads, and the message —
the machine-readable twin of ARCHITECTURE.md's compatibility tables.

Regenerate (also rewrites the ARCHITECTURE.md block)::

    python -m neuroimagedisttraining_tpu.analysis --regen-compat

The project pass (``--project``) diffs this artifact against a fresh
extraction (``compat-matrix-drift``) and the markdown twin against
this artifact (``compat-matrix-doc-stale``), so a new ctor rejection
without a regenerated matrix — or a hand-edited table — fails the
lint.
"""

from __future__ import annotations

from typing import Any

MATRIX: tuple[dict[str, Any], ...] = (
    {
        "where": 'neuroimagedisttraining_tpu/__main__.py',
        "knobs": ('algorithm', 'defense_type'),
        "message": (
            '--defense does not compose with secure aggregation (no per-c'
            'lient plaintext to select over); the clip family (norm_diff_'
            'clipping, weak_dp) c'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/__main__.py',
        "knobs": ('algorithm', 'wire_codec'),
        "message": (
            '--wire_codec does not compose with the secure turboaggregate'
            " engine (the codec's float stages would corrupt the GF(p) sh"
            'are embedding). The '),
    },
    {
        "where": 'neuroimagedisttraining_tpu/__main__.py',
        "knobs": ('client_optimizer', 'fused_update'),
        "message": (
            '--fused_update fuses the SGD clip/momentum/update tail (ops/'
            'fused_update.py); --client_optimizer has no fused kernel and'
            ' would silently trai'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/__main__.py',
        "knobs": ('defense_type', 'dp_epsilon_budget', 'dp_sigma'),
        "message": (
            '--dp_epsilon_budget needs an armed noise path to budget (--d'
            'p_sigma/--dp_clip on a DP engine, or --defense weak_dp): wit'
            'hout one the account'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/__main__.py',
        "knobs": ('defense_type', 'secure_quant'),
        "message": (
            '--defense does not compose with --secure_quant (no per-clien'
            't plaintext to select over); the clip family (norm_diff_clip'
            'ping, weak_dp) compo'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/__main__.py',
        "knobs": ('dp_clip', 'dp_sigma'),
        "message": (
            '--dp_clip/--dp_sigma need an engine with the round-level DP '
            'transform; algorithm would train un-noised while the account'
            'ant reported epsilon'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/__main__.py',
        "knobs": ('dp_clip', 'dp_sigma'),
        "message": (
            '--dp_sigma needs --dp_clip > 0 (the clip bound is the sensit'
            'ivity the noise multiplier is stated against)'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/__main__.py',
        "knobs": ('loss_scale', 'precision'),
        "message": (
            '--loss_scale needs --precision bf16_mixed: under fp32 the sc'
            'ale/unscale pair would only perturb rounding and break the b'
            'itwise-f32 contract'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/__main__.py',
        "knobs": ('secure_quant', 'wire_codec'),
        "message": (
            '--secure_quant does not compose with --wire_codec (the codec'
            "'s float stages would corrupt the GF(p) residue embedding); "
            'see ARCHITECTURE.md '),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('async_server', 'buffer_k', 'max_staleness', 'staleness_alpha'),
        "message": (
            '--buffer_k/--max_staleness/--staleness_alpha must be >= 0'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('async_server', 'ingest_workers'),
        "message": (
            '--ingest_workers shards the ASYNC ingest plane (asyncfl/inge'
            'st.py) — add --async_server'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('async_server', 'quorum', 'round_deadline'),
        "message": (
            '--async_server has no round barrier: --round_deadline/--quor'
            'um do not apply (uploads aggregate every --buffer_k arrivals'
            '; staleness is bound'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('async_server', 'secure', 'secure_quant'),
        "message": (
            '--async_server is incompatible with dense --secure: the two-'
            "phase secure weight exchange (every client's normalized weig"
            'ht depends on every '),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('async_server', 'transport'),
        "message": (
            '--async_server pairs with the selector socket core (asyncfl/'
            'loop.py); the broker daemon is a thread-per-connection trans'
            'port with its own sc'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('defense', 'ingest_workers', 'quarantine_rounds'),
        "message": (
            '--ingest_workers supports neither server-side defenses nor q'
            'uarantine: workers fold uploads into partial aggregates, so '
            'the root never sees '),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('defense', 'secure', 'secure_quant'),
        "message": (
            '--defense is incompatible with secure aggregation (quantized'
            ' included): order statistics have no per-silo plaintext to s'
            'elect over; only the'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('defense', 'secure', 'secure_quant'),
        "message": (
            '--secure (dense) is incompatible with --defense: additive-sh'
            'are aggregation never reveals per-silo updates to defend ove'
            'r. The clip-family d'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('fault_spec', 'secure'),
        "message": (
            '--secure cannot simulate byz: value faults (the share algebr'
            'a hides the very values the attack would corrupt; see cross_'
            'silo)'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('heartbeat_interval', 'heartbeat_timeout'),
        "message": (
            '--heartbeat_timeout requires 0 < --heartbeat_interval < time'
            'out (got interval= , timeout= )'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('ingest_shm', 'ingest_workers', 'sync_delta'),
        "message": (
            '--ingest_shm/--sync_delta are sharded-ingest-plane transport'
            's (asyncfl/ingest.py) — add --ingest_workers N'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('ingest_workers', 'regions'),
        "message": (
            '--regions interposes regional sub-aggregators in the SHARDED'
            ' ingest plane — pass --ingest_workers N (workers per region)'
            ' too'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('mpc_n_shares', 'n_aggregators'),
        "message": (
            '--n_aggregators ( ) must equal --mpc_n_shares ( ): slot j ro'
            'utes to aggregator j'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('n_aggregators', 'role'),
        "message": (
            '--role aggregator requires --n_aggregators > 0 (same value o'
            'n every rank)'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('n_aggregators', 'role', 'slot_index'),
        "message": (
            '--slot_index ( ) must be in [0, )'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('n_aggregators', 'secure'),
        "message": (
            '--n_aggregators requires --secure'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('n_aggregators', 'secure_quant'),
        "message": (
            '--secure_quant does not compose with --n_aggregators: mask s'
            "lots ride as PRG seeds, and any node holding a client's seed"
            's can expand every n'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('n_aggregators', 'transport'),
        "message": (
            '--transport broker routes messages through the MQTT topic sc'
            'heme (server <-> client only); the grouped multi-aggregator '
            'deployment needs --t'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('quarantine_rounds', 'secure'),
        "message": (
            'secure aggregation is incompatible with --quarantine_rounds:'
            ' the outlier scorer has no per-silo plaintext to score (see '
            "ARCHITECTURE.md 'Pri"),
    },
    {
        "where": 'neuroimagedisttraining_tpu/distributed/run.py',
        "knobs": ('secure', 'wire_codec', 'wire_mask_density'),
        "message": (
            '--secure uploads must ride the wire as field elements: the c'
            'odec would break the GF(p) share algebra or leak mask suppor'
            't. The COMPRESSED se'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/engines/base.py',
        "knobs": ('defense_type', 'fed'),
        "message": (
            'algorithm does not support --defense ; this engine supports:'
            ' ,'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/engines/base.py',
        "knobs": ('defense_type', 'fed', 'secure_quant'),
        "message": (
            '--defense does not compose with --secure_quant (no per-clien'
            't plaintext to select over); the clip family (norm_diff_clip'
            'ping, weak_dp) compo'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/engines/base.py',
        "knobs": ('dp_clip', 'dp_sigma', 'fed'),
        "message": (
            '--dp_sigma needs --dp_clip > 0: the clip bound IS the sensit'
            'ivity the noise multiplier is stated against (privacy/accoun'
            'tant.py)'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/engines/base.py',
        "knobs": ('dp_clip', 'dp_sigma', 'fed'),
        "message": (
            'algorithm does not apply the --dp_clip/--dp_sigma round-leve'
            'l DP transform (its round program would train un-noised whil'
            'e the accountant rep'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/engines/base.py',
        "knobs": ('dp_clip', 'dp_sigma', 'fed'),
        "message": (
            'dp_sigma/dp_clip must be >= 0 (got / )'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/engines/base.py',
        "knobs": ('fed', 'secure_quant'),
        "message": (
            '--secure_quant does not compose with --wire_codec: the codec'
            "'s float stages would corrupt the GF(p) residue embedding (f"
            'ield-element frames,'),
    },
    {
        "where": 'neuroimagedisttraining_tpu/engines/base.py',
        "knobs": ('fed', 'secure_quant'),
        "message": (
            '--secure_quant field too small for the in-process integer-we'
            "ight fold: a -client cohort exceeds the -bit field's capacit"
            'y of weight units — '),
    },
    {
        "where": 'neuroimagedisttraining_tpu/engines/base.py',
        "knobs": ('fed', 'secure_quant'),
        "message": (
            'algorithm does not simulate --secure_quant: its round has no'
            ' default server-side aggregation tail for the field fold to '
            'replace; supported: '),
    },
)
