"""Whole-program contract families over the declarative surfaces.

Four cross-file contracts ride the project model (analysis/project.py),
run under ``python -m neuroimagedisttraining_tpu.analysis --project``:

1. **flag<->config** — every CLI flag is consumed (mapped into a config
   field by ``config_from_args`` or read as ``args.<dest>``), every
   config field is constructible from the CLI, argparse and dataclass
   defaults agree through the mapping's wrappers, and a flag declared
   on BOTH CLIs agrees on type/default/choices/action.
2. **metric-name closure** — every metric registration and every
   ``names.<CONST>`` consumer resolves to ``obs/names.py``; a declared
   name nothing references is an orphan finding.
3. **compatibility matrix as data** — the startup-rejection sites
   (``parser.error``/``ap.error`` guards, ctor ``ValueError`` guards
   reading >= 2 knobs) are extracted and diffed against the committed
   ``analysis/compat_matrix.py`` artifact and its ARCHITECTURE.md
   markdown twin; drift in either direction is a finding, and the twin
   must be regenerated, never hand-edited.
4. **interprocedural donation** — module-level functions that forward
   parameters into donated argument positions get per-function
   summaries, propagated to a fixed point across imports; a caller in
   ANOTHER module that rereads a buffer it passed into a summarized
   donated position is flagged (the per-file rule only sees one file).

Every family suppresses through the standard ``# nidt: allow[rule-id]
-- why`` pragma on the flagged line. The REASONS, bench_gate SPECS,
and autotuner RECIPE_KEYS closures ride family 2's spirit (names must
resolve; orphans surface).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    dotted_name,
    normalize,
    register,
)
from neuroimagedisttraining_tpu.analysis.donation import (
    DonationDisciplineRule,
)
from neuroimagedisttraining_tpu.analysis.project import (
    MD_BEGIN,
    UNEVAL,
    FlagInfo,
    ProjectModel,
    ProjectRule,
    apply_wrapper,
    argparse_flags,
    attr_reads,
    bench_specs,
    committed_matrix,
    config_assigned_fields,
    config_mapping,
    dataclass_fields,
    doc_matrix_block,
    knob_vocabulary,
    load_artifact,
    metric_registrations,
    names_attr_uses,
    names_table,
    reason_key_uses,
    reasons_span,
    reasons_table,
    rejection_rows,
    render_matrix_md,
    string_literals,
)
from neuroimagedisttraining_tpu.analysis.trace_safety import (
    _annotate_parents,
    _DefIndex,
)

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


# ---------------------------------------------------------------------------
# family 1: flag <-> config
# ---------------------------------------------------------------------------

def _fmt(value: object) -> str:
    return "<uneval>" if value is UNEVAL else repr(value)


@register
class FlagConfigRule(ProjectRule):
    rule_ids = ("flag-config-default-drift", "flag-config-unmapped-flag",
                "flag-config-unmapped-field", "flag-config-cross-cli-drift")
    description = (
        "CLI flags and config dataclass fields must stay in lockstep: "
        "every flag consumed, every field constructible, defaults equal "
        "through the config_from_args wrappers, and flags shared by both "
        "CLIs agree on type/default/choices")

    def project_check(self, model: ProjectModel) -> Iterator[Finding]:
        main_cli = model.module(f"{model.package}/__main__.py")
        dist_cli = model.find("distributed/run.py")
        cfg = model.module(f"{model.package}/config.py")
        main_flags = argparse_flags(main_cli) if main_cli else {}
        dist_flags = argparse_flags(dist_cli) if dist_cli else {}
        if main_cli is not None:
            yield from self._check_consumed(main_cli, main_flags)
        if dist_cli is not None:
            yield from self._check_consumed(dist_cli, dist_flags)
        if main_cli is not None and cfg is not None:
            yield from self._check_mapping(main_cli, cfg, main_flags)
        if main_cli is not None and dist_cli is not None:
            yield from self._check_cross_cli(main_flags, dist_flags,
                                             dist_cli.path)

    def _check_consumed(self, cli: ModuleInfo,
                        flags: dict[str, FlagInfo]) -> Iterator[Finding]:
        mapped = {m.dest for m in config_mapping(cli)}
        read = attr_reads(cli, "args")
        for dest, flag in flags.items():
            if dest not in mapped and dest not in read:
                yield Finding(
                    cli.path, flag.lineno, "flag-config-unmapped-flag",
                    f"flag {flag.options[0]} (dest {dest!r}) is declared "
                    "but never consumed — neither mapped into a config "
                    "field by config_from_args nor read as "
                    f"args.{dest} anywhere in this CLI")

    def _check_mapping(self, cli: ModuleInfo, cfg: ModuleInfo,
                       flags: dict[str, FlagInfo]) -> Iterator[Finding]:
        fields = dataclass_fields(cfg)
        mappings = config_mapping(cli)
        assigned = config_assigned_fields(cli)
        # field coverage: every dataclass field is constructible from the
        # CLI path (assigned SOMETHING in config_from_args)
        for cls, cls_fields in fields.items():
            covered = assigned.get(cls, set())
            for name, info in cls_fields.items():
                if name not in covered:
                    yield Finding(
                        cfg.path, info.lineno, "flag-config-unmapped-field",
                        f"{cls}.{name} is not assigned by config_from_args "
                        "— the field cannot be set from the CLI (add a "
                        "flag + mapping, or pragma-justify why it is "
                        "internal-only)")
        # default agreement through the wrapper
        for m in mappings:
            flag = flags.get(m.dest)
            field = fields.get(m.cls, {}).get(m.field)
            if flag is None or field is None:
                continue
            if flag.default is UNEVAL or field.default is UNEVAL:
                continue
            expected = apply_wrapper(flag.default, m.wrapper)
            if expected is UNEVAL:
                continue
            if expected != field.default:
                yield Finding(
                    cli.path, m.lineno, "flag-config-default-drift",
                    f"default drift: {flag.options[0]} defaults to "
                    f"{_fmt(flag.default)} (-> {_fmt(expected)} after "
                    f"{m.wrapper or 'identity'} wrapper) but "
                    f"{m.cls}.{m.field} defaults to {_fmt(field.default)} "
                    "— a config built in code and one built from the CLI "
                    "silently diverge")

    def _check_cross_cli(self, main_flags: dict[str, FlagInfo],
                         dist_flags: dict[str, FlagInfo],
                         dist_path: str) -> Iterator[Finding]:
        by_option = {opt: f for f in main_flags.values()
                     for opt in f.options}
        for flag in dist_flags.values():
            for opt in flag.options:
                twin = by_option.get(opt)
                if twin is None:
                    continue
                drifts = []
                if flag.type != twin.type:
                    drifts.append(f"type {flag.type}!={twin.type}")
                if flag.action != twin.action:
                    drifts.append(f"action {flag.action}!={twin.action}")
                if (flag.default is not UNEVAL and twin.default is not UNEVAL
                        and flag.default != twin.default):
                    drifts.append(f"default {_fmt(flag.default)}!="
                                  f"{_fmt(twin.default)}")
                if (flag.choices is not UNEVAL and twin.choices is not UNEVAL
                        and flag.choices != twin.choices):
                    drifts.append(f"choices {_fmt(flag.choices)}!="
                                  f"{_fmt(twin.choices)}")
                if drifts:
                    yield Finding(
                        dist_path, flag.lineno, "flag-config-cross-cli-drift",
                        f"{opt} is declared on both CLIs but drifts: "
                        + "; ".join(drifts)
                        + " — the same flag spelling must mean the same "
                        "thing everywhere (or pragma-justify the "
                        "smoke-scale divergence)")
                break  # one shared option string is enough to pair them


# ---------------------------------------------------------------------------
# family 2: metric-name closure (+ REASONS and bench SPECS closures)
# ---------------------------------------------------------------------------

_METRIC_LITERAL_RE = re.compile(r"nidt_[a-z0-9_]+\Z")


@register
class MetricClosureRule(ProjectRule):
    rule_ids = ("metric-undeclared", "metric-orphan")
    description = (
        "every registered/consumed metric name must resolve to an "
        "obs/names.py declaration (metric-undeclared); a declared name "
        "with zero consumers anywhere is an orphan (metric-orphan)")

    def project_check(self, model: ProjectModel) -> Iterator[Finding]:
        names_mod = model.find("obs/names.py")
        if names_mod is None:
            return
        table = names_table(names_mod)
        values = {v for v, _ in table.values()}
        # every top-level binding is a legal `names.X` attribute target
        # (DECLARED, helper tuples, ...), not just the string constants
        module_attrs = set(table)
        for stmt in names_mod.tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        module_attrs.add(t.id)
            elif (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                module_attrs.add(stmt.target.id)
            elif isinstance(stmt, _FUNCS):
                module_attrs.add(stmt.name)

        used: set[str] = set()  # declared CONSTs with at least one consumer

        for rel, const, line in names_attr_uses(model):
            if rel == names_mod.path:
                continue
            if const in table:
                used.add(const)
            elif const not in module_attrs:
                yield Finding(
                    rel, line, "metric-undeclared",
                    f"names.{const} is not declared in obs/names.py — "
                    "declare the metric name there (the single source of "
                    "truth) before consuming it")

        for reg in metric_registrations(model):
            if reg.const is not None:
                if reg.const in table:
                    used.add(reg.const)
                else:
                    yield Finding(
                        reg.relpath, reg.lineno, "metric-undeclared",
                        f"{reg.kind}() registers names.{reg.const}, which "
                        "obs/names.py does not declare")
            elif reg.literal is not None:
                if reg.literal in values:
                    used |= {c for c, (v, _) in table.items()
                             if v == reg.literal}
                else:
                    yield Finding(
                        reg.relpath, reg.lineno, "metric-undeclared",
                        f"{reg.kind}() registers literal "
                        f"{reg.literal!r}, which obs/names.py does not "
                        "declare — add the constant and spell it "
                        "names.<CONST>")

        # nidt_* literals inside obs/ (names.py exempt): the per-file
        # health-metric-literal rule stops at the obs/ boundary; here the
        # literal must at least resolve to a declared value
        for rel, mod in model.modules.items():
            if "/obs/" not in f"/{rel}" or rel == names_mod.path:
                continue
            for value, line in string_literals(mod):
                if not _METRIC_LITERAL_RE.fullmatch(value):
                    continue
                if value in values:
                    used |= {c for c, (v, _) in table.items() if v == value}
                else:
                    yield Finding(
                        rel, line, "metric-undeclared",
                        f"metric literal {value!r} does not resolve to any "
                        "obs/names.py declaration")

        # literal value matches anywhere else in the tree also count as
        # consumption (manifests under tests/, script-built rule dicts)
        for rel, mod in model.modules.items():
            if rel == names_mod.path:
                continue
            for value, _line in string_literals(mod):
                if value in values:
                    used |= {c for c, (v, _) in table.items() if v == value}

        for const, (value, line) in sorted(table.items()):
            if const not in used:
                yield Finding(
                    names_mod.path, line, "metric-orphan",
                    f"{const} = {value!r} is declared but nothing in the "
                    "tree registers or consumes it — delete the "
                    "declaration or wire up its consumer")


@register
class ReasonClosureRule(ProjectRule):
    rule_ids = ("reason-unknown", "reason-orphan")
    description = (
        "every *_fallback_key return and report_fallback()/reason() "
        "literal must name a key of the engines/program.py REASONS "
        "table (reason-unknown); a key nothing references is an orphan "
        "(reason-orphan)")

    def project_check(self, model: ProjectModel) -> Iterator[Finding]:
        table = reasons_table(model)
        if not table:
            return
        used: set[str] = set()
        for rel, key, line in reason_key_uses(model):
            if key in table:
                used.add(key)
            else:
                yield Finding(
                    rel, line, "reason-unknown",
                    f"fallback reason {key!r} is not a key of the "
                    "engines/program.py REASONS table — the structured "
                    "nidt_fallback_total counter would carry an "
                    "unexplained label")
        # loose consumption: the key literal spelled anywhere outside the
        # table itself (program.py's own builder emits keys inline)
        span = reasons_span(model)
        prog = model.find("engines/program.py")
        for rel, mod in model.modules.items():
            for value, line in string_literals(mod):
                if value not in table:
                    continue
                if (prog is not None and rel == prog.path
                        and span[0] <= line <= span[1]):
                    continue
                used.add(value)
        for key, line in sorted(table.items()):
            if key not in used:
                yield Finding(
                    (prog.path if prog else "engines/program.py"), line,
                    "reason-orphan",
                    f"REASONS key {key!r} is declared but no fallback "
                    "path ever reports it — delete the row or wire up "
                    "the fallback")


@register
class BenchSpecClosureRule(ProjectRule):
    rule_ids = ("bench-spec-closure",)
    description = (
        "every analysis/bench_gate.py SPECS cell path must resolve "
        "inside its committed bench_matrix/*.json artifact")

    def project_check(self, model: ProjectModel) -> Iterator[Finding]:
        from neuroimagedisttraining_tpu.analysis.project import resolve_cell
        gate = model.find("analysis/bench_gate.py")
        if gate is None:
            return
        for artifact, cells in sorted(bench_specs(model).items()):
            doc = load_artifact(model, artifact)
            if doc is None:
                line = cells[0][1] if cells else 1
                yield Finding(
                    gate.path, line, "bench-spec-closure",
                    f"SPECS names bench_matrix/{artifact} but no such "
                    "committed artifact parses as JSON — regenerate it "
                    "(scripts/) or drop the spec")
                continue
            for path, line in cells:
                if not resolve_cell(doc, path):
                    yield Finding(
                        gate.path, line, "bench-spec-closure",
                        f"SPECS cell {path!r} does not resolve in "
                        f"bench_matrix/{artifact} — the gate would fail "
                        "on a missing cell, not a regression")


@register
class RecipeKeyClosureRule(ProjectRule):
    rule_ids = ("recipe-key-closure",)
    description = (
        "every committed bench_matrix/recipes/*.json cell key must "
        "resolve through the tune/recipe.py RECIPE_KEYS table to a CLI "
        "option declared on BOTH CLIs — a recipe can never name a "
        "config field the trainers do not declare")

    def project_check(self, model: ProjectModel) -> Iterator[Finding]:
        from neuroimagedisttraining_tpu.analysis.project import (
            committed_recipes,
            recipe_keys_table,
        )
        recipe_mod = model.find("tune/recipe.py")
        if recipe_mod is None:
            return
        table = recipe_keys_table(model)
        if not table:
            yield Finding(
                recipe_mod.path, 1, "recipe-key-closure",
                "tune/recipe.py has no statically-parseable RECIPE_KEYS "
                "dict literal — the closure over committed recipes "
                "cannot be checked")
            return
        cli_options: dict[str, set[str]] = {}
        for suffix in ("/__main__.py", "distributed/run.py"):
            mod = model.find(suffix)
            if mod is not None:
                cli_options[suffix] = {
                    opt for f in argparse_flags(mod).values()
                    for opt in f.options}
        for key, (option, line) in sorted(table.items()):
            for suffix, options in sorted(cli_options.items()):
                if option not in options:
                    yield Finding(
                        recipe_mod.path, line, "recipe-key-closure",
                        f"RECIPE_KEYS maps {key!r} to {option} but the "
                        f"{suffix.lstrip('/')} CLI declares no such "
                        "option — a recipe setting it would apply to a "
                        "nonexistent knob")
        anchor = min(l for _, l in table.values())
        for fn, doc in sorted(committed_recipes(model).items()):
            if not isinstance(doc, dict):
                yield Finding(
                    recipe_mod.path, anchor, "recipe-key-closure",
                    f"committed bench_matrix/recipes/{fn} does not "
                    "parse as a JSON object — --recipe would die on it "
                    "at startup; regenerate (scripts/run_autotune.sh)")
                continue
            cell = doc.get("cell")
            if not isinstance(cell, dict):
                yield Finding(
                    recipe_mod.path, anchor, "recipe-key-closure",
                    f"committed bench_matrix/recipes/{fn} has no "
                    "'cell' object — not a recipe the loader accepts")
                continue
            for key in sorted(cell):
                if key not in table:
                    yield Finding(
                        recipe_mod.path, anchor, "recipe-key-closure",
                        f"committed bench_matrix/recipes/{fn} sets "
                        f"cell key {key!r} which RECIPE_KEYS does not "
                        "declare — the loader would reject the file at "
                        "startup")


@register
class ActionDisciplineRule(ProjectRule):
    rule_ids = ("action-unknown", "action-orphan")
    description = (
        "reflex-plane closure (ISSUE 20): every literal action name — "
        "a HealthRule action= binding, a JSON-manifest 'action' field, "
        "a bus register()/on_alert()/record_action() literal — must "
        "resolve in the obs/actions.py BUILTIN_ACTIONS registry; and "
        "every registered action must be reachable from some rule or "
        "dispatch site (or documented in ARCHITECTURE.md) — a reflex "
        "nothing can ever fire is dead policy")

    def project_check(self, model: ProjectModel) -> Iterator[Finding]:
        from neuroimagedisttraining_tpu.analysis.project import (
            action_uses,
            actions_table,
        )
        actions_mod = model.find("obs/actions.py")
        if actions_mod is None:
            return
        table = actions_table(model)
        if not table:
            yield Finding(
                actions_mod.path, 1, "action-unknown",
                "obs/actions.py has no statically-parseable "
                "BUILTIN_ACTIONS dict literal — the rule->action "
                "closure cannot be checked")
            return
        uses = action_uses(model)
        for rel, name, lineno, kind in uses:
            if rel.endswith("obs/actions.py"):
                continue  # the registry's own docstrings/dispatch glue
            if name not in table:
                yield Finding(
                    rel, lineno, "action-unknown",
                    f"{kind} site names reflex action {name!r} which "
                    "obs/actions.py BUILTIN_ACTIONS does not declare — "
                    "the dispatch would die (register) or log an "
                    "'unhandled' no-op forever (rule binding)")
        # manifest 'action' fields resolve too (the example manifest is
        # the one committed JSON surface binding rules to actions)
        import json as _json
        import os as _os
        mpath = _os.path.join(model.root, "scripts",
                              "health_rules.example.json")
        manifest_names: set[str] = set()
        if _os.path.exists(mpath):
            try:
                with open(mpath, encoding="utf-8") as fh:
                    rows = _json.load(fh)
            except (OSError, _json.JSONDecodeError):
                rows = []
            for i, row in enumerate(rows if isinstance(rows, list)
                                    else []):
                name = (row.get("action", "")
                        if isinstance(row, dict) else "")
                if name:
                    manifest_names.add(name)
                    if name not in table:
                        yield Finding(
                            actions_mod.path, min(table.values()),
                            "action-unknown",
                            f"scripts/health_rules.example.json rule "
                            f"#{i} binds action {name!r} which "
                            "BUILTIN_ACTIONS does not declare — "
                            "loading the manifest would fail at "
                            "startup validation")
        # orphans: registered but unreachable and undocumented
        reachable = {name for rel, name, _, kind in uses
                     if not rel.endswith("obs/actions.py")
                     and kind in ("rule", "dispatch")} | manifest_names
        doc_path = _os.path.join(model.root, "ARCHITECTURE.md")
        doc_text = ""
        if _os.path.exists(doc_path):
            try:
                with open(doc_path, encoding="utf-8") as fh:
                    doc_text = fh.read()
            except OSError:
                pass
        for name, lineno in sorted(table.items()):
            if name not in reachable and name not in doc_text:
                yield Finding(
                    actions_mod.path, lineno, "action-orphan",
                    f"BUILTIN_ACTIONS declares {name!r} but no rule "
                    "binds it, nothing dispatches it, and "
                    "ARCHITECTURE.md does not document it — a reflex "
                    "nothing can ever fire")


# ---------------------------------------------------------------------------
# family 3: compatibility matrix as data
# ---------------------------------------------------------------------------

@register
class CompatMatrixRule(ProjectRule):
    rule_ids = ("compat-matrix-drift", "compat-matrix-doc-stale")
    description = (
        "the committed analysis/compat_matrix.py must equal a fresh "
        "extraction of the tree's startup-rejection sites "
        "(compat-matrix-drift), and the ARCHITECTURE.md table between "
        "the nidt:compat-matrix markers must be regenerated from it, "
        "never hand-edited (compat-matrix-doc-stale); fix both with "
        "--regen-compat")

    def project_check(self, model: ProjectModel) -> Iterator[Finding]:
        extracted = rejection_rows(model, knob_vocabulary(model))
        committed = committed_matrix(model)
        matrix_mod = model.find("analysis/compat_matrix.py")
        matrix_path = (matrix_mod.path if matrix_mod
                       else f"{model.package}/analysis/compat_matrix.py")
        if committed is None and extracted:
            yield Finding(
                matrix_path, 1, "compat-matrix-drift",
                f"{len(extracted)} startup-rejection site(s) extracted "
                "but no committed compat matrix exists — run "
                "`python -m neuroimagedisttraining_tpu.analysis "
                "--regen-compat` and commit the artifact")
            return
        committed = committed or []
        key = lambda r: (r["where"], tuple(r["knobs"]), r["message"])
        committed_keys = {key(r) for r in committed}
        extracted_keys = {key(r) for r in extracted}
        for row in extracted:
            if key(row) not in committed_keys:
                yield Finding(
                    row["where"], row.get("_line", 1),
                    "compat-matrix-drift",
                    "startup-rejection site (knobs: "
                    + ", ".join(row["knobs"])
                    + ") is missing from the committed compat matrix — "
                    "run --regen-compat and commit "
                    "analysis/compat_matrix.py + the ARCHITECTURE.md twin")
        for row in committed:
            if key(row) not in extracted_keys:
                yield Finding(
                    matrix_path, 1, "compat-matrix-drift",
                    f"committed matrix row ({row['where']}, knobs: "
                    + ", ".join(row["knobs"])
                    + ") matches no rejection site in today's tree — "
                    "stale row; run --regen-compat")
        # the markdown twin must be byte-identical to a regeneration
        # from the COMMITTED artifact (hand edits are findings even when
        # the artifact itself is current)
        block, line = doc_matrix_block(model)
        expected = render_matrix_md(
            [dict(r, knobs=tuple(r["knobs"])) for r in committed])
        if block is None:
            if committed:
                yield Finding(
                    "ARCHITECTURE.md", 1, "compat-matrix-doc-stale",
                    "ARCHITECTURE.md has no nidt:compat-matrix marker "
                    f"block ({MD_BEGIN!r}) — run --regen-compat to embed "
                    "the generated table")
        elif block != expected:
            yield Finding(
                "ARCHITECTURE.md", line, "compat-matrix-doc-stale",
                "the compat-matrix table between the nidt:compat-matrix "
                "markers does not match a regeneration from the "
                "committed matrix — the twin is generated, never "
                "hand-edited; run --regen-compat")


# ---------------------------------------------------------------------------
# family 4: interprocedural donation / use-after-donate across modules
# ---------------------------------------------------------------------------

def _module_dotted(relpath: str) -> str:
    rel = relpath[:-3] if relpath.endswith(".py") else relpath
    if rel.endswith("/__init__"):
        rel = rel[: -len("/__init__")]
    return rel.replace("/", ".")


@register
class XModuleDonationRule(ProjectRule):
    rule_ids = ("donation-use-after-donate-xmodule",)
    description = (
        "cross-file upgrade of donation-use-after-donate: module-level "
        "functions that forward parameters into donated argument "
        "positions are summarized and propagated across imports; a "
        "caller in another module that rereads a buffer it passed into "
        "a summarized donated position is flagged")

    def project_check(self, model: ProjectModel) -> Iterator[Finding]:
        helper = DonationDisciplineRule()
        indexes: dict[str, _DefIndex] = {}
        fns: dict[str, dict[str, ast.FunctionDef]] = {}
        for rel, mod in model.modules.items():
            _annotate_parents(mod.tree)
            indexes[rel] = _DefIndex(mod.tree)
            table: dict[str, ast.FunctionDef] = {}
            for stmt in mod.tree.body:
                if isinstance(stmt, _FUNCS):
                    table[stmt.name] = stmt
            fns[rel] = table

        # summaries: dotted function path -> donated PARAM indices
        summaries: dict[str, tuple[int, ...]] = {}
        changed = True
        rounds = 0
        while changed and rounds <= len(model.modules) + 1:
            changed = False
            rounds += 1
            for rel, mod in model.modules.items():
                dotted_mod = _module_dotted(rel)
                for name, fn in fns[rel].items():
                    fpath = f"{dotted_mod}.{name}"
                    donated = self._donated_params(
                        mod, fn, indexes[rel], summaries)
                    if donated and summaries.get(fpath) != donated:
                        summaries[fpath] = donated
                        changed = True

        if not summaries:
            return
        for rel, mod in model.modules.items():
            for fn in (n for n in ast.walk(mod.tree)
                       if isinstance(n, _FUNCS)):
                for call in ast.walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    if helper._enclosing_fn(call) is not fn:
                        continue
                    target = self._resolve_xmodule(mod, call, summaries,
                                                   fns, rel)
                    if target is None:
                        continue
                    callee, indices = target
                    for f in helper._reads_after(mod, fn, call, indices,
                                                 callee):
                        yield dataclasses.replace(
                            f, rule="donation-use-after-donate-xmodule")

    @staticmethod
    def _donated_params(mod: ModuleInfo, fn: ast.FunctionDef,
                        index: _DefIndex,
                        summaries: dict[str, tuple[int, ...]]
                        ) -> tuple[int, ...]:
        """Parameter positions of ``fn`` whose (bare-Name) values flow
        into a donated argument position of a donating call in its
        body — directly (via the per-file resolver) or through an
        already-summarized import."""
        helper = DonationDisciplineRule()
        params = [a.arg for a in fn.args.args]
        out: set[int] = set()
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            donated = helper._donating_call(call, index, mod.aliases)
            indices: tuple[int, ...] = ()
            if donated:
                indices = donated[0]
            else:
                canon = normalize(dotted_name(call.func), mod.aliases)
                if canon in summaries:
                    indices = summaries[canon]
            for i in indices:
                if i < len(call.args) and isinstance(call.args[i], ast.Name):
                    if call.args[i].id in params:
                        out.add(params.index(call.args[i].id))
        return tuple(sorted(out))

    @staticmethod
    def _resolve_xmodule(mod: ModuleInfo, call: ast.Call,
                         summaries: dict[str, tuple[int, ...]],
                         fns: dict[str, dict[str, ast.FunctionDef]],
                         rel: str) -> tuple[str, tuple[int, ...]] | None:
        """(callee label, donated indices) when ``call`` resolves through
        the import aliases to a summarized function defined in a
        DIFFERENT module (same-module reads are the per-file rule's
        job)."""
        canon = normalize(dotted_name(call.func), mod.aliases)
        if canon is None or canon not in summaries:
            return None
        mod_dotted, _, fname = canon.rpartition(".")
        if mod_dotted == _module_dotted(rel):
            return None
        if fname in fns.get(rel, {}):
            # the local def shadows; not a cross-module dispatch
            return None
        return canon, summaries[canon]
