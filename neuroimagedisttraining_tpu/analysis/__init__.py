"""``nidtlint`` — AST-based invariant checker for this package.

The training stack keeps three kinds of invariants that ordinary linters
cannot see: jitted round programs must stay trace-safe (no host syncs, no
Python RNG), every engine must keep the ``FederatedEngine`` round
contract, and the ``distributed/`` transports must honor the broker's
write-lock protocol. ``nidtlint`` turns those from comments into
machine-checked rules, run as a tier-1 gate (tests/test_analysis.py) and
via ``scripts/run_static_checks.sh``.

A second, whole-program pass (``--project``) checks the cross-file
contracts the per-file rules cannot see — flag<->config lockstep,
metric-name closure, the compatibility matrix as data, and
interprocedural donation (analysis/project.py + analysis/contracts.py).

CLI::

    python -m neuroimagedisttraining_tpu.analysis <paths> [--json]
    python -m neuroimagedisttraining_tpu.analysis --project [--json]
    python -m neuroimagedisttraining_tpu.analysis --regen-compat
    python -m neuroimagedisttraining_tpu.analysis --list-rules

Suppression: ``# nidt: allow[rule-id] -- one-line justification`` on the
offending line; the justification is mandatory (rule ``pragma``).
"""

from neuroimagedisttraining_tpu.analysis.core import (  # noqa: F401
    Finding,
    RULE_REGISTRY,
    Rule,
    all_rule_ids,
    lint_paths,
    lint_source,
    register,
)

# importing the rule modules registers every rule family
from neuroimagedisttraining_tpu.analysis import (  # noqa: E402,F401
    async_discipline,
    contracts,
    determinism,
    donation,
    engine_contract,
    health_discipline,
    lock_discipline,
    mesh_discipline,
    obs_discipline,
    precision_discipline,
    privacy_discipline,
    round_program,
    shm_discipline,
    trace_safety,
)

from neuroimagedisttraining_tpu.analysis.project import (  # noqa: E402,F401
    lint_project,
)

__all__ = [
    "Finding",
    "Rule",
    "RULE_REGISTRY",
    "all_rule_ids",
    "lint_paths",
    "lint_project",
    "lint_source",
    "register",
]
