"""Bench regression gate (ISSUE 13): diff fresh bench cells against the
committed ``bench_matrix/`` artifacts with per-cell thresholds.

ROADMAP item 2's "regenerated BENCH_MATRIX" session needs to trust its
own numbers: every committed artifact carries wall-clock cells measured
on a shared, drifty box, and until now the only way to know whether a
fresh run regressed was reading JSON by eye. This gate makes the
comparison mechanical and the verdict machine-readable:

- ``SPECS`` names, per artifact, the cells that matter and HOW each is
  judged — structural booleans exactly (``true``), wall-clock numbers
  as loose ratios vs the committed value (``ratio_min``/``ratio_max``,
  tolerances sized for this box's documented 2x run-to-run drift:
  regression tripwires, not noise detectors), and absolute contracts
  (``abs_max``, e.g. the obs-overhead <= 2% acceptance).
- missing FRESH artifacts are SKIPPED, not red (a session regenerates
  the cells it touched, not the whole matrix); ``--strict`` upgrades
  skips to failures for full-matrix regeneration sessions.
- the verdict is one JSON object (``--json`` to also write it) and the
  exit code follows the nidtlint convention: 0 green, 1 red, 2 usage
  error.

Entry points::

    python -m neuroimagedisttraining_tpu.analysis.bench_gate \
        --fresh /tmp/fresh_bench [--committed bench_matrix]

    scripts/bench_diff.py --produce ingest   # regenerate a quick
        # ingest cell into a fresh dir, then gate it

With no ``--fresh`` the gate self-diffs the committed directory — every
ratio is exactly 1.0, which verifies the spec paths still match the
artifacts (the schema-drift canary) without claiming fresh evidence.

Dependency-free (stdlib json only), like the rest of ``analysis/``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Any

__all__ = ["Check", "SPECS", "extract", "gate", "main"]


@dataclasses.dataclass(frozen=True)
class Check:
    """One gated cell: a dotted ``path`` into the artifact JSON and the
    judgment ``kind``:

    - ``true``      — fresh value must be truthy (committed ignored)
    - ``ratio_min`` — fresh / committed >= threshold (higher-better)
    - ``ratio_max`` — fresh / committed <= threshold (lower-better)
    - ``abs_max``   — fresh <= threshold (absolute contract)
    - ``eq``        — fresh == committed exactly (deterministic cells)
    """

    path: str
    kind: str
    threshold: float | None = None
    note: str = ""


#: per-artifact cell specs. Ratio thresholds are deliberately loose
#: (0.5 / 2.0): the box's wall numbers drift ~2x run to run (documented
#: in the artifacts' own notes), so the gate trips on order-of-change
#: regressions — a broken fast path, a serialized fleet — not on load.
SPECS: dict[str, tuple[Check, ...]] = {
    "ingest_bench.json": (
        Check("summary.audits_green", "true",
              note="cross-process accounting audits"),
        Check("async.uploads_per_s_sustained", "ratio_min", 0.5,
              "single-process selector baseline"),
        Check("ingest_w1.uploads_per_s_sustained", "ratio_min", 0.5,
              "sharded plane, 1 worker"),
        Check("ingest_w2.uploads_per_s_sustained", "ratio_min", 0.5,
              "sharded plane, 2 workers (the knee on this box)"),
        Check("ingest_w4.uploads_per_s_sustained", "ratio_min", 0.5,
              "sharded plane, 4 workers (headline cell)"),
    ),
    "async_bench.json": (
        Check("async.frames_reconciled", "true",
              note="zero-lost/zero-double-counted accounting"),
        Check("async.uploads_per_s", "ratio_min", 0.5,
              "buffered-server sustained throughput"),
        Check("summary.p99_advance_ratio", "ratio_min", 0.5,
              "sync-vs-async p99 version-advance advantage"),
    ),
    "obs_overhead.json": (
        Check("overhead_frac", "abs_max", 0.02,
              "armed-vs-disarmed telemetry overhead acceptance"),
    ),
    "wire_bench.json": (
        Check("masked_sparse_quant.pass", "true"),
        Check("masked_sparse_quant.bytes_reduction_x", "ratio_min", 0.5,
              "masked sparse+quant wire reduction"),
        Check("fedavg_delta_quant.pass", "true"),
        Check("fedavg_delta_quant.bytes_reduction_x", "ratio_min", 0.5,
              "delta+quant wire reduction"),
    ),
    "secure_bench.json": (
        Check("cells.secure_quant.bytes_recv", "ratio_max", 1.5,
              "secure-quant server-received bytes (deterministic frame "
              "sizes; 1.5x headroom for protocol chatter)"),
        Check("cells.secure_dense.bytes_recv", "ratio_max", 1.5),
    ),
    "byz_bench.json": (
        Check("pass", "true", note="defense-recovery acceptance"),
        Check("cells.clean.mean_auc", "ratio_min", 0.8,
              "clean-run AUC (seeded, should be near-deterministic)"),
    ),
    "round_program.json": (
        Check("engines.fedavg.dispatch_reduction", "eq",
              note="dispatch counts are deterministic compile facts"),
        Check("engines.ditto.dispatch_reduction", "eq"),
        Check("engines.dpsgd.dispatch_reduction", "eq"),
        Check("engines.subavg.dispatch_reduction", "eq"),
    ),
    "cohort_sharding.json": (
        Check("slope_s_per_client.sharded_over_sequential", "ratio_max",
              2.0, "sharded-vs-sequential per-client slope"),
    ),
    "precision_bench.json": (
        Check("parity.fp32_fused_bitwise_equals_fp32", "true"),
        Check("parity.bf16_fused_bitwise_equals_bf16", "true"),
        Check("parity.bf16_vs_fp32_loss_abs_delta", "abs_max", 2e-3,
              "bf16 loss tolerance pin"),
    ),
    # profile session (ISSUE 14, obs/probe.py): structural cells exact —
    # the probe manifest fingerprint, the deterministic dispatch/compile
    # counts, the live-scrape booleans — and every wall/TFLOPs cell at
    # the standard drift-tolerant ratio tripwires. The XLA-vs-analytic
    # FLOPs reconciliation is deterministic on a fixed backend, so its
    # ratio band is tight (same-box schema canary, not a wall cell).
    # The eq cells are deterministic AT THE COMMITTED CONFIG (counts
    # follow PROFILE_ROUNDS, the fingerprint follows devices/manifest):
    # a config-changing regeneration — the flagship TPU recipe replacing
    # the CPU smoke baseline — legitimately differs, and
    # scripts/run_profile_session.sh detects the meta mismatch and
    # treats the verdict as informational while a SAME-config red
    # blocks the install (the round_program.json eq cells carry the
    # same config-pinned contract).
    # training-health exemplar (ISSUE 15, scripts/run_health_report.sh):
    # the seeded sign-flip divergence run vs its clean twin through the
    # shipped CLI + analysis/run_report.py. Every cell is a
    # deterministic verdict fact at the committed config (seeded tiny
    # run, rule edges are debounced booleans), so the checks are exact
    # — a regeneration that stops firing the divergence rule, or starts
    # firing on the clean twin, is a broken health plane, not drift.
    "health_report.json": (
        Check("contrast.timelines_differ", "true",
              note="byz vs clean alert timelines visibly differ "
                   "(the acceptance criterion verbatim)"),
        Check("clean.summary.schema_ok", "true"),
        Check("byz.summary.schema_ok", "true"),
        Check("contrast.clean_worst", "eq",
              note="clean twin stays ok for the whole run"),
        Check("contrast.byz_worst", "eq",
              note="sign-flip run's worst status (critical)"),
        Check("contrast.clean_alerts", "eq"),
        Check("contrast.byz_alerts", "eq",
              note="alert count at the committed seed/config"),
        Check("byz.summary.rounds", "eq",
              note="metrics JSONL rounds joined (the round/seq keys)"),
    ),
    # serving plane (ISSUE 17, scripts/run_serve_bench.sh): the
    # loadgen serve fleet (1k open-loop clients) against a 2-worker
    # SO_REUSEPORT serving cell on a real ditto bundle. Structural
    # cells exact — the shutdown accounting, the one-program-per-
    # (model, bucket) compile pin, the per-site routing distinctness —
    # and the wall cells (requests/s, client p99) at the standard
    # drift-tolerant ratio tripwires.
    "serve_bench.json": (
        Check("summary.audits_green", "true",
              note="client-side exactness + root/bye verdict "
                   "reconciliation (zero unaccounted requests)"),
        Check("serve.compile_pin_ok", "true",
              note="ONE compiled program per (model, bucket); zero "
                   "recompiles (the tripwire counter)"),
        Check("serve.routing.distinct_site_models", "true",
              note="two sites observed two DIFFERENT personalized "
                   "bundle digests"),
        Check("serve.merged_metrics.has_serve_latency", "true",
              note="merged scrape carries nidt_serve_latency_ms "
                   "samples"),
        Check("serve.merged_metrics.has_rtt_samples", "true",
              note="client-observed nidt_client_rtt_ms published "
                   "through the shared fleet path"),
        Check("serve.serve_workers", "eq",
              note="the committed cell is the 2-worker config"),
        Check("serve.requests_per_s", "ratio_min", 0.5,
              "client-confirmed serving throughput"),
        Check("serve.rtt_ms_p99", "ratio_max", 2.0,
              "client-observed p99 RTT tripwire (box drift "
              "tolerated)"),
    ),
    # hierarchical aggregation tier (ISSUE 18,
    # scripts/run_region_bench.sh): a 2-region x 2-worker tree under the
    # committed ingest_bench load (1k clients) plus the downlink
    # delta-sync A/B (same fleet, delta on vs off). Structural cells
    # exact — the audits, the shm-beats-pipe A/B, the tree-vs-committed-
    # single-root floor, the >=3x delta-bytes pin (all computed as
    # booleans by the bench itself so the gate re-judges fresh runs,
    # not just the committed one) — and the absolute throughput cell at
    # the standard drift-tolerant ratio tripwire.
    "region_bench.json": (
        Check("summary.audits_green", "true",
              note="every cell's received/accepted accounting exact + "
                   "frames reconciled through the region tier"),
        Check("summary.tree_at_least_committed_single_root", "true",
              note="the 2x2 tree sustains >= the committed single-root "
                   "best (ingest_bench ingest_w*)"),
        Check("summary.shm_beats_pipe", "true",
              note="shared-memory partial hand-off beats the pickled "
                   "pipe on mean per-export latency"),
        Check("summary.delta_sync_3x", "true",
              note=">=3x fewer bytes per changed-version sync reply "
                   "(delta vs dense, decoded bitwise-equal)"),
        Check("summary.delta_errors", "abs_max", 0,
              "zero base-mismatch delta replies ever shipped"),
        Check("summary.regions", "eq",
              note="the committed cell is the 2-region tree"),
        Check("summary.workers_per_region", "eq"),
        Check("summary.tree_uploads_per_s_sustained", "ratio_min", 0.5,
              "tree sustained throughput tripwire (box drift "
              "tolerated)"),
        Check("summary.delta_sync_bytes_ratio", "ratio_min", 0.5,
              "dense/delta sync-bytes ratio (codec regression "
              "tripwire)"),
    ),
    "profile_session.json": (
        Check("session.structural_fingerprint", "eq",
              note="the declared probe manifest (structural cells)"),
        Check("session.probes_completed", "eq",
              note="every declared probe ran (skips are structural)"),
        Check("session.metrics_scrape_ok", "true",
              note="live /metrics served nidt_dispatch_ms + "
                   "nidt_mfu/nidt_sustained_tflops samples"),
        Check("session.healthz_compute_ok", "true",
              note="/healthz compute block (dispatch liveness)"),
        Check("probes.fused_dispatch_k4.dispatches", "eq",
              note="dispatch counts are deterministic compile facts"),
        Check("probes.fused_dispatch_k4.compiles", "eq"),
        Check("probes.fp32_baseline.compiles", "eq"),
        Check("probes.fp32_baseline.round_ms", "ratio_max", 2.0,
              "per-round wall tripwire (box drift tolerated)"),
        Check("probes.bf16.round_ms", "ratio_max", 2.0),
        Check("probes.fp32_baseline.sustained_tflops", "ratio_min", 0.5,
              "sustained analytic TFLOP/s over the last boundary "
              "window (the MFU numerator)"),
        # the MFU ratio cells are ACTIVE but judge only when the
        # committed side carries a number: mfu is null off-chip (no
        # device peak), the committed cell is the CPU baseline, and a
        # null committed value SKIPS a ratio check (the self-diff
        # canary in tests/test_bench_gate.py pins exactly this — only
        # .mfu cells may skip). The first TPU-session regeneration
        # flips them to judging with zero spec edits.
        Check("probes.fp32_baseline.mfu", "ratio_min", 0.5,
              "model FLOPs utilization (judged once the committed "
              "artifact was measured where the device peak is known)"),
        Check("probes.bf16.mfu", "ratio_min", 0.5),
        Check("xla.train_step.parity_ratio", "ratio_min", 0.9,
              "XLA cost_analysis vs analytic ops/flops.py FLOPs — "
              "deterministic on a fixed backend"),
        Check("xla.train_step.parity_ratio", "ratio_max", 1.1),
    ),
    # autotuner session (ISSUE 19, scripts/run_autotune.sh): the seeded
    # successive-halving search over the declared space through the
    # virtual backend, plus one REAL-driver run of the winner. Every
    # cell is a deterministic search fact at the committed seed/space —
    # the byte-determinism self-check, the winner identity, the space
    # census — so the checks are exact; a regeneration that changes the
    # winner changed the space/seed/cost model, not the weather.
    "autotune_session.json": (
        Check("session.deterministic", "true",
              note="same seed + space reproduced the same recipe "
                   "BYTES twice (in-memory rerun self-check)"),
        Check("winner.fingerprint", "eq",
              note="winner identity at the committed seed/space"),
        Check("winner.score", "eq",
              note="committed-window score (virtual backend: seeded, "
                   "exact)"),
        Check("space.fingerprint", "eq",
              note="the declared space (axes + device context + "
                   "pinned knobs)"),
        Check("space.n_cells", "eq",
              note="valid-cell census after the validity predicates"),
        Check("winner_validation.ran", "true",
              note="the winner ran once through the REAL probe "
                   "driver after emission"),
        Check("winner_validation.status", "eq",
              note="and survived it (committed cell says 'ok')"),
    ),
    # the committed per-hardware recipe itself (tune/recipe.py): the
    # artifact --recipe auto loads on this box. Identity cells exact —
    # the sha256 self-pin covers every other byte.
    "recipes/cpu.json": (
        Check("device_kind", "eq",
              note="the recipe file matches its directory slot"),
        Check("fingerprint", "eq",
              note="winning-cell identity"),
        Check("score", "eq"),
        Check("space_fingerprint", "eq"),
        Check("sha256", "eq",
              note="the self-pin: any other drift shows here"),
    ),
}

#: default committed-artifact directory (repo-relative)
DEFAULT_COMMITTED = "bench_matrix"


def extract(doc: Any, dotted: str) -> Any:
    """Walk ``a.b.c`` through nested dicts; None when any hop is
    missing (missing != zero — the caller distinguishes skip from
    fail)."""
    cur = doc
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _judge(check: Check, fresh: Any, committed: Any) -> tuple[bool, str]:
    """(ok, detail) for one cell; raises nothing — malformed values
    read as failures with the reason in ``detail``."""
    k = check.kind
    if k == "true":
        return bool(fresh), f"fresh={fresh!r}"
    if k == "abs_max":
        try:
            ok = float(fresh) <= float(check.threshold)
        except (TypeError, ValueError):
            return False, f"non-numeric fresh value {fresh!r}"
        return ok, f"fresh={fresh} <= {check.threshold}"
    if k == "eq":
        return fresh == committed, f"fresh={fresh!r} vs {committed!r}"
    # ratio kinds need both numbers
    try:
        f, c = float(fresh), float(committed)
    except (TypeError, ValueError):
        return False, (f"non-numeric value (fresh={fresh!r}, "
                       f"committed={committed!r})")
    if c == 0:
        return False, "committed value is 0 — ratio undefined"
    ratio = f / c
    if k == "ratio_min":
        return ratio >= float(check.threshold), (
            f"fresh/committed={ratio:.3f} >= {check.threshold}")
    if k == "ratio_max":
        return ratio <= float(check.threshold), (
            f"fresh/committed={ratio:.3f} <= {check.threshold}")
    return False, f"unknown check kind {k!r}"


def gate(fresh_dir: str | None, committed_dir: str = DEFAULT_COMMITTED,
         artifacts: list[str] | None = None,
         strict: bool = False) -> dict:
    """Run the gate; returns the machine-readable verdict document.

    ``fresh_dir=None`` self-diffs the committed artifacts (spec-path
    canary). ``artifacts`` filters to the named files. ``strict``
    turns missing fresh artifacts/paths into failures."""
    self_diff = fresh_dir is None
    fdir = committed_dir if self_diff else fresh_dir
    wanted = set(artifacts) if artifacts else None
    unknown = (wanted or set()) - set(SPECS)
    if unknown:
        raise ValueError(
            f"unknown artifacts {sorted(unknown)}; gated artifacts are "
            f"{sorted(SPECS)}")
    cells: list[dict] = []
    skipped: list[dict] = []

    def _load(path: str):
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    for name in sorted(SPECS):
        if wanted is not None and name not in wanted:
            continue
        fresh_doc = _load(os.path.join(fdir, name))
        committed_doc = _load(os.path.join(committed_dir, name))
        if fresh_doc is None:
            skipped.append({"artifact": name,
                            "reason": "no fresh artifact"})
            continue
        if committed_doc is None:
            skipped.append({"artifact": name,
                            "reason": "no committed artifact"})
            continue
        for check in SPECS[name]:
            fv = extract(fresh_doc, check.path)
            cv = extract(committed_doc, check.path)
            row = {"artifact": name, "path": check.path,
                   "kind": check.kind, "threshold": check.threshold,
                   "fresh": fv, "committed": cv, "note": check.note}
            if fv is None:
                # a quick session regenerates SOME cells — absent ones
                # skip (e.g. a fresh ingest_bench with only the w2 cell)
                skipped.append({**row, "reason": "path missing in "
                                                 "fresh artifact"})
                continue
            if cv is None and check.kind in ("ratio_min", "ratio_max",
                                             "eq"):
                skipped.append({**row, "reason": "path missing in "
                                                 "committed artifact"})
                continue
            ok, detail = _judge(check, fv, cv)
            cells.append({**row, "ok": ok, "detail": detail})
    red = [c for c in cells if not c["ok"]]
    if strict and skipped:
        red = red + [{"ok": False, **s} for s in skipped]
    verdict = ("red" if red else ("green" if cells else "empty"))
    return {
        "verdict": verdict,
        "self_diff": self_diff,
        "fresh_dir": fdir,
        "committed_dir": committed_dir,
        "checked": len(cells),
        "failed": len(red),
        "skipped": len(skipped),
        "cells": cells,
        "skips": skipped,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m neuroimagedisttraining_tpu.analysis.bench_gate",
        description=__doc__.split("\n\n")[0])
    ap.add_argument("--fresh", type=str, default=None,
                    help="directory of freshly produced bench_matrix "
                         "artifacts; omitted = self-diff the committed "
                         "dir (spec-path canary, trivially green)")
    ap.add_argument("--committed", type=str, default=DEFAULT_COMMITTED,
                    help="committed artifact directory (default "
                         "bench_matrix/)")
    ap.add_argument("--artifact", action="append", default=None,
                    help="gate only this artifact file name "
                         "(repeatable); default: every spec'd artifact")
    ap.add_argument("--strict", action="store_true",
                    help="missing fresh artifacts/paths fail instead "
                         "of skipping (full-matrix regeneration runs)")
    ap.add_argument("--json", type=str, default="",
                    help="also write the verdict document here")
    ap.add_argument("--quiet", action="store_true",
                    help="print only the one-line verdict summary, not "
                         "the full document")
    try:
        args = ap.parse_args(argv)
        res = gate(args.fresh, committed_dir=args.committed,
                   artifacts=args.artifact, strict=args.strict)
    except ValueError as e:
        print(f"bench_gate: {e}", file=sys.stderr)
        return 2
    if args.json:
        d = os.path.dirname(args.json)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1, sort_keys=True)
    if args.quiet:
        print(json.dumps({k: res[k] for k in
                          ("verdict", "checked", "failed", "skipped",
                           "self_diff")}))
    else:
        print(json.dumps(res, indent=1, default=str))
    return 0 if res["verdict"] != "red" else 1


if __name__ == "__main__":
    sys.exit(main())
