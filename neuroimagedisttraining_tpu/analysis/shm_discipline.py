"""Shared-memory lifecycle rules for the partial hand-off slabs.

POSIX shared memory outlives the process that maps it: a segment is a
named kernel object that dies only when someone calls ``unlink()`` (and
every mapping is ``close()``d). The hierarchical ingest tier
(asyncfl/ingest.py ``_ShmSlabWriter``/``_ShmSlabReader``, ISSUE 18)
splits the lifecycle across processes — the worker OWNS its slabs, the
parent only ATTACHES — so the teardown rules are asymmetric and a mixed-
up call site leaks segments under ``/dev/shm`` run after run, or worse,
yanks a segment out from under a peer that still maps it:

- ``shm-owner-teardown`` — a class that creates a segment
  (``SharedMemory(..., create=True)``) must, somewhere in the class,
  call BOTH ``.close()`` (drop its own mapping) and ``.unlink()``
  (destroy the name). Missing unlink leaks the segment past process
  exit; missing close leaks the mapping (and trips BufferError on
  interpreter teardown when numpy views are still live).
- ``shm-attach-unlink`` — a class that only attaches
  (``SharedMemory(name)`` without ``create=True``) must NEVER call
  ``.unlink()``: destroying a name the attacher does not own races the
  owner's own teardown and invalidates the discipline that exactly one
  process is responsible for the segment's lifetime.

The rule is lexical and CLASS-scoped (module-level functions form their
own scope): presence of the teardown calls anywhere in the owning class
satisfies it — whether they actually run on every path is the runtime
tests' job (tests/test_region.py drives real slabs through writer and
reader teardown), not an AST question.
"""

from __future__ import annotations

import ast
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    normalize,
    register,
)


def _is_shared_memory_ctor(call: ast.Call, aliases: dict) -> bool:
    name = normalize(dotted_name(call.func), aliases)
    return name is not None and (
        name == "SharedMemory"
        or name.endswith("shared_memory.SharedMemory"))


def _is_create(call: ast.Call) -> bool:
    """``SharedMemory(..., create=True)`` — keyword or the second
    positional argument (``SharedMemory(name, True, size)``)."""
    for kwarg in call.keywords:
        if kwarg.arg == "create":
            return isinstance(kwarg.value, ast.Constant) \
                and bool(kwarg.value.value)
    if len(call.args) >= 2:
        return isinstance(call.args[1], ast.Constant) \
            and bool(call.args[1].value)
    return False


class _ScopeUse:
    """What one class (or module-level function) does with shm."""

    def __init__(self) -> None:
        self.creates: list[ast.Call] = []
        self.attaches: list[ast.Call] = []
        self.closes = False
        self.unlinks: list[ast.Call] = []


def _scan_scope(scope: ast.AST, aliases: dict) -> _ScopeUse:
    use = _ScopeUse()
    for node in ast.walk(scope):
        if not isinstance(node, ast.Call):
            continue
        if _is_shared_memory_ctor(node, aliases):
            (use.creates if _is_create(node)
             else use.attaches).append(node)
        elif isinstance(node.func, ast.Attribute):
            if node.func.attr == "close":
                use.closes = True
            elif node.func.attr == "unlink":
                use.unlinks.append(node)
    return use


@register
class ShmDisciplineRule(Rule):
    rule_ids = ("shm-owner-teardown", "shm-attach-unlink")
    description = ("a class creating SharedMemory(create=True) must "
                   "call both .close() and .unlink(); an attach-only "
                   "class must never .unlink() a segment it does not "
                   "own")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for scope in self._scopes(mod.tree):
            use = _scan_scope(scope, mod.aliases)
            if use.creates:
                has_unlink = bool(use.unlinks)
                for call in use.creates:
                    if not use.closes:
                        yield Finding(
                            mod.path, call.lineno, "shm-owner-teardown",
                            f"{self._label(scope)} creates a shared-"
                            "memory segment but never calls .close() — "
                            "the owner must drop its own mapping "
                            "before unlinking")
                    if not has_unlink:
                        yield Finding(
                            mod.path, call.lineno, "shm-owner-teardown",
                            f"{self._label(scope)} creates a shared-"
                            "memory segment but never calls .unlink() "
                            "— the name (and its backing pages) leaks "
                            "past process exit")
            elif use.attaches:
                for call in use.unlinks:
                    yield Finding(
                        mod.path, call.lineno, "shm-attach-unlink",
                        f"{self._label(scope)} only ATTACHES shared-"
                        "memory segments yet calls .unlink() — "
                        "destroying a name it does not own races the "
                        "owner's teardown (attach side must only "
                        ".close() its mapping)")

    @staticmethod
    def _scopes(tree: ast.Module) -> Iterator[ast.AST]:
        """Class bodies, plus module-level functions NOT inside a class
        (a method's shm use belongs to its class's lifecycle)."""
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                yield node
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                yield node

    @staticmethod
    def _label(scope: ast.AST) -> str:
        kind = ("class" if isinstance(scope, ast.ClassDef)
                else "function")
        return f"{kind} {getattr(scope, 'name', '?')!r}"
