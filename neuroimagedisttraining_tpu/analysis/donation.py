"""Donation-discipline rules: round programs donate, callers never reread.

Buffer donation (``jax.jit(..., donate_argnums=...)``) is the round
driver's HBM contract (ISSUE 4): every round/consensus program consumes
its state pytrees in place, and the runtime DELETES donated buffers at
dispatch — a later host-side read raises ``Array has been deleted`` at
best, and at worst only on hardware where donation is implemented. Two
lexical rules keep the tree honest:

- ``donation-missing`` — a ``jax.jit`` of a function whose name matches
  ``*round*``/``*consensus*`` (the repo's round-program naming
  convention: ``round_fn``, ``_round_body``, ``fused_round_fn``,
  ``_consensus``) must pass a ``donate_argnums`` keyword. Declaring
  ``donate_argnums=self._donate_argnums(...)`` counts (the engine-level
  gate); programs that legitimately cannot donate take a pragma.
- ``donation-use-after-donate`` — inside one function body, a variable
  passed in a donated argument position of a known-donating call must
  not be read on any later line until it is rebound. Donating callables
  are resolved lexically: direct ``jax.jit(..., donate_argnums=...)``
  results (assigned or returned), ``self.<prop>`` cached properties and
  ``self.<factory>(...)`` plan caches whose bodies build such a jit, and
  module-level defs decorated ``@partial(jax.jit, donate_argnums=...)``.

Both rules are intentionally lexical/straight-line (same limits as the
trace-safety family): a rebinding on the same statement as the dispatch
(``params, ... = self._round_jit(params, ...)``) is the blessed driver
shape, and reads reachable only through loop back-edges are out of
scope — the tier-1 engine tests execute those paths for real.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    normalize,
    register,
)
from neuroimagedisttraining_tpu.analysis.trace_safety import (
    _ancestors,
    _annotate_parents,
    _DefIndex,
)

#: round-program naming convention (ISSUE 4): jits of these must donate
_ROUND_NAME_RE = re.compile(r"round|consensus")
_PARTIAL = "functools.partial"
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _target_name(node: ast.AST) -> str | None:
    """Best-effort name of a jit target: ``round_fn``, ``self._round_body``
    -> ``_round_body``, lambdas -> None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _unwrap_partial_call(node: ast.AST, aliases: dict) -> ast.AST:
    if (isinstance(node, ast.Call)
            and normalize(dotted_name(node.func), aliases) == _PARTIAL
            and node.args):
        return _unwrap_partial_call(node.args[0], aliases)
    return node


def _donate_kwarg(call: ast.Call) -> ast.keyword | None:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return kw
    return None


def _donated_indices(kw: ast.keyword) -> tuple[int, ...]:
    """Integer argument positions named by a ``donate_argnums`` value:
    a literal int/tuple, or the int literals of a gating call like
    ``self._donate_argnums(0, 1, 6)``. Unknown shapes yield () — the
    declaration still satisfies ``donation-missing``."""
    v = kw.value
    if isinstance(v, ast.Constant) and isinstance(v.value, int):
        return (v.value,)
    if isinstance(v, (ast.Tuple, ast.List)):
        out = []
        for el in v.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
        return tuple(out)
    if isinstance(v, ast.Call):
        return tuple(a.value for a in v.args
                     if isinstance(a, ast.Constant)
                     and isinstance(a.value, int))
    return ()


def _jit_calls(root: ast.AST, aliases: dict) -> Iterator[ast.Call]:
    """Every ``jax.jit(...)`` call lexically inside ``root`` (including
    through ``functools.partial(jax.jit, ...)``)."""
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        name = normalize(dotted_name(node.func), aliases)
        if name == "jax.jit":
            yield node
        elif name == _PARTIAL and node.args and \
                normalize(dotted_name(node.args[0]), aliases) == "jax.jit":
            yield node


def _method_donation(index: _DefIndex, at: ast.AST, name: str,
                     aliases: dict) -> tuple[int, ...] | None:
    """Donated indices when ``self.<name>`` / local def ``name`` builds a
    ``jax.jit(..., donate_argnums=...)`` anywhere in its body (covers
    cached properties, ``_plan_cached`` build closures, and jit-factory
    methods); None when it builds none."""
    target = index.resolve_method(at, name) or index.resolve_name(at, name)
    if target is None:
        return None
    found: tuple[int, ...] | None = None
    for call in _jit_calls(target, aliases):
        kw = _donate_kwarg(call)
        if kw is not None:
            found = tuple(sorted(set((found or ()) + _donated_indices(kw))))
    return found


@register
class DonationDisciplineRule(Rule):
    rule_ids = ("donation-missing", "donation-use-after-donate")
    description = (
        "jitted *round*/*consensus* programs must declare donate_argnums "
        "(donation-missing), and a variable passed in a donated argument "
        "position must not be read again before rebinding "
        "(donation-use-after-donate)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        _annotate_parents(mod.tree)
        index = _DefIndex(mod.tree)
        yield from self._check_missing(mod, index)
        yield from self._check_use_after(mod, index)

    # ---------- donation-missing ----------

    def _check_missing(self, mod: ModuleInfo,
                       index: _DefIndex) -> Iterator[Finding]:
        aliases = mod.aliases
        for call in _jit_calls(mod.tree, aliases):
            # partial(jax.jit, ...) decorators: the target is the def
            if normalize(dotted_name(call.func), aliases) == _PARTIAL:
                parent = getattr(call, "_nidt_parent", None)
                tname = (parent.name if isinstance(parent, _FUNCS)
                         and call in parent.decorator_list else None)
            else:
                if not call.args:
                    continue
                tname = _target_name(
                    _unwrap_partial_call(call.args[0], aliases))
            if tname is None or not _ROUND_NAME_RE.search(tname):
                continue
            if _donate_kwarg(call) is None:
                yield Finding(
                    mod.path, call.lineno, "donation-missing",
                    f"jax.jit of round program {tname!r} declares no "
                    "donate_argnums — the round's consumed state pytrees "
                    "double-buffer across the dispatch (declare "
                    "donate_argnums, e.g. via self._donate_argnums(...), "
                    "or pragma-justify why this program cannot donate)")

    # ---------- donation-use-after-donate ----------

    def _check_use_after(self, mod: ModuleInfo,
                         index: _DefIndex) -> Iterator[Finding]:
        aliases = mod.aliases
        for fn in (n for n in ast.walk(mod.tree) if isinstance(n, _FUNCS)):
            # only direct statements of THIS function (nested defs are
            # visited on their own)
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                if self._enclosing_fn(call) is not fn:
                    continue
                donated = self._donating_call(call, index, aliases)
                if not donated:
                    continue
                indices, callee = donated
                yield from self._reads_after(mod, fn, call, indices, callee)

    @staticmethod
    def _enclosing_fn(node: ast.AST) -> ast.AST | None:
        for anc in _ancestors(node):
            if isinstance(anc, _FUNCS + (ast.Lambda,)):
                return anc
        return None

    def _donating_call(self, call: ast.Call, index: _DefIndex,
                       aliases: dict) -> tuple[tuple[int, ...], str] | None:
        """(donated indices, callee label) when ``call`` dispatches a
        known-donating jitted callable."""
        func = call.func
        # direct: jax.jit(f, donate_argnums=...)(args)
        if isinstance(func, ast.Call):
            name = normalize(dotted_name(func.func), aliases)
            if name == "jax.jit":
                kw = _donate_kwarg(func)
                if kw is not None:
                    idx = _donated_indices(kw)
                    return (idx, "jax.jit(...)") if idx else None
            # factory: self._round_jit_for(plan)(args) /
            # self._fused_round_jit(k)(args)
            fname = _target_name(func.func)
            if fname is not None:
                idx = _method_donation(index, call, fname, aliases)
                if idx:
                    return idx, f"{fname}(...)"
            return None
        # property/name: self._round_jit(args) or round_prog(args) where
        # the definition (or a local assignment) builds a donating jit.
        # NOT when this call is itself immediately invoked — then it is a
        # jit FACTORY (self._round_jit_for(plan)(...)) and the donated
        # positions belong to the OUTER call, handled above.
        parent = getattr(call, "_nidt_parent", None)
        if isinstance(parent, ast.Call) and parent.func is call:
            return None
        name = _target_name(func)
        if name is None:
            return None
        idx = _method_donation(index, call, name, aliases)
        if idx:
            return idx, name
        return None

    def _reads_after(self, mod: ModuleInfo, fn: ast.AST, call: ast.Call,
                     indices: tuple[int, ...], callee: str
                     ) -> Iterator[Finding]:
        stmt = self._enclosing_stmt(call)
        if stmt is None or stmt.end_lineno is None:
            return
        donated_names = []
        for i in indices:
            if i < len(call.args) and isinstance(call.args[i], ast.Name):
                donated_names.append(call.args[i].id)
        if not donated_names:
            return
        # rebinding on the dispatch statement itself (the blessed
        # driver shape) clears the name immediately
        rebound_here = self._assigned_names(stmt)
        tracked = [n for n in donated_names if n not in rebound_here]
        if not tracked:
            return
        # later statements: a load before a rebind is a use-after-donate
        first_rebind: dict[str, int] = {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.stmt) or node.lineno <= stmt.end_lineno:
                continue
            for n in self._assigned_names(node):
                if n in tracked:
                    first_rebind[n] = min(first_rebind.get(n, 1 << 30),
                                          node.lineno)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in tracked
                    and node.lineno > stmt.end_lineno
                    and node.lineno < first_rebind.get(node.id, 1 << 30)):
                continue
            yield Finding(
                mod.path, node.lineno, "donation-use-after-donate",
                f"{node.id!r} is read after being passed in a donated "
                f"argument position of {callee} (line {call.lineno}); "
                "the dispatch deletes donated buffers — snapshot before "
                "dispatching or rebind the name from the call's result")

    @staticmethod
    def _enclosing_stmt(node: ast.AST) -> ast.stmt | None:
        cur: ast.AST | None = node
        while cur is not None:
            if isinstance(cur, ast.stmt):
                return cur
            cur = getattr(cur, "_nidt_parent", None)
        return None

    @staticmethod
    def _assigned_names(stmt: ast.stmt) -> set[str]:
        out: set[str] = set()

        def collect(t: ast.AST) -> None:
            if isinstance(t, ast.Name):
                out.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    collect(el)
            elif isinstance(t, ast.Starred):
                collect(t.value)

        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                collect(t)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            collect(stmt.target)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            collect(stmt.target)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    collect(item.optional_vars)
        return out
