"""Trace-safety rules: no host syncs or Python-RNG reads inside traced code.

Every engine's round program is jitted (``jax.jit(round_fn)``) and its
per-client block is vmapped; a ``float()``/``.item()``/``np.asarray``/
``jax.device_get`` there either fails to trace or — worse — silently bakes
a traced value into a Python constant, and ``np.random.*`` bakes ONE draw
into the compiled executable, destroying round-to-round randomness. The
rule marks a function as traced when it is

- decorated with ``jax.jit`` (or ``functools.partial(jax.jit, ...)``), or
- passed (possibly through ``functools.partial``) to ``jax.jit``,
  ``jax.vmap``, ``jax.pmap``, ``pjit`` or ``shard_map`` — resolved
  lexically: local ``def``s by enclosing-scope name lookup, methods by
  ``self.<name>`` within the class, lambdas in place, or
- CALLED from a traced body by a lexically resolvable name (bare name
  or ``self.<name>``), transitively — the tracer does not stop at a
  call boundary, so ``jax.vmap(lambda u: attack(u, ref))`` traces
  ``attack``'s body too (the faults/adversary.py idiom, ISSUE 5);
  foreign attributes (``module.fn``) still lint in their own file,

and then flags the calls above anywhere lexically inside it (nested
helpers included). Calls *of* the traced function, and host code that
merely consumes its outputs, are not flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    normalize,
    register,
)

#: tracer entry point -> positional indices of the arguments it traces
#: (jax.lax.cond traces both branches; while_loop traces cond AND body)
TRACERS: dict[str, tuple[int, ...]] = {
    "jax.jit": (0,),
    "jax.shard_map": (0,),  # jax >= 0.8 spelling of shard_map
    "jax.vmap": (0,),
    "jax.pmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.experimental.pjit.pjit": (0,),
    "jax.experimental.shard_map.shard_map": (0,),
    "jax.lax.scan": (0,),
    "jax.lax.map": (0,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.associative_scan": (0,),
}
_PARTIAL = {"functools.partial"}

#: host-synchronizing calls by canonical dotted name
HOST_SYNC_DOTTED = {
    "numpy.asarray",
    "numpy.array",
    "numpy.ascontiguousarray",
    "jax.device_get",
}
#: host-synchronizing zero-arg methods on array-likes
HOST_SYNC_METHODS = {"item", "tolist"}

_SCOPES = (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
           ast.ClassDef)
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _annotate_parents(tree: ast.Module) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._nidt_parent = node  # type: ignore[attr-defined]


def _ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, "_nidt_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_nidt_parent", None)


def _unwrap_partial(node: ast.AST, aliases: dict[str, str]) -> ast.AST:
    """``functools.partial(f, ...)`` -> ``f`` (recursively)."""
    if (isinstance(node, ast.Call)
            and normalize(dotted_name(node.func), aliases) in _PARTIAL
            and node.args):
        return _unwrap_partial(node.args[0], aliases)
    return node


def _is_tracer(node: ast.AST, aliases: dict[str, str]) -> bool:
    return normalize(dotted_name(node), aliases) in TRACERS


class _DefIndex:
    """Lexical lookup of function definitions: ``(scope, name) -> def``."""

    def __init__(self, tree: ast.Module):
        self._by_scope: dict[tuple[int, str], ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = self._enclosing_scope(node)
                self._by_scope[(id(scope), node.name)] = node

    @staticmethod
    def _enclosing_scope(node: ast.AST) -> ast.AST:
        for anc in _ancestors(node):
            if isinstance(anc, _SCOPES):
                return anc
        return node

    def resolve_name(self, at: ast.AST, name: str) -> ast.AST | None:
        """Innermost-scope-first lookup of ``name`` from ``at``'s position."""
        for anc in _ancestors(at):
            if isinstance(anc, _SCOPES):
                hit = self._by_scope.get((id(anc), name))
                if hit is not None:
                    return hit
        return None

    def resolve_method(self, at: ast.AST, name: str) -> ast.AST | None:
        for anc in _ancestors(at):
            if isinstance(anc, ast.ClassDef):
                return self._by_scope.get((id(anc), name))
        return None


def collect_traced(mod: ModuleInfo) -> list[ast.AST]:
    """All function/lambda nodes handed to a tracer in this module."""
    _annotate_parents(mod.tree)
    index = _DefIndex(mod.tree)
    aliases = mod.aliases
    traced: dict[int, ast.AST] = {}

    def mark(node: ast.AST | None) -> None:
        if isinstance(node, _FUNCS):
            traced[id(node)] = node

    def mark_target(at: ast.AST, target: ast.AST) -> None:
        target = _unwrap_partial(target, aliases)
        if isinstance(target, (ast.List, ast.Tuple)):
            for el in target.elts:  # e.g. jax.lax.switch branch lists
                mark_target(at, el)
        elif isinstance(target, ast.Lambda):
            mark(target)
        elif isinstance(target, ast.Name):
            mark(index.resolve_name(at, target.id))
        elif (isinstance(target, ast.Attribute)
              and isinstance(target.value, ast.Name)
              and target.value.id in ("self", "cls")):
            mark(index.resolve_method(at, target.attr))
        # imported / foreign attributes (e.g. jax.vmap(module.fn)) are not
        # resolvable lexically — their bodies are linted in their own file

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                target = deco.func if isinstance(deco, ast.Call) else deco
                if _is_tracer(target, aliases):
                    mark(node)
                elif (isinstance(deco, ast.Call)
                      and normalize(dotted_name(deco.func), aliases)
                      in _PARTIAL and deco.args
                      and _is_tracer(deco.args[0], aliases)):
                    mark(node)
        if not (isinstance(node, ast.Call)
                and _is_tracer(node.func, aliases) and node.args):
            continue
        for idx in TRACERS[normalize(dotted_name(node.func), aliases)]:
            if idx < len(node.args):
                mark_target(node, node.args[idx])

    # transitive closure (ISSUE 5): a call from inside a traced body to
    # a lexically resolvable function (bare name / self-method) traces
    # the callee's body too — jax.vmap(lambda u: attack(u, ref)) runs
    # attack under the tracer just as surely as attack's own decorator
    # would. Foreign attributes (module.fn) are not resolvable here and
    # lint in their own file.
    work = list(traced.values())
    while work:
        root = work.pop()
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            callee = None
            if isinstance(node.func, ast.Name):
                callee = index.resolve_name(node, node.func.id)
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id in ("self", "cls")):
                callee = index.resolve_method(node, node.func.attr)
            if isinstance(callee, _FUNCS) and id(callee) not in traced:
                traced[id(callee)] = callee
                work.append(callee)
    return list(traced.values())


@register
class TraceSafetyRule(Rule):
    rule_ids = ("trace-host-sync", "trace-np-random")
    description = ("no float()/.item()/.tolist()/np.asarray/jax.device_get "
                   "(trace-host-sync) or np.random.* (trace-np-random) "
                   "lexically inside jitted/vmapped/shard_mapped functions")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        seen: set[int] = set()
        for root in collect_traced(mod):
            for node in ast.walk(root):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                seen.add(id(node))
                yield from self._check_call(mod, node)

    def _check_call(self, mod: ModuleInfo,
                    node: ast.Call) -> Iterator[Finding]:
        func = node.func
        if isinstance(func, ast.Name) and func.id == "float":
            yield Finding(mod.path, node.lineno, "trace-host-sync",
                          "float() on a traced value forces a host sync "
                          "(bakes the tracer into a Python constant)")
            return
        if (isinstance(func, ast.Attribute)
                and func.attr in HOST_SYNC_METHODS and not node.args):
            yield Finding(mod.path, node.lineno, "trace-host-sync",
                          f".{func.attr}() forces a host sync inside a "
                          "traced function")
            return
        name = normalize(dotted_name(func), mod.aliases)
        if name in HOST_SYNC_DOTTED:
            yield Finding(mod.path, node.lineno, "trace-host-sync",
                          f"{name} materializes on host inside a traced "
                          "function")
        elif name is not None and name.startswith("numpy.random."):
            yield Finding(mod.path, node.lineno, "trace-np-random",
                          f"{name} inside a traced function bakes one "
                          "Python-RNG draw into the compiled executable")
