"""Lock-discipline rules for ``distributed/`` transports.

The broker's concurrency contract (distributed/broker.py:20-26): a frame
must be written atomically under the destination socket's write lock, and
the shared topic/subscriber maps are only touched under the broker lock.
The rule enforces both lexically:

- ``lock-send``       — ``.send``/``.sendall`` on a socket must happen
  inside a ``with <lock>:`` block (any context manager whose dotted name
  mentions "lock"); otherwise two serve threads fanning out to the same
  subscriber can interleave bytes mid-frame and desync the stream.
- ``lock-shared-map`` — mutations of the broker's shared registries
  (``_subs``/``_retained``/``_wlocks``/``_conns`` and friends) must
  happen under a lock; an unlocked ``dict``/``list``/``set`` mutation
  races subscriber registration against teardown.

Lexical means per-function: a helper that writes without taking the lock
is flagged at its ``def`` site even if every current caller holds the
lock — that invariant lives in the callers and must be pragma'd with the
justification where the send happens. The rule fires for files under a
``distributed/``, ``faults/`` or ``asyncfl/`` directory (the
fault-injection wrapper and the selector core both write raw frames —
torn-frame sends carry the same interleaving hazard as the
transports').
"""

from __future__ import annotations

import ast
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)

SEND_METHODS = {"send", "sendall", "sendto"}
SHARED_MAP_ATTRS = {"_subs", "_retained", "_wlocks", "_conns",
                    "_subscribers", "_topics"}
MUTATING_METHODS = {"append", "extend", "insert", "remove", "pop",
                    "popitem", "clear", "update", "setdefault", "add",
                    "discard"}


def _is_lock_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Subscript):  # e.g. self._wlocks[conn]
        return _is_lock_expr(node.value)
    name = dotted_name(node)
    if name is None and isinstance(node, ast.Call):
        # e.g. self._wlocks.setdefault(conn, threading.Lock())
        name = dotted_name(node.func)
    return name is not None and any(
        "lock" in part.lower() for part in name.split("."))


def _shared_attr(node: ast.AST) -> str | None:
    """``self._subs`` (or ``self.x._subs``) -> ``_subs``."""
    if isinstance(node, ast.Attribute) and node.attr in SHARED_MAP_ATTRS:
        return node.attr
    return None


@register
class LockDisciplineRule(Rule):
    rule_ids = ("lock-send", "lock-shared-map")
    description = ("in distributed/ and faults/, socket .send/.sendall "
                   "and mutations of shared topic/subscriber maps must "
                   "sit inside a `with <lock>:` block")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not {"distributed", "faults", "asyncfl"} & set(mod.path_parts):
            return
        yield from self._walk(mod, mod.tree.body, lock_depth=0)

    def _walk(self, mod: ModuleInfo, stmts: list[ast.stmt],
              lock_depth: int) -> Iterator[Finding]:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                if lock_depth == 0:
                    # the header's own expressions run BEFORE the lock is
                    # held (e.g. `with self._wlocks.setdefault(c, Lock()):`
                    # mutates the shared registry unlocked)
                    yield from self._check_stmt_exprs(mod, stmt)
                held = lock_depth + sum(
                    _is_lock_expr(item.context_expr)
                    for item in stmt.items)
                yield from self._walk(mod, stmt.body, held)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                # a nested def runs later, outside the enclosing with
                yield from self._walk(mod, stmt.body, lock_depth=0)
                continue
            if lock_depth == 0:
                yield from self._check_stmt_exprs(mod, stmt)
            yield from self._walk_nested_blocks(mod, stmt, lock_depth)

    def _walk_nested_blocks(self, mod: ModuleInfo, stmt: ast.stmt,
                            lock_depth: int) -> Iterator[Finding]:
        """Recurse into if/for/while/try bodies, preserving lock depth."""
        for field in ("body", "orelse", "finalbody"):
            block = getattr(stmt, field, None)
            if isinstance(block, list) and block and isinstance(
                    block[0], ast.stmt):
                yield from self._walk(mod, block, lock_depth)
        for handler in getattr(stmt, "handlers", []) or []:
            yield from self._walk(mod, handler.body, lock_depth)

    def _check_stmt_exprs(self, mod: ModuleInfo,
                          stmt: ast.stmt) -> Iterator[Finding]:
        """Flag unlocked sends / shared-map mutations in this statement's
        own expressions (nested statement blocks are handled by _walk)."""
        for node in self._own_expressions(stmt):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and isinstance(
                        sub.func, ast.Attribute):
                    attr = sub.func.attr
                    recv = sub.func.value
                    if attr in SEND_METHODS and not _shared_attr(recv):
                        yield Finding(
                            mod.path, sub.lineno, "lock-send",
                            f".{attr}() outside a `with <lock>:` block — "
                            "concurrent writers can interleave bytes "
                            "mid-frame (broker.py concurrency contract)")
                    shared = _shared_attr(recv)
                    if shared and attr in MUTATING_METHODS:
                        yield Finding(
                            mod.path, sub.lineno, "lock-shared-map",
                            f"mutation {shared}.{attr}() outside a "
                            "`with <lock>:` block races concurrent "
                            "register/teardown")
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = (stmt.targets if isinstance(stmt, (ast.Assign,
                                                         ast.Delete))
                       else [stmt.target])
            for t in targets:
                if isinstance(t, ast.Subscript):
                    shared = _shared_attr(t.value)
                    if shared:
                        yield Finding(
                            mod.path, t.lineno, "lock-shared-map",
                            f"subscript write to {shared} outside a "
                            "`with <lock>:` block races concurrent "
                            "register/teardown")

    @staticmethod
    def _own_expressions(stmt: ast.stmt) -> Iterator[ast.AST]:
        """The statement's expression children, excluding nested statement
        blocks (those keep their own lock depth via _walk)."""
        for field, value in ast.iter_fields(stmt):
            if isinstance(value, ast.expr):
                yield value
            elif isinstance(value, list):
                for item in value:
                    if isinstance(item, ast.expr):
                        yield item
                    elif isinstance(item, ast.withitem):
                        yield item.context_expr
