"""precision-discipline rules: no stray f32 upcasts in the train step.

The mixed-precision contract (ISSUE 10, core/optim.py) is load-bearing
arithmetic, not a style choice: under ``precision=bf16_mixed`` the train
step's compute and activations are bfloat16 while the master weights,
momentum, and loss stay float32. A bare ``.astype(jnp.float32)`` (or
``jnp.asarray(x, jnp.float32)`` / ``jnp.float32(x)``) inside a TRACED
train-step body silently re-widens an activation mid-step: the bf16
model quietly pays f32 HBM traffic for that tensor on every step, the
bench's precision cells stop measuring what they claim, and nothing
fails — the classic mixed-precision regression.

The rule rides the trace-safety resolver (``collect_traced``: decorated
jits, functions handed to tracers, lambdas, self-methods, transitive
call closure) and fires for files under the train-step planes —
``core/``, ``ops/``, ``models/`` — where the contract lives. The
engines' aggregation tails are deliberately OUT of scope: they operate
on f32 master weights by contract, so their ``astype(jnp.float32)``
weight/summary vectors are the blessed representation, not an upcast.

Blessed sites inside the scope (the input-quantization raw cast, loss
weights, f32 histogram bins) carry ``# nidt: allow[precision-upcast] --
reason`` pragmas — the escape hatch the contract names.
"""

from __future__ import annotations

import ast
from typing import Iterator

from neuroimagedisttraining_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    normalize,
    register,
)
from neuroimagedisttraining_tpu.analysis.trace_safety import collect_traced

#: canonical dotted names that denote the float32 dtype
F32_DOTTED = {"jax.numpy.float32", "numpy.float32"}

#: cast-shaped callables whose dtype argument we inspect
CAST_DOTTED = {"jax.numpy.asarray", "jax.numpy.array", "numpy.asarray",
               "numpy.array"}


def _is_f32(node: ast.AST | None, aliases: dict[str, str]) -> bool:
    if node is None:
        return False
    if isinstance(node, ast.Constant):
        return node.value == "float32"
    return normalize(dotted_name(node), aliases) in F32_DOTTED


@register
class PrecisionDisciplineRule(Rule):
    rule_ids = ("precision-upcast",)
    description = (
        "no bare float32 upcasts (.astype(jnp.float32), jnp.asarray(x, "
        "jnp.float32), jnp.float32(x)) inside traced train-step bodies "
        "under core/, ops/, models/ — the bf16_mixed contract keeps "
        "compute in the model dtype; blessed master-weight/loss sites "
        "carry a precision-upcast pragma")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not {"core", "ops", "models"} & set(mod.path_parts):
            return
        seen: set[int] = set()
        for root in collect_traced(mod):
            for node in ast.walk(root):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                seen.add(id(node))
                yield from self._check_call(mod, node)

    def _check_call(self, mod: ModuleInfo,
                    node: ast.Call) -> Iterator[Finding]:
        aliases = mod.aliases
        func = node.func
        # x.astype(jnp.float32) / x.astype("float32")
        if (isinstance(func, ast.Attribute) and func.attr == "astype"
                and node.args and _is_f32(node.args[0], aliases)):
            yield Finding(
                mod.path, node.lineno, "precision-upcast",
                ".astype(float32) inside a traced train-step body "
                "re-widens a tensor regardless of the precision policy "
                "— use the model/compute dtype, or pragma a blessed "
                "master-weight/loss site")
            return
        name = normalize(dotted_name(func), aliases)
        # jnp.float32(x) — scalar/array construction pinned to f32
        if name in F32_DOTTED and node.args:
            yield Finding(
                mod.path, node.lineno, "precision-upcast",
                f"{name}(...) inside a traced train-step body pins the "
                "value to float32 regardless of the precision policy")
            return
        # jnp.asarray(x, jnp.float32) / dtype=jnp.float32
        if name in CAST_DOTTED:
            dtype_arg = node.args[1] if len(node.args) > 1 else None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dtype_arg = kw.value
            if _is_f32(dtype_arg, aliases):
                yield Finding(
                    mod.path, node.lineno, "precision-upcast",
                    f"{name}(..., float32) inside a traced train-step "
                    "body is an unconditional f32 cast — thread the "
                    "compute dtype, or pragma a blessed site")
