"""Asynchronous buffered control plane (ISSUE 7).

The cross-silo server (distributed/cross_silo.py) is round-synchronous:
deadline + quorum + barrier, throughput capped by the slowest survivor,
one listener/dispatch thread pair per connection. This package is its
cross-device-scale counterpart:

- :mod:`asyncfl.loop` — ``SelectorCommManager``, a selector-based
  rewrite of the server-side socket core behind the same
  ``BaseCommManager`` frame contract: ONE event-loop thread holds
  thousands of concurrent connections (persistent duplex or the legacy
  one-frame-per-connection clients, interchangeably), with bounded
  per-connection write queues for backpressure.
- :mod:`asyncfl.server` — ``BufferedFedAvgServer``, a FedBuff-style
  (Nguyen et al., AISTATS 2022) server: uploads accepted continuously
  into a bounded buffer, aggregated every K arrivals with polynomial
  staleness weighting ``(1 + tau)^-alpha``, broadcasts version-tagged so
  the wire codec's delta references stay correct against each client's
  actual base version, admitted staleness hard-bounded.
- :mod:`asyncfl.loadgen` — an asyncio load harness driving thousands of
  lightweight simulated clients (canned update pytrees, seeded
  ``FaultSchedule`` churn) against one server, emitting the
  ``bench_matrix/async_bench.json`` sync-vs-async cell.
"""

from neuroimagedisttraining_tpu.asyncfl.loop import (  # noqa: F401
    SelectorCommManager,
)
from neuroimagedisttraining_tpu.asyncfl.server import (  # noqa: F401
    BufferedFedAvgServer,
    staleness_weight,
)

__all__ = ["SelectorCommManager", "BufferedFedAvgServer",
           "staleness_weight"]
