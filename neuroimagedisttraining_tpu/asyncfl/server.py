"""``BufferedFedAvgServer``: FedBuff-style asynchronous aggregation.

Nguyen et al., "Federated Learning with Buffered Asynchronous
Aggregation" (AISTATS 2022): the server never waits for a cohort — it
accepts uploads continuously into a bounded buffer and aggregates every
K arrivals, down-weighting stale contributions. This is the high-traffic
limit of the FedProx premise (PAPERS.md: progress from whatever subset
reports) and ROADMAP item 3.

How it composes with everything already landed:

- **Version tags** reuse the PR 2 round-tag plumbing verbatim: the model
  VERSION (number of aggregations so far) rides ``ARG_ROUND_IDX`` on
  every sync, and clients echo it on upload — ``FedAvgClientProc`` and
  the ``FaultyCommManager`` chaos wrapper work unchanged. Staleness of
  an upload is ``tau = current_version - echoed_version``.
- **Codec reference threading** (PR 3) stays correct against the
  client's ACTUAL base version: the server keeps a ring of the last
  ``max_staleness + 1`` param trees and decodes each upload's delta
  frame against the very tree it broadcast under that version tag — a
  stale delta decoded against the current model would silently corrupt
  the update, which is why ``max_staleness`` also bounds the ring.
- **Aggregation** dispatches through the SAME jitted programs the
  synchronous server uses: ``survivor_weighted_mean``
  (``tree_weighted_mean``) when undefended, ``survivor_defended_mean``
  (``robust.aggregate_with_defense``) when a ``--defense`` is armed —
  over "effective uploads" ``u + (params_now - params_base)``
  (delta-transported to the current base). A zero-staleness upload skips
  the transport entirely, so a buffer of all-current uploads with
  ``buffer_k == cohort`` reproduces one synchronous round BITWISE
  (pinned in tests/test_asyncfl.py).
- **Weights**: ``staleness_weight(n, tau, alpha) = n * (1+tau)^-alpha``
  — FedBuff's polynomial staleness discount on the FedAvg sample-count
  weight. ``tau == 0`` gives exactly ``n`` (the equivalence pin's
  precondition); ``tau > max_staleness`` never reaches the weight: the
  upload is dropped at accept time with a logged reason.
- **Quarantine / strikes / heartbeats / EF reset** are inherited from
  ``FedAvgServer``: outlier scoring runs per aggregation over the
  buffer's delta-transported trees against the current params (the same
  ``update_outlier_flags`` leave-one-out geometry), and a released
  silo's first sync carries ``ARG_EF_RESET`` exactly as in the
  synchronous plane.

What does NOT compose (rejected at STARTUP, like the secure/codec
rejection): secure aggregation — its two-phase weight exchange is a
round barrier by construction (every client's normalized weight depends
on every other reporter), which is the one thing an asynchronous buffer
cannot provide; ``distributed/run.py`` refuses ``--secure
--async_server``. Round deadlines/quorum are meaningless without a round
barrier and are refused too.

Protocol (no barrier anywhere):

- register -> the server immediately replies with the current
  version-tagged model (first contact gets ``INIT_CONFIG``, a
  re-register gets ``SYNC_MODEL`` — the late-rejoin path, verbatim).
- upload -> accept/drop, maybe aggregate, then reply with the CURRENT
  model so the sender trains again at once. Every client is therefore
  always either training or has one upload in flight; fast clients
  simply lap slow ones, whose uploads arrive stale and down-weighted.
- after ``comm_round`` aggregations the server broadcasts FINISH to
  every rank that ever registered.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from neuroimagedisttraining_tpu.asyncfl.loop import SelectorCommManager
from neuroimagedisttraining_tpu.codec import wire as codec
from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.comm import BASE_PORT
from neuroimagedisttraining_tpu.distributed.cross_silo import (
    FedAvgServer,
    survivor_defended_mean,
    survivor_weighted_mean,
    tree_all_finite,
)
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.obs import names as obs_names
from neuroimagedisttraining_tpu.obs import rules as obs_rules

log = logging.getLogger("neuroimagedisttraining_tpu.asyncfl")

#: flow-END events emitted per aggregation when the tracer is armed
#: (ISSUE 13) — bounded so trace volume never scales with buffer_k
_FLOW_ENDS_MAX = 64


def staleness_weight(n: float, tau: int, alpha: float) -> float:
    """FedBuff polynomial staleness weight on the FedAvg sample count:
    ``n * (1 + tau)^-alpha``, float64 host math so ``tau == 0`` returns
    ``n`` EXACTLY (the equivalence pin depends on it) and the host
    replay in tests reproduces the server's weights bitwise."""
    return float(n) * (1.0 + float(int(tau))) ** (-float(alpha))


class BufferedFedAvgServer(FedAvgServer):
    """Rank 0 of the asynchronous control plane. See the module
    docstring for the protocol; knobs:

    - ``buffer_k`` — aggregate every K accepted uploads (0 = cohort
      size, which with zero staleness reproduces the synchronous
      server). Since every sender holds at most ONE buffer slot, the
      effective trigger threshold shrinks below K when clients are
      known to be gone (heartbeat-suspect, quarantined) — see
      ``_k_eff``; a full cohort-sized buffer would otherwise deadlock
      on the first permanent crash.
    - ``staleness_alpha`` — polynomial staleness exponent (0 disables
      down-weighting; FedBuff's default regime is ~0.5).
    - ``max_staleness`` — hard admission bound: an upload based on a
      version more than this many aggregations old is DROPPED with a
      logged reason (and its sender immediately re-synced), and the
      param ring that backs codec delta decoding holds exactly this
      many historical versions.
    """

    def __init__(self, init_params, comm_round: int, num_clients: int,
                 buffer_k: int = 0, staleness_alpha: float = 0.5,
                 max_staleness: int = 20, world_size: int | None = None,
                 host_map: dict[int, str] | None = None,
                 base_port: int | None = None, comm=None,
                 secure_quant=None, **kw):
        from neuroimagedisttraining_tpu.core import robust

        # --- async knobs fail loudly HERE (startup), never mid-run ---
        self.buffer_k = int(buffer_k) if buffer_k else int(num_clients)
        if self.buffer_k < 1:
            raise ValueError(f"buffer_k must be >= 1, got {buffer_k}")
        if staleness_alpha < 0:
            raise ValueError(
                f"staleness_alpha must be >= 0, got {staleness_alpha}")
        if max_staleness < 0:
            raise ValueError(
                f"max_staleness must be >= 0, got {max_staleness}")
        self.staleness_alpha = float(staleness_alpha)
        self.max_staleness = int(max_staleness)
        if kw.get("round_deadline", 0) or kw.get("quorum", 0):
            raise ValueError(
                "BufferedFedAvgServer has no round barrier: "
                "round_deadline/quorum do not apply (uploads aggregate "
                "every buffer_k arrivals instead)")
        # secure QUANTIZED aggregation composes with the buffer (ISSUE
        # 8): the one-phase protocol has no weight exchange — clients
        # ship field-element frames of their UNWEIGHTED quantized update
        # + n in the clear, and the staleness weights fold inside the
        # field as integers (privacy/secure_quant.integer_weights). The
        # dense two-phase --secure protocol remains rejected at the CLI
        # (its weight exchange IS a round barrier). What secure mode
        # costs here: no codec delta transport (frames are not deltas),
        # no non-finite gate, no server-side defenses or outlier scoring
        # (the server never sees plaintext), and frames fold UNSCALED —
        # per-version references would disagree on leaf scales, so
        # raw-moment leaves (BatchNorm stats) lean on the 32-bit
        # field's range margin instead. ARCHITECTURE.md "Privacy plane"
        # carries the full matrix.
        self.secure_quant = secure_quant
        if secure_quant is not None:
            from neuroimagedisttraining_tpu.privacy import check_headroom

            if kw.get("defense", "none") in robust.ROBUST_AGGREGATORS \
                    or kw.get("quarantine_rounds", 0):
                raise ValueError(
                    "secure_quant supports neither order-statistic "
                    "defenses nor quarantine on the buffered server: "
                    "the buffer holds masked field elements, not "
                    "per-silo updates (clip-family defenses run client-"
                    "side; see ARCHITECTURE.md 'Privacy plane')")
            if kw.get("wire_masks") is not None:
                raise ValueError(
                    "secure_quant is incompatible with the wire codec "
                    "mask handoff (field-element frames, not model "
                    "floats)")
            check_headroom(secure_quant, min(self.buffer_k,
                                             int(num_clients)))
            from neuroimagedisttraining_tpu.privacy import secure_quant \
                as sq

            # one-phase folding applies the (integer-scaled) staleness
            # weights INSIDE the field, so the aggregate range scales
            # with the folded weight mass. The value bound the capacity
            # is stated against starts from the init model's ACTUAL
            # leaf magnitudes (doubled for drift) — raw-moment leaves
            # like BatchNorm stats dwarf VALUE_BOUND; growth beyond 2x
            # the largest observed startup magnitude still leans on the
            # 32-bit field's remaining margin (frames fold UNSCALED —
            # see the protocol note above). This check precludes
            # weight-mass overflow under that bound, never value growth
            # it cannot observe.
            import jax

            init_mag = max((float(np.max(np.abs(
                np.asarray(x, np.float64))))
                for x in jax.tree.leaves(init_params)
                if np.asarray(x).size), default=0.0)
            self._sq_value_bound = max(sq.VALUE_BOUND, 2.0 * init_mag)
            k_cap = min(self.buffer_k, int(num_clients))
            cap = sq.weighted_fold_capacity(secure_quant,
                                            self._sq_value_bound)
            if cap <= k_cap:
                raise ValueError(
                    f"secure_quant field too small for the buffered "
                    f"one-phase fold: capacity {cap:.1f} weight units "
                    f"< buffer of {k_cap} at value bound "
                    f"{self._sq_value_bound:.0f} — use "
                    "--secure_quant_field_bits 32 (the two-phase sync "
                    "protocol keeps the small field; see "
                    "ARCHITECTURE.md 'Privacy plane')")
            #: expected frame leaf structure, computed ONCE — the
            #: admission gate compares every upload against it on the
            #: single dispatch thread (the model structure is fixed for
            #: the run; version skew is exactly what the compare rejects)
            self._sq_sizes = [(n, int(np.asarray(x).size))
                              for n, x in sq._named_leaves(init_params)]
        if comm is None:
            # replies run on the single dispatch thread under _rlock: a
            # peer that uploads but stops READING would otherwise stall
            # the whole control plane for send_timeout per reply once
            # its bounded write queue fills. 2 s bounds the stall; the
            # timeout surfaces as ConnectionError, _send_tolerant marks
            # the peer suspect, and the federation moves on.
            comm = SelectorCommManager(
                0, world_size or num_clients + 1, host_map=host_map,
                base_port=BASE_PORT if base_port is None else base_port,
                send_timeout=2.0)
        super().__init__(init_params, comm_round, num_clients,
                         world_size=world_size, comm=comm, **kw)
        # the aggregation cohort is the BUFFER, not the client count —
        # but one slot per sender also caps it at the cohort size, so
        # an order-statistic defense must be feasible over
        # min(buffer_k, num_clients) uploads or it would fall back on
        # every single aggregation (checking bare buffer_k would let
        # buffer_k > cohort silently disarm the defense for the run)
        if self.defense in robust.ROBUST_AGGREGATORS:
            robust._check_f(min(self.buffer_k, int(num_clients)),
                            self.byz_f, self.defense)
        #: there is no registration barrier: the federation is "started"
        #: from the first moment, which is also what lets the inherited
        #: heartbeat monitor invoke ``_maybe_complete`` when a new
        #: suspect lowers ``_k_eff`` below the buffer occupancy
        self._started = True
        #: version ring: version -> broadcast params (numpy), the delta
        #: reference for codec frames tagged with that version
        self._ring: dict[int, dict] = {0: self.params}
        #: accepted-but-not-yet-aggregated uploads, arrival order
        self._buffer: list[dict] = []
        #: sender -> highest ARG_UPLOAD_SEQ accepted (watermark dedup:
        #: a re-delivered frame repeats its seq and is dropped, while an
        #: honest repeat contribution from an unchanged base version
        #: ships a fresh seq and is accepted; reset when the sender
        #: re-registers, since a restarted process restarts its counter)
        self._seq_seen: dict[int, int] = {}
        #: sender -> base versions already accepted, the dedup fallback
        #: for legacy senders that ship no seq: at most one contribution
        #: per sync version (exactly what the sync protocol produces)
        self._contributed: dict[int, set[int]] = {}
        #: every _on_model increments ``received`` and then EXACTLY ONE
        #: other counter — the frame-accounting audit the load harness
        #: reconciles (upload_audit)
        self.upload_stats = {
            "received": 0, "accepted": 0, "dropped_stale": 0,
            "dropped_duplicate": 0, "dropped_future": 0,
            "dropped_quarantined": 0, "dropped_undecodable": 0,
            "dropped_nonfinite": 0, "dropped_after_done": 0,
            # frame decoded as a Message but its fields are broken
            # (missing num_samples, non-numeric tags): a buggy client
            # among thousands must never kill the dispatch thread
            "dropped_malformed": 0,
            # accepted into the buffer, then discarded because THIS
            # aggregation's outlier scoring quarantined the sender
            "quarantine_discarded": 0,
            # accepted, then discarded whole-buffer because a secure-
            # quant aggregation failed mid-fold (structure skew past
            # the admission gate) — the model stayed at its version
            "aggregation_discarded": 0,
            # accepted, then replaced by a NEWER accepted upload from
            # the same sender before the buffer filled (one slot per
            # sender per aggregation — see _accept_async)
            "superseded_in_buffer": 0,
        }
        # ---- obs plane (ISSUE 9): the registry mirror of upload_stats
        # (every bump goes through _stat, so counter == dict entry by
        # construction — the no-double-counting pin), plus the
        # distributions ROADMAP item 3 needs to SEE: the staleness
        # spectrum the (1+tau)^-alpha weighting actually met, and the
        # buffer occupancy between aggregations. All on the dispatch
        # thread under _rlock — never inside a jitted program.
        self._obs_uploads = obs_metrics.counter(
            obs_names.ASYNC_UPLOADS,
            "async-server upload verdicts (mirrors upload_stats)",
            labelnames=("outcome",))
        self._obs_staleness = obs_metrics.histogram(
            obs_names.ASYNC_STALENESS,
            "staleness tau (versions) of accepted uploads",
            buckets=(0, 1, 2, 4, 8, 16, 32, 64))
        self._obs_buffer = obs_metrics.gauge(
            obs_names.ASYNC_BUFFER_OCCUPANCY,
            "uploads currently buffered toward the next aggregation")
        self._obs_k_eff = obs_metrics.gauge(
            obs_names.ASYNC_BUFFER_K_EFF,
            "effective aggregation trigger threshold (buffer_k shrunk "
            "by known-gone clients)")
        self._obs_k_eff.set(self._k_eff())

    def _stat(self, key: str, n: int = 1) -> None:
        """Under ``_rlock``: bump one ``upload_stats`` counter AND its
        registry mirror in lockstep (the single bump point that keeps
        ``upload_audit`` and a ``/metrics`` scrape equal)."""
        self.upload_stats[key] += n
        self._obs_uploads.inc(n, outcome=key)

    # the async server must NEVER crash its dispatch thread because one
    # of thousands of clients vanished mid-reply: always send tolerantly
    @property
    def fault_tolerant(self) -> bool:
        return True

    @property
    def version(self) -> int:
        """Model version == number of aggregations so far. It IS
        ``round_idx`` — the alias the round-tag plumbing generalizes
        through."""
        return self.round_idx

    def current_version(self) -> int:
        with self._rlock:
            return self.round_idx

    def _observe_health_boundary(self) -> None:
        """Evaluate the armed anomaly rules (obs/rules.py) at this
        version boundary against the process registry; the sharded
        ingest root overrides with the fan-in-MERGED snapshot so rules
        fire on worker-labeled series too. No-op while unarmed."""
        obs_rules.observe_boundary(self.round_idx)

    # ---- handlers (dispatch thread) ----

    def _on_register(self, msg: M.Message) -> None:
        """No registration barrier: first contact is answered with the
        current version-tagged model immediately — a cross-device cohort
        trickles in over hours and the federation must already be
        making progress."""
        with self._rlock:
            c = msg.sender_id
            if self._done.is_set():
                self._send_tolerant(M.Message(M.MSG_TYPE_S2C_FINISH, 0, c))
                return
            first = c not in self._registered
            self._registered.add(c)
            self._suspect.discard(c)
            # restarted process: fresh seq counter, fresh legacy
            # per-version dedup marks (its pre-restart contribution was
            # a different process's training)
            self._seq_seen.pop(c, None)
            self._contributed.pop(c, None)
            self._last_beat[c] = time.monotonic()
            if not first:
                log.info("server: client %d re-registered; shipping "
                         "version %d state", c, self.round_idx)
            self._send_sync_to(M.MSG_TYPE_S2C_INIT_CONFIG if first
                               else M.MSG_TYPE_S2C_SYNC_MODEL, c)

    def _on_model(self, msg: M.Message) -> None:
        with self._rlock:
            self._stat("received")
            if self._done.is_set():
                self._stat("dropped_after_done")
                return
            c = msg.sender_id
            self._last_beat[c] = time.monotonic()
            self._suspect.discard(c)
            try:
                ok = self._accept_async(msg)
            except Exception as e:  # noqa: BLE001 — a frame with broken
                # FIELDS (missing num_samples, non-numeric version/seq
                # from a version-skewed client) is a dropped upload, not
                # a dead dispatch thread — the same contract the decode
                # guard keeps for broken BODIES
                self._stat("dropped_malformed")
                obs_flight.record("drop_malformed", client=c,
                                  version=self.round_idx,
                                  error=f"{type(e).__name__}: {e}")
                log.warning("server: dropping malformed upload from %d "
                            "(%s: %s)", c, type(e).__name__, e)
                ok = False
            if ok:
                self._stat("accepted")
                if len(self._buffer) >= self._k_eff():
                    self._aggregate_buffer()
            if not self._done.is_set():
                # accepted or dropped, the sender gets the CURRENT model
                # so it immediately trains at the freshest version —
                # liveness never depends on the verdict
                self._send_sync_to(M.MSG_TYPE_S2C_SYNC_MODEL, c)

    def _accept_async(self, msg: M.Message) -> bool:
        """Under ``_rlock``: admission control. Returns True iff the
        upload entered the buffer; every rejection increments exactly
        one ``upload_stats`` counter and logs its reason."""
        c = msg.sender_id
        tag = msg.get(M.ARG_ROUND_IDX)
        fid = obs_trace.flow_id_of(msg.get(M.ARG_TRACE_CTX))
        v = self.round_idx if tag is None else int(tag)
        tau = self.round_idx - v
        if tau < 0:
            self._stat("dropped_future")
            obs_flight.record("drop_future", client=c, tagged=v,
                              version=self.round_idx)
            log.warning("server: dropping upload from %d tagged with "
                        "FUTURE version %d (current %d)", c, v,
                        self.round_idx)
            return False
        if tau > self.max_staleness:
            self._stat("dropped_stale")
            obs_flight.record("drop_stale", client=c, tagged=v,
                              tau=tau, version=self.round_idx)
            log.warning("server: dropping ancient upload from %d "
                        "(base version %d, current %d, staleness %d > "
                        "max_staleness %d)", c, v, self.round_idx, tau,
                        self.max_staleness)
            return False
        seq = msg.get(M.ARG_UPLOAD_SEQ)
        if seq is not None:
            if int(seq) <= self._seq_seen.get(c, -1):
                self._stat("dropped_duplicate")
                obs_flight.record("drop_duplicate", client=c,
                                  seq=int(seq), version=self.round_idx)
                log.warning("server: dropping re-delivered upload from "
                            "%d (seq %s <= watermark %d)", c, seq,
                            self._seq_seen[c])
                return False
            # advance the watermark NOW, not on acceptance: the verdict
            # rendered below (accept OR reject) is final for this seq,
            # and a transport re-delivery must repeat the VERDICT
            # (duplicate-drop), never the processing — a duplicated
            # transient-NaN frame re-processed here would strike its
            # sender twice and could quarantine an honest silo
            self._seq_seen[c] = int(seq)
        elif v in self._contributed.get(c, ()):
            self._stat("dropped_duplicate")
            obs_flight.record("drop_duplicate", client=c, base_version=v,
                              version=self.round_idx)
            log.warning("server: dropping duplicate upload from %d for "
                        "base version %d (sender ships no upload_seq)",
                        c, v)
            return False
        if c in self._quarantined_now():
            self._stat("dropped_quarantined")
            obs_flight.record("drop_quarantined", client=c,
                              version=self.round_idx)
            log.warning("server: dropping upload from QUARANTINED silo "
                        "%d (version %d; window ends at version %d)",
                        c, self.round_idx, self._quarantine_until[c])
            return False
        if self.secure_quant is not None:
            from neuroimagedisttraining_tpu.privacy import secure_quant as sq

            frame = msg.get(M.ARG_MODEL_PARAMS)
            try:
                sq._validate_frame(frame, self.secure_quant)
                # structure gate at ADMISSION (the plain path's decode
                # gate, transposed): a frame whose leaf set differs from
                # the model must never reach the aggregation fold, where
                # it would be a mid-buffer failure instead of a drop
                if sq.SlotAccumulator._frame_sizes(frame) != \
                        self._sq_sizes:
                    raise ValueError(
                        "frame leaf structure differs from the model "
                        "(version skew)")
            except (ValueError, KeyError, TypeError) as e:
                self._stat("dropped_undecodable")
                obs_flight.record("drop_undecodable", client=c,
                                  base_version=v, error=str(e))
                log.warning("server: dropping invalid secure-quant frame "
                            "from %d (base version %d): %s", c, v, e)
                return False
            n = float(msg.get(M.ARG_NUM_SAMPLES))
            if not (np.isfinite(n) and n >= 0):
                # a NaN sample count would silently collapse the whole
                # buffer's integer fold weights to uniform — treat it as
                # the malformed field it is (raise into _on_model's
                # dropped_malformed accounting, dispatch thread lives)
                raise ValueError(f"non-finite num_samples {n!r}")
            if seq is None:
                self._contributed.setdefault(c, set()).add(v)
            # no delta transport for stale frames (the server cannot see
            # the update to re-anchor it) and no non-finite gate (masked
            # field elements are always finite by construction — the
            # quantize maps a client-side NaN to the neutral zero
            # residue, never into the aggregate) — staleness is handled
            # by the down-weighting alone
            self._buffer_put(c, tau, n, {"frame": frame, "fid": fid})
            return True
        ref = self._ring[v]  # present by construction: tau <= ring span
        try:
            decoded = codec.decode_update(msg.get(M.ARG_MODEL_PARAMS),
                                          like=self.params,
                                          reference=ref,
                                          masks=self.wire_masks)
        except Exception as e:  # noqa: BLE001 — an undecodable frame is
            # a dropped upload, never a dead dispatch thread (same
            # contract as the synchronous server's _on_model)
            self._stat("dropped_undecodable")
            obs_flight.record("drop_undecodable", client=c,
                              base_version=v, error=str(e))
            log.warning("server: dropping undecodable upload from %d "
                        "(base version %d): %s", c, v, e)
            return False
        if not tree_all_finite(decoded):
            self._stat("dropped_nonfinite")
            obs_flight.record("reject_nonfinite", client=c,
                              base_version=v)
            self.byz_stats["nonfinite_rejected"] += 1
            log.warning("server: REJECTING non-finite upload from silo "
                        "%d (base version %d)", c, v)
            if self.quarantine_rounds > 0:
                self._strike(c, "non-finite upload")
            if seq is None:
                # legacy senders dedup by version: mark it so a
                # re-delivered copy of this rejected frame cannot
                # strike twice either
                self._contributed.setdefault(c, set()).add(v)
            return False
        n = float(msg.get(M.ARG_NUM_SAMPLES))
        if not (np.isfinite(n) and n >= 0):
            # a NaN/negative sample count poisons the staleness weight
            # and, under weak_dp, the accountant's geometry — malformed
            # field, same contract as the secure branch (raises into
            # _on_model's dropped_malformed accounting)
            raise ValueError(f"non-finite num_samples {n!r}")
        if tau == 0:
            u_eff = decoded  # bitwise passthrough (the equivalence pin)
        else:
            # delta-transport the stale model to the current base:
            # u + (params_now - params_base). The client's LEARNING
            # (u - base) is preserved exactly; what changes is the
            # anchor it applies to.
            import jax

            u_eff = jax.tree.map(
                lambda u, p, r: (np.asarray(u, np.float32)
                                 + (np.asarray(p, np.float32)
                                    - np.asarray(r, np.float32))
                                 ).astype(np.asarray(u).dtype),
                decoded, self.params, ref)
        if seq is None:  # the watermark already advanced at the gate
            self._contributed.setdefault(c, set()).add(v)
        self._buffer_put(c, tau, n, {"tree": u_eff, "fid": fid})
        return True

    def _buffer_put(self, c: int, tau: int, n: float,
                    payload: dict) -> None:
        """Under ``_rlock``: ONE buffer slot per sender — a client that
        laps the buffer (trains faster than it fills) REPLACES its
        older entry rather than occupying extra slots. This is what
        keeps the armed defense's threat model sound —
        robust._check_f(buffer_k, byz_f) bounds Byzantine ENTRIES, and
        without the cap a fast sign-flipping client could fill f+1
        slots by pace alone — and it keeps the aggregation weighting
        unbiased toward fast clients (FedBuff's one-contribution-per-
        client shape). Shared by the plain ({"tree": ...}) and
        secure-quant ({"frame": ...}) admission paths so the invariant
        lives in exactly one place."""
        for i, e in enumerate(self._buffer):
            if e["client"] == c:
                del self._buffer[i]
                self._stat("superseded_in_buffer")
                obs_flight.record("superseded_in_buffer", client=c,
                                  tau_old=int(e["tau"]), tau_new=int(tau),
                                  version=self.round_idx)
                log.info("server: upload from %d supersedes its own "
                         "buffered entry (tau %d -> %d)", c,
                         e["tau"], tau)
                break
        self._buffer.append({
            "client": c, "n": n, "tau": tau,
            "weight": staleness_weight(n, tau, self.staleness_alpha),
            **payload})
        if self._buffer[-1].get("fid") is not None \
                and obs_trace.TRACER.armed:
            # wire trace context (ISSUE 13): flow STEP at admission,
            # inside its own slice so Perfetto binds the arrow
            with obs_trace.span("upload_admit", client=int(c)):
                obs_trace.flow("upload", self._buffer[-1]["fid"], "t",
                               client=int(c))
        # accepted-upload observability: the staleness spectrum the
        # (1+tau)^-alpha weighting actually meets, live buffer depth,
        # and the accept decision in the flight ring
        self._obs_staleness.observe(int(tau))
        self._obs_buffer.set(len(self._buffer))
        obs_flight.record("accept", client=c, tau=int(tau),
                          version=self.round_idx)

    # ---- aggregation ----

    def _aggregate_buffer(self) -> None:
        """Under ``_rlock``: one FedBuff aggregation over the buffered
        uploads — outlier scoring first (a silo quarantined by THIS
        buffer is excluded from this very aggregation, mirroring the
        synchronous server), then the same jitted defended/weighted-mean
        dispatch, then the version advances and the ring/buffer/history
        roll forward."""
        from neuroimagedisttraining_tpu.core import robust

        # aggregate in CLIENT-ID order, not arrival order: float
        # reduction order must not depend on OS scheduling, so two runs
        # over the same upload set produce the same model bitwise — the
        # exact reason the synchronous server sorts its senders
        entries = sorted(self._buffer, key=lambda e: e["client"])
        if self.secure_quant is not None:
            self._aggregate_buffer_secure(entries)
            return
        senders = [e["client"] for e in entries]
        trees = [e["tree"] for e in entries]
        self._score_survivors(senders, trees)
        q = self._quarantined_now()
        if q & set(senders):
            kept = [e for e in entries if e["client"] not in q]
            self._stat("quarantine_discarded",
                       len(entries) - len(kept))
            entries = kept
        if not entries:
            # every buffered upload came from silos quarantined by this
            # very scoring pass: nothing trustworthy to aggregate —
            # keep the model, refill the buffer
            log.warning("server: buffer emptied by quarantine at "
                        "version %d - skipping aggregation", self.round_idx)
            obs_flight.record("buffer_emptied_by_quarantine",
                              version=self.round_idx)
            self._buffer = []
            self._obs_buffer.set(0)
            return
        trees = [e["tree"] for e in entries]
        ws = [e["weight"] for e in entries]
        senders = [e["client"] for e in entries]
        defense = robust.effective_defense(
            self.defense, len(entries), self.byz_f, warn=log.warning)
        extra = None
        if defense == "none":
            self.params = survivor_weighted_mean(trees, ws)
        else:
            rngs = None
            if defense == "weak_dp":
                import jax
                import jax.numpy as jnp

                base = jax.random.fold_in(
                    jax.random.key(self.defense_seed), self.round_idx)
                rngs = jax.vmap(
                    lambda s: jax.random.fold_in(base, s))(
                    jnp.asarray(senders, jnp.uint32))
                dp = self._note_weak_dp(senders, ws)
                extra = {"weak_dp": dp} if dp is not None else None
            self.params = survivor_defended_mean(
                trees, ws, self.params, defense=defense,
                byz_f=self.byz_f, geomed_iters=self.geomed_iters,
                norm_bound=self.norm_bound, stddev=self.stddev,
                rngs=rngs)
        self._advance_version(entries, senders, extra=extra)

    def _aggregate_buffer_secure(self, entries: list) -> None:
        """Under ``_rlock``: one buffered aggregation over secure-quant
        field-element frames. Staleness weights fold INSIDE the field as
        deterministic integer scalings (``integer_weights`` — the
        largest fixed-point scale whose total keeps the aggregate in
        headroom, re-derived per buffer so a replay is bitwise); the
        dequantized total divided by the integer weight mass is the
        staleness-weighted mean of the quantized updates. No plaintext
        ever materializes, so outlier scoring and server-side defenses
        are structurally out (rejected at startup); the weak_dp ledger
        still charges (the noise was added client-side, its geometry is
        config)."""
        from neuroimagedisttraining_tpu.privacy import (
            SlotAccumulator, integer_weights,
        )

        senders = [e["client"] for e in entries]
        ws = [e["weight"] for e in entries]
        try:
            w_int, denom = integer_weights(ws, self.secure_quant,
                                           self._sq_value_bound)
            acc = SlotAccumulator(self.secure_quant, like=self.params)
            for e, wi in zip(entries, w_int):
                acc.fold(e["frame"], weight_int=int(wi))
            new_params = acc.finalize(like=self.params,
                                      rescale=1.0 / denom)
        except (ValueError, KeyError, TypeError) as e:
            # belt over the admission gate's braces: a fold failure here
            # must cost one buffer, never the dispatch thread (this
            # server's own 'a dropped upload, never a dead dispatch
            # thread' contract) — the model stays at its last version
            # and the federation keeps moving
            log.error("server: secure-quant aggregation at version %d "
                      "failed (%s: %s) - discarding the %d-upload "
                      "buffer, model unchanged", self.round_idx,
                      type(e).__name__, e, len(entries))
            self._stat("aggregation_discarded", len(entries))
            obs_flight.record("aggregation_discarded",
                              version=self.round_idx,
                              uploads=len(entries),
                              error=f"{type(e).__name__}: {e}")
            self._buffer = []
            self._obs_buffer.set(0)
            return
        self.params = new_params
        extra = {"secure_quant": True,
                 "weights_int": [int(w) for w in w_int]}
        if self.defense == "weak_dp":
            dp = self._note_weak_dp(senders, ws)
            if dp is not None:
                extra["weak_dp"] = dp
        self._advance_version(entries, senders, extra=extra)

    def _advance_version(self, entries: list, senders: list,
                         extra: dict | None = None) -> None:
        """Under ``_rlock``: the shared post-aggregation transition —
        version++, ring/dedup maintenance, history, finish."""
        self._buffer = []
        self.round_idx += 1
        if obs_trace.TRACER.armed:
            # flow ENDS for the aggregated uploads (ISSUE 13): one
            # aggregate slice, the merged contexts' arrows land in it
            with obs_trace.span("aggregate", version=self.round_idx,
                                clients=len(senders)):
                for e in entries[:_FLOW_ENDS_MAX]:
                    if e.get("fid") is not None:
                        obs_trace.flow("upload", e["fid"], "f",
                                       version=self.round_idx)
        obs_flight.record("aggregate", version=self.round_idx,
                          clients=len(senders),
                          taus=[int(e["tau"]) for e in entries])
        self._obs_buffer.set(0)
        self._obs_round_gauge.set(self.round_idx)
        self._obs_k_eff.set(self._k_eff())
        # training-health boundary (ISSUE 15): every version advance is
        # a host boundary — evaluate the armed anomaly rules so a
        # mid-run /metrics scrape carries nidt_alert samples (the chaos
        # smoke asserts this); unarmed processes no-op
        self._observe_health_boundary()
        self._ring[self.round_idx] = self.params
        floor = self.round_idx - self.max_staleness
        for old in [k for k in self._ring if k < floor]:
            del self._ring[old]
        for c, seen in self._contributed.items():
            # versions below the ring can only be stale-dropped now;
            # keeping their dedup marks would grow without bound
            self._contributed[c] = {v for v in seen if v >= floor}
        self.history.append({
            "version": self.round_idx, "clients": len(senders),
            "contributors": senders,
            "taus": [int(e["tau"]) for e in entries],
            "weights": [float(e["weight"]) for e in entries],
            "t": time.monotonic(), **(extra or {})})
        if self.round_idx >= self.comm_round:
            self._broadcast_finish()
            self._done.set()
            # let the selector flush the queued FINISH frames before the
            # shutdown tears the write queues down under them
            drain = getattr(self.com_manager, "drain_sends", None)
            if drain is not None:
                drain(5.0)
            self.finish()

    def _k_eff(self) -> int:
        """Under ``_rlock``: the occupancy threshold that actually
        triggers aggregation. With one buffer slot per sender, a buffer
        can never hold more DISTINCT contributors than the cohort has
        live members — so clients known to be incapable of contributing
        (heartbeat-suspect corpses, quarantined silos) shrink the
        threshold below ``buffer_k`` instead of deadlocking the
        federation waiting for uploads that can never come. Without a
        liveness signal (heartbeats off) a silent corpse is
        indistinguishable from a slow client, exactly like the
        synchronous server without a deadline — arm heartbeats for
        crash tolerance."""
        gone = len(self._suspect | self._quarantined_now())
        return max(1, min(self.buffer_k, self.num_clients - gone))

    def _maybe_complete(self) -> None:
        """No round barrier to complete — but the inherited heartbeat
        monitor calls this when suspicion changes, and a NEW suspect may
        have just lowered ``_k_eff`` below the current buffer occupancy
        (the buffered uploads would otherwise wait for a corpse)."""
        if self._done.is_set() or not self._buffer:
            return
        if len(self._buffer) >= self._k_eff():
            self._aggregate_buffer()

    def _broadcast_finish(self) -> None:
        # only ranks that ever registered expect a FINISH; iterating the
        # full 1..num_clients range would dial thousands of never-seen
        # addresses
        for c in sorted(self._registered):
            self._send_tolerant(M.Message(M.MSG_TYPE_S2C_FINISH, 0, c))

    # ---- audits (the load harness reconciles these) ----

    def upload_audit(self) -> dict:
        """Frame accounting: every received upload is accounted exactly
        once, and every accepted upload is either in a recorded
        aggregation or still buffered — zero lost, zero double-counted."""
        with self._rlock:
            s = dict(self.upload_stats)
            dropped = sum(v for k, v in s.items()
                          if k.startswith("dropped_"))
            aggregated = sum(h["clients"] for h in self.history
                             if "version" in h)
            audit = {
                **s,
                "aggregated": aggregated,
                "buffered": len(self._buffer),
                "received_accounted":
                    s["received"] == s["accepted"] + dropped,
                "accepted_accounted":
                    s["accepted"] == (aggregated + len(self._buffer)
                                      + s["quarantine_discarded"]
                                      + s["aggregation_discarded"]
                                      + s["superseded_in_buffer"]),
            }
        if not (audit["received_accounted"]
                and audit["accepted_accounted"]):
            # a red accounting audit IS the post-mortem trigger (ISSUE
            # 9): the frames the audit cannot reconcile are exactly the
            # decisions the flight ring recorded — dump it while the
            # evidence is fresh (outside _rlock; record/dump take only
            # the recorder's own lock)
            obs_flight.record("audit_failure", version=self.round_idx,
                              audit={k: v for k, v in audit.items()
                                     if isinstance(v, (int, bool))})
            out = obs_flight.dump(reason="upload_audit failure")
            if out:
                log.error("server: upload audit FAILED (%s) - flight "
                          "recorder dumped to %s", audit, out)
            else:
                # no dump path configured (--flight_out unset): the
                # post-mortem must not vanish — put the tail of the
                # ring in the log instead
                evs = obs_flight.events()
                log.error("server: upload audit FAILED (%s) - no "
                          "flight dump path configured; last %d of %d "
                          "flight events: %s", audit, min(20, len(evs)),
                          len(evs), evs[-20:])
        return audit
