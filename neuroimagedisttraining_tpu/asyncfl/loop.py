"""``SelectorCommManager``: one event-loop thread, thousands of sockets.

The thread-per-connection socket core (distributed/comm.py) spends one OS
thread per peer plus one short-lived connection per frame — fine for 21
silos, impossible for a cross-device population. This manager keeps the
exact ``BaseCommManager`` contract (length-prefixed ``Message`` frames,
``byte_stats()`` counters, blocking dispatch via ``QueueDispatchMixin``)
but multiplexes every socket through ONE ``selectors`` event loop:

- **accept** — the listener is non-blocking; accepted connections are
  registered for reads and live until the peer closes them. A legacy
  ``SocketCommManager`` peer that opens a connection, writes one frame
  and closes is served by the same path (read until EOF), so the
  threaded client side plugs in unchanged.
- **read** — per-connection reassembly buffer; every complete frame is
  decoded and enqueued for the dispatch thread. A mid-frame EOF or a
  malformed body drops that frame (logged) and never touches the loop.
  The first frame a peer sends maps its rank to the connection (latest
  connection wins), so replies ride the same socket back — the piece the
  dial-out transport cannot do for peers that listen on nothing.
- **write / backpressure** — ``send_message`` appends whole frames to a
  BOUNDED per-connection write queue and wakes the loop via a self-pipe;
  the loop flushes as the socket drains. A full queue blocks the sender
  (condition wait) until the slow reader catches up or the send timeout
  expires — bytes are never dropped and never interleaved, because the
  loop thread is the only writer on every persistent socket.
- **dial-out fallback** — a receiver with no live inbound connection is
  reached the legacy way (short-lived connection to ``base_port + rank``
  with capped exponential backoff), so this manager is a drop-in server
  core for the existing round-synchronous protocol too.

``FaultyCommManager`` wraps this manager like any other transport (it
only decorates ``send_message`` and the observer path).
"""

from __future__ import annotations

import logging
import selectors
import socket
import struct
import threading
import time
from collections import deque

from neuroimagedisttraining_tpu.distributed.comm import (
    BASE_PORT,
    BaseCommManager,
    QueueDispatchMixin,
)
from neuroimagedisttraining_tpu.distributed.message import (
    ARG_CONN_PERSISTENT,
    Message,
    frame_bytes,
)
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics

log = logging.getLogger("neuroimagedisttraining_tpu.asyncfl")

#: refuse absurd length prefixes (a peer speaking another protocol would
#: otherwise make the loop wait forever for terabytes that never come)
_MAX_FRAME = 1 << 32


class _Conn:
    """Per-connection state owned by the loop thread; the write queue and
    ``open`` flag are shared with senders under the manager's lock."""

    __slots__ = ("sock", "rbuf", "wq", "wq_frames", "rank", "open",
                 "want_write")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rbuf = bytearray()
        #: deque of (memoryview, original frame length); the head may be
        #: a partially-written tail of its frame — kept as a memoryview
        #: so re-queuing the remainder after a partial send is zero-copy
        #: (re-slicing bytes would memcpy O(frame^2/sndbuf) per large
        #: frame to a slow reader, on the one thread every socket shares)
        self.wq: deque[tuple[memoryview, int]] = deque()
        self.wq_frames = 0
        self.rank: int | None = None
        self.open = True
        self.want_write = False


class SelectorCommManager(QueueDispatchMixin, BaseCommManager):
    """Selector-multiplexed manager for one rank (normally the server,
    rank 0). API-compatible with ``SocketCommManager`` including the
    retry keywords on ``send_message``, so every caller in
    ``cross_silo.py`` works unchanged."""

    def __init__(self, rank: int, world_size: int,
                 host_map: dict[int, str] | None = None,
                 base_port: int = BASE_PORT,
                 max_pending_frames: int = 64,
                 send_timeout: float = 30.0):
        self.rank = rank
        self.world_size = world_size
        self.base_port = base_port
        self.host_map = host_map or {}
        self.max_pending_frames = int(max_pending_frames)
        self.send_timeout = float(send_timeout)
        self._init_dispatch()
        #: guards _conns/_by_rank/every write queue; doubles as the
        #: backpressure condition senders wait on
        self._send_lock = threading.Condition()
        self._conns: dict[socket.socket, _Conn] = {}
        self._by_rank: dict[int, _Conn] = {}
        self.peak_connections = 0
        # obs plane (ISSUE 9): the selector loop's own health, published
        # from the loop thread at tick granularity (throttled to one
        # gauge sweep per _OBS_TICK_S — never per event, the loop is the
        # one thread every socket shares) plus a counter senders bump
        # when the bounded write queue blocks them (backpressure stalls
        # are the signal that a reader is slow, the thing the p99
        # version-advance number degrades on first)
        lab = dict(rank=str(rank))
        self._obs_conns = obs_metrics.gauge(
            "nidt_selector_connections",
            "live connections registered with the selector loop",
            labelnames=("rank",)).labels(**lab)
        self._obs_wq_frames = obs_metrics.gauge(
            "nidt_selector_write_queue_frames",
            "frames pending across every persistent write queue",
            labelnames=("rank",)).labels(**lab)
        self._obs_stalls = obs_metrics.counter(
            "nidt_backpressure_stalls_total",
            "sends that blocked on a full per-connection write queue",
            labelnames=("rank",)).labels(**lab)
        self._obs_last_tick = 0.0
        self._running = True
        self._sel = selectors.DefaultSelector()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("0.0.0.0", base_port + rank))
        self._server.listen(1024)
        self._server.setblocking(False)
        self._sel.register(self._server, selectors.EVENT_READ, "accept")
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._loop_thread = threading.Thread(target=self._loop,
                                             daemon=True)
        self._loop_thread.start()

    # ---- event loop (the only thread that touches the selector or
    # writes on persistent sockets) ----

    _OBS_TICK_S = 0.25  # gauge-sweep throttle for the loop thread

    def _obs_tick(self) -> None:
        """Loop-thread tick: refresh the selector-health gauges at most
        every ``_OBS_TICK_S`` — one monotonic read per select wakeup,
        one short ``_send_lock`` hold per tick."""
        now = time.monotonic()
        if now - self._obs_last_tick < self._OBS_TICK_S:
            return
        self._obs_last_tick = now
        with self._send_lock:
            n_conns = len(self._conns)
            wq = sum(c.wq_frames for c in self._conns.values())
        self._obs_conns.set(n_conns)
        self._obs_wq_frames.set(wq)

    def _loop(self) -> None:
        while self._running:
            try:
                events = self._sel.select(timeout=0.5)
            except OSError:
                return  # selector closed during shutdown
            self._obs_tick()
            for key, mask in events:
                if key.data == "accept":
                    self._accept_ready()
                elif key.data == "wake":
                    self._drain_wake()
                else:
                    conn: _Conn = key.data
                    if mask & selectors.EVENT_WRITE:
                        self._flush(conn)
                    if mask & selectors.EVENT_READ and conn.open:
                        self._read_ready(conn)

    def _accept_ready(self) -> None:
        while True:
            try:
                sock, _ = self._server.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            conn = _Conn(sock)
            with self._send_lock:
                self._conns[sock] = conn
                self.peak_connections = max(self.peak_connections,
                                            len(self._conns))
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass
        # senders queued frames since the last pass: express write
        # interest for every connection with pending bytes
        with self._send_lock:
            pending = [c for c in self._conns.values()
                       if c.wq and not c.want_write and c.open]
            for c in pending:
                c.want_write = True
        for c in pending:
            try:
                self._sel.modify(c.sock, selectors.EVENT_READ
                                 | selectors.EVENT_WRITE, c)
            except (KeyError, ValueError, OSError):
                pass  # closed between the lock and here

    def _read_ready(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(1 << 16)
        except BlockingIOError:
            return
        except OSError as e:
            self._close(conn, f"read error: {e}")
            return
        if not data:
            why = (f"EOF mid-frame ({len(conn.rbuf)} buffered bytes "
                   "dropped)" if conn.rbuf else "peer closed")
            self._close(conn, why)
            return
        conn.rbuf += data
        while True:
            if len(conn.rbuf) < 8:
                return
            (length,) = struct.unpack("!Q", bytes(conn.rbuf[:8]))
            if length > _MAX_FRAME:
                self._close(conn, f"insane frame length {length}")
                return
            if len(conn.rbuf) < 8 + length:
                return
            raw = bytes(conn.rbuf[8:8 + length])
            del conn.rbuf[:8 + length]
            try:
                msg = Message.from_bytes(raw)
            except Exception as e:  # noqa: BLE001 — any malformed body
                # (magic mismatch, msgpack OutOfData, schema drift) is a
                # dropped frame, never a dead event loop
                log.warning("rank %d: dropped malformed frame: %s",
                            self.rank, e)
                continue
            self._count_recv(length + 8)
            with self._send_lock:
                conn.rank = msg.sender_id
                if msg.get(ARG_CONN_PERSISTENT):
                    # the peer promises to keep this connection open:
                    # replies to its rank ride it back (latest wins —
                    # a rejoined client's fresh connection supersedes
                    # its corpse). Legacy one-frame-per-connection
                    # peers never set the flag and are reached by
                    # dial-out instead.
                    self._by_rank[msg.sender_id] = conn
            self._enqueue(msg)

    def _flush(self, conn: _Conn) -> None:
        with self._send_lock:
            while conn.wq:
                buf, frame_len = conn.wq[0]
                try:
                    n = conn.sock.send(buf)
                except BlockingIOError:
                    break
                except OSError as e:
                    self._close_locked(conn, f"write error: {e}")
                    self._sel_unregister(conn)
                    return
                if n < len(buf):
                    conn.wq[0] = (buf[n:], frame_len)
                    break
                conn.wq.popleft()
                conn.wq_frames -= 1
                self._count_sent(frame_len)
            drained = not conn.wq
            if drained:
                conn.want_write = False
            self._send_lock.notify_all()  # backpressure release
        if drained:
            try:
                self._sel.modify(conn.sock, selectors.EVENT_READ, conn)
            except (KeyError, ValueError, OSError):
                pass

    def _close_locked(self, conn: _Conn, why: str) -> None:
        """Under ``_send_lock``: drop a connection's shared state and
        wake any sender blocked on its queue."""
        if not conn.open:
            return
        conn.open = False
        self._conns.pop(conn.sock, None)  # nidt: allow[lock-shared-map] -- every caller holds _send_lock (method contract in the docstring); the lock cannot be re-taken here without deadlocking
        if conn.rank is not None and \
                self._by_rank.get(conn.rank) is conn:
            self._by_rank.pop(conn.rank, None)
        if conn.wq_frames and self._running:
            log.warning("rank %d: closing conn to rank %s with %d "
                        "unflushed frames (%s)", self.rank, conn.rank,
                        conn.wq_frames, why)
        else:
            log.debug("rank %d: conn to rank %s closed (%s)", self.rank,
                      conn.rank, why)
        self._send_lock.notify_all()

    def _sel_unregister(self, conn: _Conn) -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def _close(self, conn: _Conn, why: str) -> None:
        with self._send_lock:
            self._close_locked(conn, why)
        self._sel_unregister(conn)

    # ---- send side (any thread) ----

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\0")  # nidt: allow[lock-send] -- 1-byte self-pipe nudge; the pipe has exactly one writer semantic-free byte stream
        except (BlockingIOError, OSError):
            pass  # pipe full: the loop is already scheduled to wake

    def send_message(self, msg: Message, retries: int = 7,
                     retry_delay: float = 0.1,
                     max_delay: float = 2.0) -> None:
        """Route one frame. A live inbound connection from the receiver
        carries it back (bounded queue, blocking backpressure); otherwise
        fall back to the legacy dial-out (same retry semantics as
        ``SocketCommManager.send_message``, so round-synchronous callers
        and their error handling work unchanged)."""
        frame = frame_bytes(msg)
        deadline = None
        with self._send_lock:
            conn = self._by_rank.get(msg.receiver_id)
            while (conn is not None and conn.open and self._running
                   and conn.wq_frames >= self.max_pending_frames):
                if deadline is None:
                    deadline = time.monotonic() + self.send_timeout
                    # counted ONCE per stalled send, on entry — the
                    # wait loop below may spin many times per stall
                    self._obs_stalls.inc()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConnectionError(
                        f"rank {self.rank}: send to rank "
                        f"{msg.receiver_id} timed out after "
                        f"{self.send_timeout}s of backpressure "
                        f"({conn.wq_frames} frames pending)")
                self._send_lock.wait(min(remaining, 0.5))
                conn = self._by_rank.get(msg.receiver_id)
            if conn is not None and conn.open and self._running:
                conn.wq.append((memoryview(frame), len(frame)))
                conn.wq_frames += 1
                self._wake()
                return
        self._dial_out(msg, frame, retries, retry_delay, max_delay)

    def _dial_out(self, msg: Message, frame: bytes, retries: int,
                  retry_delay: float, max_delay: float) -> None:
        host = self.host_map.get(msg.receiver_id, "127.0.0.1")
        addr = (host, self.base_port + msg.receiver_id)
        last_err: Exception | None = None
        for attempt in range(retries):
            try:
                with socket.create_connection(addr, timeout=10.0) as conn:
                    conn.sendall(frame)  # nidt: allow[lock-send] -- fresh per-frame connection local to this call; no concurrent writer exists
                self._count_sent(len(frame))
                return
            except OSError as e:
                last_err = e
                if attempt + 1 < retries:
                    time.sleep(min(max_delay,
                                   retry_delay * (2.0 ** attempt)))
        raise ConnectionError(
            f"rank {self.rank} could not reach rank {msg.receiver_id} "
            f"at {addr} (no live inbound connection either): {last_err}")

    # ---- lifecycle ----

    def connection_count(self) -> int:
        with self._send_lock:
            return len(self._conns)

    def drain_sends(self, timeout: float = 5.0) -> bool:
        """Block until every persistent write queue has flushed (or
        ``timeout``). Callers about to stop the manager use this so a
        just-broadcast frame (e.g. FINISH to a thousand clients) is not
        torn out of the queues by the shutdown."""
        deadline = time.monotonic() + timeout
        with self._send_lock:
            while any(c.wq for c in self._conns.values()):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._send_lock.wait(min(remaining, 0.2))
        return True

    def stop_receive_message(self) -> None:
        self._running = False
        self._wake()
        self._loop_thread.join(timeout=5.0)
        with self._send_lock:
            conns = list(self._conns.values())
            for c in conns:
                self._close_locked(c, "manager stopped")
        for c in conns:
            self._sel_unregister(c)
        for s in (self._server, self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        try:
            self._sel.close()
        except OSError:
            pass
        self._stop_dispatch()
