"""``SelectorCommManager``: one event-loop thread, thousands of sockets.

The thread-per-connection socket core (distributed/comm.py) spends one OS
thread per peer plus one short-lived connection per frame — fine for 21
silos, impossible for a cross-device population. This manager keeps the
exact ``BaseCommManager`` contract (length-prefixed ``Message`` frames,
``byte_stats()`` counters, blocking dispatch via ``QueueDispatchMixin``)
but multiplexes every socket through ONE ``selectors`` event loop:

- **accept** — the listener is non-blocking; accepted connections are
  registered for reads and live until the peer closes them. A legacy
  ``SocketCommManager`` peer that opens a connection, writes one frame
  and closes is served by the same path (read until EOF), so the
  threaded client side plugs in unchanged.
- **read** — per-connection reassembly buffer; every complete frame is
  decoded and enqueued for the dispatch thread. A mid-frame EOF or a
  malformed body drops that frame (logged) and never touches the loop.
  The first frame a peer sends maps its rank to the connection (latest
  connection wins), so replies ride the same socket back — the piece the
  dial-out transport cannot do for peers that listen on nothing.
- **write / backpressure** — ``send_message`` appends whole frames to a
  BOUNDED per-connection write queue and wakes the loop via a self-pipe;
  the loop flushes as the socket drains. A full queue blocks the sender
  (condition wait) until the slow reader catches up or the send timeout
  expires — bytes are never dropped and never interleaved, because the
  loop thread is the only writer on every persistent socket.
- **dial-out fallback** — a receiver with no live inbound connection is
  reached the legacy way (short-lived connection to ``base_port + rank``
  with capped exponential backoff), so this manager is a drop-in server
  core for the existing round-synchronous protocol too.

``FaultyCommManager`` wraps this manager like any other transport (it
only decorates ``send_message`` and the observer path).
"""

from __future__ import annotations

import logging
import selectors
import socket
import struct
import threading
import time
from collections import deque

from neuroimagedisttraining_tpu.distributed.comm import (
    BASE_PORT,
    BaseCommManager,
    QueueDispatchMixin,
)
from neuroimagedisttraining_tpu.distributed.message import (
    ARG_CONN_PERSISTENT,
    Message,
    frame_bytes,
)
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as obs_names

log = logging.getLogger("neuroimagedisttraining_tpu.asyncfl")

#: refuse absurd length prefixes (a peer speaking another protocol would
#: otherwise make the loop wait forever for terabytes that never come)
_MAX_FRAME = 1 << 32


class _Conn:
    """Per-connection state owned by the loop thread; the write queue and
    ``open`` flag are shared with senders under the manager's lock."""

    __slots__ = ("sock", "rbuf", "wq", "wq_frames", "rank", "open",
                 "want_write")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rbuf = bytearray()
        #: deque of (memoryview, original frame length); the head may be
        #: a partially-written tail of its frame — kept as a memoryview
        #: so re-queuing the remainder after a partial send is zero-copy
        #: (re-slicing bytes would memcpy O(frame^2/sndbuf) per large
        #: frame to a slow reader, on the one thread every socket shares)
        self.wq: deque[tuple[memoryview, int]] = deque()
        self.wq_frames = 0
        self.rank: int | None = None
        self.open = True
        self.want_write = False


class SelectorCommManager(QueueDispatchMixin, BaseCommManager):
    """Selector-multiplexed manager for one rank (normally the server,
    rank 0). API-compatible with ``SocketCommManager`` including the
    retry keywords on ``send_message``, so every caller in
    ``cross_silo.py`` works unchanged."""

    def __init__(self, rank: int, world_size: int,
                 host_map: dict[int, str] | None = None,
                 base_port: int = BASE_PORT,
                 max_pending_frames: int = 64,
                 send_timeout: float = 30.0,
                 reuse_port: bool = False,
                 inline_dispatch: bool = False):
        self.rank = rank
        self.world_size = world_size
        self.base_port = base_port
        self.host_map = host_map or {}
        self.max_pending_frames = int(max_pending_frames)
        self.send_timeout = float(send_timeout)
        #: inline_dispatch runs observers ON the loop thread instead of
        #: handing each frame to the dispatch thread over the queue.
        #: Every cross-thread handoff is a futex wakeup — a SYSCALL, ~1
        #: ms in sandboxed kernels, two per frame round trip — so a
        #: server whose per-frame work is small and bounded (the ingest
        #: worker's admission+fold, ~0.3 ms) roughly doubles its
        #: throughput by staying on the loop thread. Servers with heavy
        #: per-frame work (the buffered server's jitted aggregation)
        #: MUST keep the queue: inline observers stall every socket the
        #: loop owns for as long as they run.
        self._inline = bool(inline_dispatch)
        self._init_dispatch()
        #: guards _conns/_by_rank/every write queue; doubles as the
        #: backpressure condition senders wait on
        self._send_lock = threading.Condition()
        #: True while a self-pipe wake byte is in flight (under
        #: _send_lock): senders skip the wake SYSCALL when one is
        #: already pending — a socket send costs ~1 ms in sandboxed
        #: kernels, and per-frame nudges were the measured choke of the
        #: reply path at 1k-client upload rates
        self._wake_armed = False
        self._conns: dict[socket.socket, _Conn] = {}
        self._by_rank: dict[int, _Conn] = {}
        self.peak_connections = 0
        # obs plane (ISSUE 9): the selector loop's own health, published
        # from the loop thread at tick granularity (throttled to one
        # gauge sweep per _OBS_TICK_S — never per event, the loop is the
        # one thread every socket shares) plus a counter senders bump
        # when the bounded write queue blocks them (backpressure stalls
        # are the signal that a reader is slow, the thing the p99
        # version-advance number degrades on first)
        lab = dict(rank=str(rank))
        self._obs_conns = obs_metrics.gauge(
            obs_names.SELECTOR_CONNECTIONS,
            "live connections registered with the selector loop",
            labelnames=("rank",)).labels(**lab)
        self._obs_wq_frames = obs_metrics.gauge(
            obs_names.SELECTOR_WRITE_QUEUE,
            "frames pending across every persistent write queue",
            labelnames=("rank",)).labels(**lab)
        self._obs_stalls = obs_metrics.counter(
            obs_names.BACKPRESSURE_STALLS,
            "sends that blocked on a full per-connection write queue",
            labelnames=("rank",)).labels(**lab)
        self._obs_last_tick = 0.0
        self._running = True
        self._sel = selectors.DefaultSelector()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuse_port:
            # sharded ingest plane (asyncfl/ingest.py): N worker
            # processes bind the SAME port and the kernel hash-balances
            # incoming connections across their listeners — a client's
            # persistent connection therefore has a stable worker
            # affinity for its whole lifetime
            self._server.setsockopt(socket.SOL_SOCKET,
                                    socket.SO_REUSEPORT, 1)
        self._server.bind(("0.0.0.0", base_port + rank))
        self._server.listen(1024)
        self._server.setblocking(False)
        self._sel.register(self._server, selectors.EVENT_READ, "accept")
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._loop_thread = threading.Thread(target=self._loop,
                                             daemon=True)
        self._loop_thread.start()

    # ---- event loop (the only thread that touches the selector or
    # writes on persistent sockets) ----

    _OBS_TICK_S = 0.25  # gauge-sweep throttle for the loop thread

    def _obs_tick(self) -> None:
        """Loop-thread tick: refresh the selector-health gauges at most
        every ``_OBS_TICK_S`` — one monotonic read per select wakeup,
        one short ``_send_lock`` hold per tick."""
        now = time.monotonic()
        if now - self._obs_last_tick < self._OBS_TICK_S:
            return
        self._obs_last_tick = now
        with self._send_lock:
            n_conns = len(self._conns)
            wq = sum(c.wq_frames for c in self._conns.values())
        self._obs_conns.set(n_conns)
        self._obs_wq_frames.set(wq)

    def _loop(self) -> None:
        while self._running:
            try:
                events = self._sel.select(timeout=0.5)
            except OSError:
                return  # selector closed during shutdown
            self._obs_tick()
            for key, mask in events:
                if key.data == "accept":
                    self._accept_ready()
                elif key.data == "wake":
                    self._drain_wake()
                else:
                    conn: _Conn = key.data
                    if mask & selectors.EVENT_WRITE:
                        self._flush(conn)
                    if mask & selectors.EVENT_READ and conn.open:
                        self._read_ready(conn)

    def _accept_ready(self) -> None:
        while True:
            try:
                sock, _ = self._server.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            conn = _Conn(sock)
            with self._send_lock:
                self._conns[sock] = conn
                self.peak_connections = max(self.peak_connections,
                                            len(self._conns))
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass
        # senders queued frames since the last pass: express write
        # interest for every connection with pending bytes
        with self._send_lock:
            self._wake_armed = False
            pending = [c for c in self._conns.values()
                       if c.wq and not c.want_write and c.open]
            for c in pending:
                c.want_write = True
        for c in pending:
            try:
                self._sel.modify(c.sock, selectors.EVENT_READ
                                 | selectors.EVENT_WRITE, c)
            except (KeyError, ValueError, OSError):
                pass  # closed between the lock and here

    def _read_ready(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(1 << 16)
        except BlockingIOError:
            return
        except OSError as e:
            self._close(conn, f"read error: {e}")
            return
        if not data:
            why = (f"EOF mid-frame ({len(conn.rbuf)} buffered bytes "
                   "dropped)" if conn.rbuf else "peer closed")
            self._close(conn, why)
            return
        conn.rbuf += data
        while True:
            if len(conn.rbuf) < 8:
                return
            (length,) = struct.unpack("!Q", bytes(conn.rbuf[:8]))
            if length > _MAX_FRAME:
                self._close(conn, f"insane frame length {length}")
                return
            if len(conn.rbuf) < 8 + length:
                return
            raw = bytes(conn.rbuf[8:8 + length])
            del conn.rbuf[:8 + length]
            try:
                msg = Message.from_bytes(raw)
            except Exception as e:  # noqa: BLE001 — any malformed body
                # (magic mismatch, msgpack OutOfData, schema drift) is a
                # dropped frame, never a dead event loop
                log.warning("rank %d: dropped malformed frame: %s",
                            self.rank, e)
                continue
            self._count_recv(length + 8)
            # queue-stage anchor (ISSUE 13): the nidt_upload_stage_ms
            # "queue" stage is handler-start minus this read-completion
            # stamp — with inline dispatch it measures the frame loop's
            # own backlog, with queued dispatch the handoff wait
            msg.recv_ns = time.perf_counter_ns()
            with self._send_lock:
                conn.rank = msg.sender_id
                if msg.get(ARG_CONN_PERSISTENT):
                    # the peer promises to keep this connection open:
                    # replies to its rank ride it back (latest wins —
                    # a rejoined client's fresh connection supersedes
                    # its corpse). Legacy one-frame-per-connection
                    # peers never set the flag and are reached by
                    # dial-out instead.
                    self._by_rank[msg.sender_id] = conn
            self._deliver(msg)

    def _deliver(self, msg: Message) -> None:
        if not self._inline:
            self._enqueue(msg)
            return
        # inline mode: observers run here, on the loop thread — no
        # queue handoff, no futex wakeup. An observer failure is a
        # dropped frame, never a dead event loop (the dispatch-thread
        # contract, kept).
        try:
            for obs in list(self._observers):
                obs.receive_message(msg.msg_type, msg)
        except Exception:  # noqa: BLE001 — see above
            log.exception("rank %s: inline observer failed on %s",
                          self.rank, msg.msg_type)

    def _flush(self, conn: _Conn) -> None:
        while True:
            with self._send_lock:
                if not conn.wq:
                    conn.want_write = False
                    self._send_lock.notify_all()  # backpressure release
                    break
                buf, frame_len = conn.wq[0]
            # the send SYSCALL runs outside the lock (it costs ~1 ms in
            # sandboxed kernels, and every sender in the process would
            # queue-wait behind it); only this loop thread ever pops wq
            # or closes conns, so the head reference stays valid between
            # the two holds and senders only ever append on the right
            try:
                n = conn.sock.send(buf)  # nidt: allow[lock-send] -- non-blocking; only the loop thread (this one) ever writes a persistent socket or pops wq, so no concurrent writer can interleave mid-frame
            except BlockingIOError:
                return
            except OSError as e:
                self._close(conn, f"write error: {e}")
                return
            with self._send_lock:
                if n < len(buf):
                    conn.wq[0] = (buf[n:], frame_len)
                    return
                conn.wq.popleft()
                conn.wq_frames -= 1
                self._count_sent(frame_len)
                self._send_lock.notify_all()  # backpressure release
        try:
            self._sel.modify(conn.sock, selectors.EVENT_READ, conn)
        except (KeyError, ValueError, OSError):
            pass

    def _close_locked(self, conn: _Conn, why: str) -> None:
        """Under ``_send_lock``: drop a connection's shared state and
        wake any sender blocked on its queue."""
        if not conn.open:
            return
        conn.open = False
        self._conns.pop(conn.sock, None)  # nidt: allow[lock-shared-map] -- every caller holds _send_lock (method contract in the docstring); the lock cannot be re-taken here without deadlocking
        if conn.rank is not None and \
                self._by_rank.get(conn.rank) is conn:
            self._by_rank.pop(conn.rank, None)
        if conn.wq_frames and self._running:
            log.warning("rank %d: closing conn to rank %s with %d "
                        "unflushed frames (%s)", self.rank, conn.rank,
                        conn.wq_frames, why)
        else:
            log.debug("rank %d: conn to rank %s closed (%s)", self.rank,
                      conn.rank, why)
        self._send_lock.notify_all()

    def _sel_unregister(self, conn: _Conn) -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def _close(self, conn: _Conn, why: str) -> None:
        with self._send_lock:
            self._close_locked(conn, why)
        self._sel_unregister(conn)

    # ---- send side (any thread) ----

    def _wake(self) -> None:
        """One self-pipe nudge per loop wakeup, not per queued frame:
        the armed flag dedups wake bytes, and ``_drain_wake`` re-arms
        BEFORE it collects pending writers — a frame queued after the
        collection always sees the flag down and sends a fresh byte, so
        no wakeup is ever lost."""
        with self._send_lock:
            if self._wake_armed:
                return
            self._wake_armed = True
        try:
            self._wake_w.send(b"\0")  # nidt: allow[lock-send] -- 1-byte self-pipe nudge; the pipe has exactly one writer semantic-free byte stream
        except (BlockingIOError, OSError):
            pass  # pipe full: the loop is already scheduled to wake

    def send_message(self, msg: Message, retries: int = 7,
                     retry_delay: float = 0.1,
                     max_delay: float = 2.0) -> None:
        """Route one frame. A live inbound connection from the receiver
        carries it back (bounded queue, blocking backpressure); otherwise
        fall back to the legacy dial-out (same retry semantics as
        ``SocketCommManager.send_message``, so round-synchronous callers
        and their error handling work unchanged)."""
        frame = frame_bytes(msg)
        deadline = None
        with self._send_lock:
            conn = self._by_rank.get(msg.receiver_id)
            while (conn is not None and conn.open and self._running
                   and conn.wq_frames >= self.max_pending_frames):
                if threading.get_ident() == self._loop_thread.ident:
                    # inline observers send from the loop thread — the
                    # thread that IS the flusher. Blocking here would
                    # deadlock every socket for send_timeout; a full
                    # queue to a non-draining reader drops the frame
                    # loudly instead (the peer re-syncs on its next
                    # upload).
                    raise ConnectionError(
                        f"rank {self.rank}: write queue to rank "
                        f"{msg.receiver_id} full ({conn.wq_frames} "
                        "frames) on the loop thread; dropping rather "
                        "than deadlocking the flusher")
                if deadline is None:
                    deadline = time.monotonic() + self.send_timeout
                    # counted ONCE per stalled send, on entry — the
                    # wait loop below may spin many times per stall
                    self._obs_stalls.inc()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConnectionError(
                        f"rank {self.rank}: send to rank "
                        f"{msg.receiver_id} timed out after "
                        f"{self.send_timeout}s of backpressure "
                        f"({conn.wq_frames} frames pending)")
                self._send_lock.wait(min(remaining, 0.5))
                conn = self._by_rank.get(msg.receiver_id)
            if conn is not None and conn.open and self._running:
                on_loop = (threading.get_ident()
                           == self._loop_thread.ident)
                if on_loop and not conn.wq:
                    # optimistic inline send (the asyncio-transport
                    # idiom): the ping-pong common case is a writable
                    # socket and an empty queue — ONE send syscall, no
                    # wake pipe, no epoll re-arm, no flush pass. Only
                    # the loop thread may touch the socket directly;
                    # with wq empty there is no partial frame to
                    # interleave with.
                    try:
                        n = conn.sock.send(frame)  # nidt: allow[lock-send] -- non-blocking socket, loop thread owns it; the blocking path below is the lint's target
                    except (BlockingIOError, InterruptedError):
                        n = 0
                    except OSError as e:
                        self._close_locked(conn, f"write error: {e}")
                        self._sel_unregister(conn)
                        return
                    if n == len(frame):
                        self._count_sent(len(frame))
                        return
                    conn.wq.append((memoryview(frame)[n:], len(frame)))
                    conn.wq_frames += 1
                else:
                    conn.wq.append((memoryview(frame), len(frame)))
                    conn.wq_frames += 1
                if on_loop:
                    # the loop thread owns the selector: arm write
                    # interest directly instead of nudging itself
                    # through the wake pipe
                    if not conn.want_write:
                        conn.want_write = True
                        try:
                            self._sel.modify(
                                conn.sock, selectors.EVENT_READ
                                | selectors.EVENT_WRITE, conn)
                        except (KeyError, ValueError, OSError):
                            pass
                else:
                    self._wake()
                return
        self._dial_out(msg, frame, retries, retry_delay, max_delay)

    def _dial_out(self, msg: Message, frame: bytes, retries: int,
                  retry_delay: float, max_delay: float) -> None:
        host = self.host_map.get(msg.receiver_id, "127.0.0.1")
        addr = (host, self.base_port + msg.receiver_id)
        last_err: Exception | None = None
        for attempt in range(retries):
            try:
                with socket.create_connection(addr, timeout=10.0) as conn:
                    conn.sendall(frame)  # nidt: allow[lock-send] -- fresh per-frame connection local to this call; no concurrent writer exists
                self._count_sent(len(frame))
                return
            except OSError as e:
                last_err = e
                if attempt + 1 < retries:
                    time.sleep(min(max_delay,
                                   retry_delay * (2.0 ** attempt)))
        raise ConnectionError(
            f"rank {self.rank} could not reach rank {msg.receiver_id} "
            f"at {addr} (no live inbound connection either): {last_err}")

    # ---- lifecycle ----

    def connection_count(self) -> int:
        with self._send_lock:
            return len(self._conns)

    def drain_sends(self, timeout: float = 5.0) -> bool:
        """Block until every persistent write queue has flushed (or
        ``timeout``). Callers about to stop the manager use this so a
        just-broadcast frame (e.g. FINISH to a thousand clients) is not
        torn out of the queues by the shutdown."""
        deadline = time.monotonic() + timeout
        with self._send_lock:
            while any(c.wq for c in self._conns.values()):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._send_lock.wait(min(remaining, 0.2))
        return True

    def stop_receive_message(self) -> None:
        self._running = False
        self._wake()
        self._loop_thread.join(timeout=5.0)
        with self._send_lock:
            conns = list(self._conns.values())
            for c in conns:
                self._close_locked(c, "manager stopped")
        for c in conns:
            self._sel_unregister(c)
        for s in (self._server, self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        try:
            self._sel.close()
        except OSError:
            pass
        self._stop_dispatch()
