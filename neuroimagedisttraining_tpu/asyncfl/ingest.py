"""Sharded ingest plane: N selector worker processes, one root server.

The async control plane (asyncfl/loop.py + server.py) holds 1,000
concurrent clients on ONE selector thread, but a single Python process
GIL-saturates near ~250 sustained uploads/s on this box
(bench_matrix/async_bench.json) — decode, admission, and reply
serialization all fight for one interpreter. ROADMAP item 3(a) names the
fix and this module builds it:

- **N worker processes, one port.** Every worker binds the SAME listen
  port with ``SO_REUSEPORT`` (asyncfl/loop.py) and runs the existing
  ``SelectorCommManager`` frame loop; the kernel hash-balances incoming
  connections across the listeners, so a client's persistent connection
  has a stable worker AFFINITY for its lifetime. Workers decode uploads
  (wire codec against their version ring), run the admission gates, and
  FOLD accepted uploads into a local partial aggregate; only partials
  and tiny verdict events cross to the root over a pipe.
- **One commutative merge algebra.** ``privacy/secure_quant.py``'s
  slot-major int64 fold is ALREADY the right merge algebra (FedBuff
  frames server concurrency as the scaling knob; Bonawitz-scale fan-in
  demands bounded per-node work), so the sharded plane speaks it for
  BOTH paths: a ``--secure_quant`` worker folds field-element frames
  into a ``SlotAccumulator`` and exports center-lifted int64 totals
  (``export_centered``); a dense worker quantizes each delta-transported
  upload into the same fixed-point int64 lattice (``FoldSpec``) and
  folds directly. Integer addition is exact, commutative and
  associative, so the root's merge — partials combined in worker-id
  order — is BITWISE equal to folding every upload in one process, for
  any worker count and any partitioning (pinned in tests/test_ingest.py,
  dense and secure). Float summation could never give that invariant:
  its reduction tree changes with the partitioning.
- **Admission state placement.** Per-sender state (upload-seq
  watermarks, the legacy per-version dedup marks) partitions cleanly by
  connection affinity: a transport re-delivery arrives on the SAME
  connection (same worker), and a reconnect — the only way to move
  workers — re-registers, which resets the watermark exactly as the
  single-process server does. Version/staleness gates run against the
  worker's ring, which the root advances over the pipe (a worker can lag
  the root by the pipe latency; a FUTURE-tagged upload in that window is
  dropped and the sender immediately re-synced — the same verdict the
  single-process gate renders, liveness unaffected). Global state —
  registration, heartbeats/suspicion, aggregation triggering, the
  version counter, the accounting audit — lives at the root, fed by
  per-upload verdict events.
- **Audit extension.** Worker verdicts stream to the root in BATCHES
  (``VERDICT_BATCH_MAX`` or ``VERDICT_BATCH_AGE_S``, whichever first —
  one pipe message per ~64 uploads keeps the root's fan-in cost off the
  per-upload path), and every batch is flushed BEFORE the partial that
  contains its uploads (one pipe, FIFO, one worker-side lock ordering
  fold, batch, and export), so ``upload_audit()`` reconciles across
  workers exactly as in-process: received == accepted + dropped, and
  accepted == aggregated + still-buffered-at-workers +
  ``lost_with_worker`` (uploads a SIGKILLed worker accepted but never
  shipped — counted, never silently vanished; the kill-one-worker chaos
  case pins the audit green).

What does NOT compose (rejected at startup, the privacy-plane matrix
pattern): server-side defenses and quarantine — the root merges
pre-folded partials and never sees per-client uploads, so there is
nothing to order-select or outlier-score (the same structural reason
the buffered secure path rejects them); use the single-process plane or
client-side clipping. The one-slot-per-sender supersede rule is also
out: a folded entry cannot be un-folded, so the sharded buffer is the
plain FedBuff shape (every accepted upload contributes once).

Reply protocol: every upload is still answered immediately, but a
reply at an UNCHANGED version omits the model body (the sender already
holds that exact tree; ``FedAvgClientProc`` reuses its cached sync) —
at cross-device scale the redundant downlink bodies, not the uploads,
are the bandwidth bill.

Numerics: the dense fold quantizes at ``2^-frac_bits`` absolute
resolution (default 2^-20 — at the f32 epsilon scale for O(1) model
values) and fixed-point weights at ``2^-weight_frac_bits`` relative;
``make_fold_spec`` validates single-upload headroom at startup and the
root re-checks total mass before every merge (a violation discards the
buffer with ``aggregation_discarded``, never wraps silently). Secure
partials chunk-lift inside the worker before the folded weight mass
can leave the field's centered range, so even small fields never wrap
on honest values.

Measured: scripts/run_ingest_bench.sh -> bench_matrix/ingest_bench.json
(sustained accepted uploads/s at N in {1, 2, 4} workers vs the
single-process ``BufferedFedAvgServer`` baseline on the same box, all
audits green).
"""

from __future__ import annotations

import dataclasses
import logging
import multiprocessing as mp
import sys
import threading
import time
from typing import Any

import numpy as np

from neuroimagedisttraining_tpu.asyncfl.server import (
    BufferedFedAvgServer,
    staleness_weight,
)
from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.comm import (
    BASE_PORT,
    BaseCommManager,
    Observer,
    QueueDispatchMixin,
)
from neuroimagedisttraining_tpu.obs import fanin as obs_fanin
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.obs import names as obs_names
from neuroimagedisttraining_tpu.obs import rules as obs_rules

log = logging.getLogger("neuroimagedisttraining_tpu.asyncfl")

PyTree = Any

#: dense fixed-point fraction bits: value resolution 2^-20 absolute,
#: around the float32 epsilon for O(1) model parameters
INGEST_FRAC_BITS = 20
#: fixed-point bits for the integer fold weights (relative resolution
#: ~2^-10 on the staleness-discounted sample-count weights)
INGEST_WEIGHT_FRAC_BITS = 10
#: int64 totals must stay provably exact: the root refuses to merge a
#: buffer whose weight mass could push any coordinate past this
_INT64_SAFE = 1 << 62
#: verdict events are BATCHED worker-side (one pipe message per ~batch,
#: not per upload): at 1k+ uploads/s the root's per-event pipe recv +
#: counter work was the measured choke on this box — batching moves the
#: fan-in cost off the per-upload path on BOTH ends of the pipe
VERDICT_BATCH_MAX = 64
#: a partially-filled batch never ages past this before flushing, so
#: the root's pending count (and the harvest trigger riding it) lags
#: the workers by at most one poll tick
VERDICT_BATCH_AGE_S = 0.05
#: flow-END events emitted per merged aggregation (ISSUE 13): enough
#: to causally link a representative set of uploads in the merged
#: trace without the event volume scaling with buffer_k
_FLOW_ENDS_MAX = 64


# ---------------------------------------------------------------------------
# fold algebra (shared by workers, the root merge, and the test replays)
# ---------------------------------------------------------------------------


def _named_leaves(tree: PyTree):
    from neuroimagedisttraining_tpu.codec.wire import _named_leaves as nl

    return nl(tree)


def _rebuild_like(template: PyTree, by_name: dict):
    from neuroimagedisttraining_tpu.codec.wire import _rebuild_like as rl

    return rl(template, by_name)


@dataclasses.dataclass(frozen=True)
class FoldSpec:
    """Geometry of the sharded fold — every worker and the root must
    hold the identical spec (it ships once, at worker spawn).

    ``quant`` is None for the dense int64 lattice or the
    ``privacy.QuantSpec`` of the secure field; ``value_bound`` is the
    per-coordinate magnitude the headroom math assumes (honest updates
    stay inside it; violations saturate sign-preservingly on the dense
    path and lean on the documented field margin on the secure path —
    the same contract as privacy/secure_quant.py); ``weight_ref``
    normalizes the staleness-discounted sample-count weights so typical
    weights land near 1.0 before fixed-point scaling."""

    frac_bits: int = INGEST_FRAC_BITS
    weight_frac_bits: int = INGEST_WEIGHT_FRAC_BITS
    value_bound: float = 16.0
    weight_ref: float = 32.0
    weight_cap: int = 1 << 20
    quant: Any = None  # privacy.QuantSpec | None

    # -- derived bounds --

    @property
    def q_max(self) -> int:
        """Per-coordinate magnitude bound of one folded upload, in
        lattice units (the dense clamp edge / the secure per-chunk
        aggregate bound)."""
        if self.quant is not None:
            return int(self.quant.p // 2)
        return int(round(self.value_bound * (1 << self.frac_bits)))

    @property
    def chunk_capacity(self) -> float:
        """Secure path: the fold weight mass one ``SlotAccumulator``
        chunk can hold before the aggregate could leave the field's
        centered range — the worker lifts the chunk into plain int64
        totals (``export_centered``) before crossing it."""
        assert self.quant is not None
        return (self.quant.p // 2) / (
            self.value_bound * (1 << self.quant.frac_bits))

    def weight_int(self, n: float, tau: int, alpha: float) -> int:
        """The integer fold weight of one accepted upload — a pure
        function of (n, tau, alpha), so it is identical no matter which
        worker folds the upload (the partition-independence the bitwise
        merge pin rests on). Ratios are preserved to ~2^-weight_frac_bits
        relative; weights below the lattice floor round up to 1 (an
        admitted upload never folds at zero) and weights above
        ``weight_cap`` saturate (documented, like value saturation)."""
        w = staleness_weight(n, tau, alpha) / self.weight_ref
        return int(min(self.weight_cap,
                       max(1, int(round(w * (1 << self.weight_frac_bits))))))

    def mass_bound(self) -> int:
        """Total integer weight mass one MERGED aggregation may hold
        with int64 exactness guaranteed; the root checks it before
        every merge."""
        if self.quant is not None:
            per = self.value_bound * (1 << self.quant.frac_bits)
        else:
            per = float(self.q_max)
        return int(_INT64_SAFE // max(1, int(per)))


def make_fold_spec(init_params: PyTree, quant=None,
                   weight_ref: float = 32.0,
                   frac_bits: int = INGEST_FRAC_BITS) -> FoldSpec:
    """Build + validate the run's fold geometry at STARTUP (never
    mid-run): the value bound starts from the init model's actual leaf
    magnitudes doubled for drift (the async secure-path precedent —
    BatchNorm raw-moment leaves dwarf any fixed constant), and the
    single-upload headroom (weight cap x value bound) must leave the
    int64 lattice room for thousands of uploads."""
    import jax

    init_mag = max((float(np.max(np.abs(np.asarray(x, np.float64))))
                    for x in jax.tree.leaves(init_params)
                    if np.asarray(x).size), default=0.0)
    value_bound = max(16.0, 2.0 * init_mag)
    if weight_ref <= 0:
        raise ValueError(f"ingest weight_ref must be > 0, got {weight_ref}")
    weight_cap = 1 << 20
    if quant is not None:
        cap = (quant.p // 2) / (value_bound * (1 << quant.frac_bits))
        # one upload must fit a chunk with room for at least 8 peers,
        # or every fold would lift a chunk (correct but pathological)
        weight_cap = int(cap / 8.0)
        if weight_cap < 1 << INGEST_WEIGHT_FRAC_BITS:
            raise ValueError(
                f"secure_quant field too small for the sharded ingest "
                f"fold at value bound {value_bound:.0f}: chunk capacity "
                f"{cap:.1f} weight units cannot resolve weight ratios "
                f"at {INGEST_WEIGHT_FRAC_BITS} fraction bits — raise "
                "--secure_quant_field_bits (32 recommended) or lower "
                "--secure_quant_frac_bits")
    spec = FoldSpec(frac_bits=int(frac_bits),
                    value_bound=float(value_bound),
                    weight_ref=float(weight_ref),
                    weight_cap=int(weight_cap), quant=quant)
    if spec.weight_cap * spec.q_max >= _INT64_SAFE:
        raise ValueError(
            f"ingest fold headroom exceeded: one upload at weight cap "
            f"{spec.weight_cap} x value range {spec.q_max} leaves no "
            f"int64 margin — lower frac_bits ({frac_bits}) or the "
            f"weight cap")
    return spec


class PartialAccumulator:
    """One process's partial aggregate: plain int64 totals + the
    integer weight mass. ``fold_dense`` quantizes a decoded upload
    into the lattice; ``fold_frame`` folds a secure-quant field frame
    through a ``SlotAccumulator`` chunk that is center-lifted into the
    totals before its mass could leave the field's range. ``merge`` is
    exact int64 addition — commutative, associative, so N partials
    merged in any order equal one accumulator that folded everything
    (THE sharded-ingest invariant).

    Storage is ONE flat int64 vector with fixed per-leaf offsets, so
    the per-upload hot path is a single short numpy op chain instead of
    a per-leaf dict walk (the per-leaf layout profiled at ~0.5 ms per
    upload — dominated by numpy call overhead on small leaves, not
    arithmetic); the element-wise operations are unchanged, so the
    totals are BITWISE what the per-leaf fold produced. The per-leaf
    dict view (``totals``) is derived by ``np.split`` on demand."""

    def __init__(self, spec: FoldSpec, sizes: list[tuple[str, int]]):
        self.spec = spec
        self.sizes = sizes
        self._splits = np.cumsum([s for _, s in sizes])[:-1]
        self._total_size = int(sum(s for _, s in sizes))
        self._flat: np.ndarray | None = None
        self.w_int_total = 0
        self.count = 0
        #: secure path: the in-progress SlotAccumulator chunk
        self._chunk = None
        self._chunk_mass = 0

    @property
    def totals(self) -> dict[str, np.ndarray] | None:
        """Per-leaf views into the flat totals (the wire/test shape)."""
        if self._flat is None:
            return None
        return {name: seg for (name, _), seg in
                zip(self.sizes, np.split(self._flat, self._splits))}

    # ---- dense ----

    def flatten_upload(self, u_eff: PyTree) -> np.ndarray:
        """One f32 vector in template leaf order; validates structure."""
        named = _named_leaves(u_eff)
        if [(n, int(np.asarray(x).size)) for n, x in named] != self.sizes:
            raise ValueError("upload leaf structure differs from the "
                             "model (version skew); upload discarded")
        return np.concatenate([np.asarray(x, np.float32).reshape(-1)
                               for _, x in named])

    def fold_dense(self, u_eff: PyTree, w_int: int) -> None:
        self.fold_flat(self.flatten_upload(u_eff), w_int)

    def fold_flat(self, flat: np.ndarray, w_int: int) -> None:
        spec = self.spec
        if self._flat is None:
            self._flat = np.zeros(self._total_size, np.int64)
        scaled = np.rint(flat * np.float32(1 << spec.frac_bits))
        # NaN -> neutral zero contribution, +/-inf saturates sign-
        # preservingly (the quantize32 contract; the non-finite
        # admission gate makes this belt-over-braces)
        scaled = np.where(np.isnan(scaled), np.float32(0.0), scaled)
        q = np.clip(scaled, -float(spec.q_max),
                    float(spec.q_max)).astype(np.int64)
        self._flat += int(w_int) * q
        self.w_int_total += int(w_int)
        self.count += 1

    # ---- secure (field frames) ----

    def _lift_chunk(self) -> None:
        if self._chunk is None or self._chunk.folded == 0:
            return
        lifted = self._chunk.export_centered()
        if self._flat is None:
            self._flat = np.zeros(self._total_size, np.int64)
        self._flat += np.concatenate(
            [np.asarray(lifted[name], np.int64).reshape(-1)
             for name, _ in self.sizes])
        self._chunk = None
        self._chunk_mass = 0

    def fold_frame(self, frame: dict, w_int: int) -> None:
        from neuroimagedisttraining_tpu.privacy import SlotAccumulator

        spec = self.spec
        if self._chunk is not None and \
                self._chunk_mass + w_int > spec.chunk_capacity:
            self._lift_chunk()
        if self._chunk is None:
            self._chunk = SlotAccumulator(spec.quant)
            # lock the chunk's structure to the model template
            self._chunk._sizes = list(self.sizes)
        self._chunk.fold(frame, weight_int=int(w_int))
        self._chunk_mass += int(w_int)
        self.w_int_total += int(w_int)
        self.count += 1

    # ---- export / merge / finalize ----

    def export(self) -> dict | None:
        """The wire form of this partial: center-lifted int64 totals +
        mass + count. None when nothing folded."""
        self._lift_chunk()
        if self._flat is None:
            return None
        return {"slots": self.totals, "w_int": self.w_int_total,
                "count": self.count}

    def merge_payload(self, payload: dict) -> None:
        """Exact int64 merge of one exported partial into this one."""
        self._lift_chunk()
        if self._flat is None:
            self._flat = np.zeros(self._total_size, np.int64)
        slots = payload["slots"]
        if sorted(slots) != sorted(name for name, _ in self.sizes):
            raise ValueError("partial leaf structure mismatch at merge")
        self._flat += np.concatenate(
            [np.asarray(slots[name], np.int64).reshape(-1)
             for name, _ in self.sizes])
        self.w_int_total += int(payload["w_int"])
        self.count += int(payload["count"])

    def finalize(self, like: PyTree) -> PyTree:
        """Dequantize the merged totals to the aggregated model:
        ``totals / (w_int_total * 2^frac_bits)`` in float64, reshaped and
        cast like the template. Deterministic in the totals alone."""
        self._lift_chunk()
        if self._flat is None or self.w_int_total == 0:
            raise ValueError("finalize() before any upload folded")
        fb = (self.spec.quant.frac_bits if self.spec.quant is not None
              else self.spec.frac_bits)
        denom = float(self.w_int_total) * float(1 << fb)
        totals = self.totals
        out = {}
        for name, x in _named_leaves(like):
            arr = np.asarray(x)
            out[name] = (totals[name].astype(np.float64) / denom
                         ).reshape(arr.shape).astype(arr.dtype)
        return _rebuild_like(like, out)


def model_sizes(like: PyTree) -> list[tuple[str, int]]:
    return [(name, int(np.asarray(x).size))
            for name, x in _named_leaves(like)]


def single_process_fold(entries: list[tuple], spec: FoldSpec,
                        like: PyTree) -> PartialAccumulator:
    """THE reference the multi-process merge is pinned against: fold
    every entry through ONE accumulator, in the given order. Entries are
    ``(u_eff_or_frame, w_int)``. Because the algebra is exact integer
    arithmetic, any partitioning of the same entries into per-worker
    accumulators, merged in any order, produces bitwise-identical
    totals (tests/test_ingest.py)."""
    acc = PartialAccumulator(spec, model_sizes(like))
    for payload, w_int in entries:
        if spec.quant is not None:
            acc.fold_frame(payload, w_int)
        else:
            acc.fold_dense(payload, w_int)
    return acc


# ---------------------------------------------------------------------------
# cross-worker exactly-once dedup (ISSUE 18)
# ---------------------------------------------------------------------------


class SeqWatermarks:
    """Root-held upload-seq watermarks per (sender, incarnation).

    The per-worker watermark dedups transport re-deliveries on ONE
    connection; this table closes the cross-worker hole: marks of
    accepted (seq, incarnation) pairs ride every verdict batch up to
    the root, and when a sender RE-registers anywhere in the tree with
    the SAME incarnation (a reconnect — its monotone seq continues),
    the root sends the watermark floor back down to the new worker
    BEFORE that worker answers the register, so a re-sent upload the
    old worker already accepted is dropped as a duplicate instead of
    double-contributing. A register under a NEW incarnation is a
    restart: fresh floor, seq 0 legitimate — the documented
    reset-on-re-register semantics for legacy senders are untouched
    (no incarnation => no floor traffic at all). Not thread-safe by
    itself: the root mutates it under its event-loop lock."""

    def __init__(self):
        self._wm: dict[int, list[int]] = {}  # c -> [incarnation, max_seq]

    def register(self, c: int, inc: int) -> int:
        """Floor for a registering sender: its surviving watermark on a
        same-incarnation reconnect, -1 on a new incarnation."""
        cur = self._wm.get(int(c))
        if cur is not None and cur[0] == int(inc):
            return cur[1]
        self._wm[int(c)] = [int(inc), -1]
        return -1

    def advance(self, c: int, inc: int, seq: int) -> None:
        """One accepted-upload mark from a verdict batch. Marks from a
        superseded incarnation (an old worker's batch draining after
        the sender restarted) are ignored — latest incarnation wins."""
        cur = self._wm.get(int(c))
        if cur is None:
            self._wm[int(c)] = [int(inc), int(seq)]
        elif cur[0] == int(inc):
            cur[1] = max(cur[1], int(seq))

    def floor(self, c: int, inc: int) -> int:
        cur = self._wm.get(int(c))
        return cur[1] if cur is not None and cur[0] == int(inc) else -1


# ---------------------------------------------------------------------------
# shared-memory partial hand-off (ISSUE 18)
# ---------------------------------------------------------------------------

#: slab header: int64 x3 — seqlock generation, w_int_total, count
_SHM_HEADER_BYTES = 24
#: double buffering: one slab being read by the parent while the next
#: export writes the other; both un-acked => pickled-pipe fallback
#: (counted, never blocked — exactness is transport-independent)
_SHM_SLABS = 2


class _ShmSlabWriter:
    """OWNER side of one partial-export slab: creates the segment,
    writes the flat int64 vector under a seqlock-style generation
    counter (odd while writing, even when consistent), and — on its
    teardown path — both ``close()``es AND ``unlink()``s it (the
    nidtlint ``shm-discipline`` contract; a SIGKILLed owner's segment
    is reclaimed by multiprocessing's resource tracker instead)."""

    def __init__(self, total_size: int):
        from multiprocessing import shared_memory

        self.total_size = int(total_size)
        self.shm = shared_memory.SharedMemory(
            create=True, size=_SHM_HEADER_BYTES + self.total_size * 8)
        self.name = self.shm.name
        self._hdr = np.ndarray(3, np.int64, buffer=self.shm.buf)
        self._vec = np.ndarray(self.total_size, np.int64,
                               buffer=self.shm.buf,
                               offset=_SHM_HEADER_BYTES)
        self._hdr[:] = 0

    def write(self, segs: list[np.ndarray], w_int: int,
              count: int) -> int:
        """One exported partial into the slab; returns the (even)
        generation the reader must observe unchanged around its copy.
        The ack protocol makes a concurrent write impossible — the
        seqlock turns 'impossible' into 'loudly detected'."""
        gen = int(self._hdr[0])
        self._hdr[0] = gen + 1          # odd: write in progress
        if len(segs) == 1:
            np.copyto(self._vec, segs[0])
        else:
            np.concatenate(segs, out=self._vec)
        self._hdr[1] = int(w_int)
        self._hdr[2] = int(count)
        self._hdr[0] = gen + 2          # even: consistent
        return gen + 2

    def destroy(self) -> None:
        """Owner teardown: close the mapping AND unlink the name."""
        # numpy views export the buffer; drop them or close() raises
        self._hdr = self._vec = None
        try:
            self.shm.close()
        finally:
            try:
                self.shm.unlink()
            except FileNotFoundError:  # pragma: no cover
                pass


class _ShmSlabReader:
    """ATTACH side of one slab: copies the vector out under the
    generation check, then acks so the writer may reuse the slab. The
    attach side only ever ``close()``s — it must NEVER ``unlink()`` a
    segment it does not own (nidtlint ``shm-attach-unlink``); a dead
    owner's segment is the resource tracker's to reclaim."""

    def __init__(self, name: str, total_size: int):
        from multiprocessing import shared_memory

        self.total_size = int(total_size)
        self.shm = shared_memory.SharedMemory(name=name)
        self._hdr = np.ndarray(3, np.int64, buffer=self.shm.buf)
        self._vec = np.ndarray(self.total_size, np.int64,
                               buffer=self.shm.buf,
                               offset=_SHM_HEADER_BYTES)

    def read(self, gen: int) -> tuple[np.ndarray, int, int]:
        """``(flat_copy, w_int, count)`` — raises on a torn or stale
        generation instead of ever returning a silently-wrong vector
        (the audit would catch the count; the totals must never be
        guessable-wrong)."""
        g0 = int(self._hdr[0])
        flat = self._vec.copy()
        w_int, count = int(self._hdr[1]), int(self._hdr[2])
        g1 = int(self._hdr[0])
        if g0 != int(gen) or g1 != int(gen) or g0 % 2:
            raise RuntimeError(
                f"shm slab torn read: generation {g0}/{g1}, expected "
                f"{int(gen)} — writer reused an un-acked slab")
        return flat, w_int, count

    def close(self) -> None:
        self._hdr = self._vec = None
        try:
            self.shm.close()
        except (OSError, BufferError):  # pragma: no cover
            pass


# ---------------------------------------------------------------------------
# worker-side core (socket-free; unit-testable)
# ---------------------------------------------------------------------------


class IngestWorkerCore:
    """Admission + fold state of one ingest worker — everything the
    worker does per upload EXCEPT sockets and pipes, so the gates are
    unit-testable in-process. Mirrors ``BufferedFedAvgServer``'s
    admission verdicts key for key."""

    def __init__(self, wid: int, spec: FoldSpec, init_params: PyTree,
                 max_staleness: int, staleness_alpha: float,
                 wire_masks=None):
        self.wid = wid
        self.spec = spec
        self.params = init_params
        self.version = 0
        self.max_staleness = int(max_staleness)
        self.staleness_alpha = float(staleness_alpha)
        self.wire_masks = wire_masks
        self.sizes = model_sizes(init_params)
        self._ring: dict[int, PyTree] = {0: init_params}
        #: upload-lifecycle stage latencies (ISSUE 13): queue/decode/
        #: admit/fold observed here per upload, merge/aggregate at the
        #: root per aggregation — the instrument that replaced the
        #: ingest bench's hand-timed attribution
        self._stage_hist = obs_fanin.stage_histogram()
        self._stage_ns: dict[str, int] = {}
        self.partial = PartialAccumulator(spec, self.sizes)
        #: flat f32 cache of the ring (one flatten per VERSION, so the
        #: per-upload delta transport is three vector ops, not a
        #: per-leaf tree walk)
        self._flat_ring: dict[int, np.ndarray] = {
            0: self.partial.flatten_upload(init_params)}
        self._seq_seen: dict[int, int] = {}
        self._contributed: dict[int, set[int]] = {}
        self.registered: set[int] = set()
        self.last_synced: dict[int, int] = {}
        #: ISSUE 18: sender-lifetime nonces (reconnect vs restart) and
        #: the delta-sync capability set, both declared at registration
        self.incarnations: dict[int, int] = {}
        self.sync_delta_ok: set[int] = set()
        #: delta frames are shared by every client syncing (base ->
        #: current); cache one encode per pair, cleared on set_model
        self._delta_cache: dict[tuple[int, int], dict] = {}
        #: delta-sync accounting (honest fallback counts, ISSUE 18)
        self.sync_stats = {"sync_delta_sent": 0, "sync_dense_sent": 0,
                           "sync_dense_fallback_ring": 0}
        #: per-entry metadata riding the next exported partial:
        #: (client, tag_version, anchor_version, n, w_int, tau)
        self.entries: list[tuple] = []
        self.stats = {
            "received": 0, "accepted": 0, "dropped_stale": 0,
            "dropped_duplicate": 0, "dropped_future": 0,
            "dropped_quarantined": 0, "dropped_undecodable": 0,
            "dropped_nonfinite": 0, "dropped_after_done": 0,
            "dropped_malformed": 0,
        }
        self.done = False

    # ---- model/version plane (root -> worker) ----

    def set_model(self, version: int, params: PyTree) -> None:
        self.version = int(version)
        self.params = params
        self._ring[self.version] = params
        self._flat_ring[self.version] = \
            self.partial.flatten_upload(params)
        floor = self.version - self.max_staleness
        for old in [v for v in self._ring if v < floor]:
            del self._ring[old]
            self._flat_ring.pop(old, None)
        for c, seen in self._contributed.items():
            self._contributed[c] = {v for v in seen if v >= floor}
        # delta-sync frames against superseded versions are dead weight
        # (every changed-version reply now deltas against a new pair)
        self._delta_cache.clear()

    # ---- client plane ----

    def handle_register(self, c: int, incarnation: int | None = None,
                        delta_ok: bool = False) -> bool:
        """Returns True on first worker-local contact. A re-register —
        which is also how a connection migrates workers — resets the
        sender's LOCAL dedup state, exactly like the single-process
        server; a sender that declared an incarnation then has the
        root's cross-worker watermark floor applied via
        ``note_seqfloor`` BEFORE its register is answered (ISSUE 18),
        so a worker hop cannot double-contribute."""
        first = c not in self.registered
        self.registered.add(c)
        self._seq_seen.pop(c, None)
        self._contributed.pop(c, None)
        if incarnation is not None:
            self.incarnations[c] = int(incarnation)
        else:
            self.incarnations.pop(c, None)
        if delta_ok:
            self.sync_delta_ok.add(c)
        else:
            self.sync_delta_ok.discard(c)
        return first

    def note_seqfloor(self, c: int, inc: int, floor: int) -> None:
        """Apply the root's cross-worker watermark floor (ISSUE 18).
        Guarded by incarnation: a floor for a superseded incarnation
        (the sender restarted while the message was in flight) must not
        poison the fresh sender's seq space."""
        if self.incarnations.get(c) != int(inc):
            return
        if int(floor) > self._seq_seen.get(c, -1):
            self._seq_seen[c] = int(floor)

    def build_sync_body(self, c: int):
        """The model body of a CHANGED-version sync reply for sender
        ``c``: the lossless delta against the sender's last-synced
        version when it advertised the capability and the base is still
        in the broadcast ring, else the dense tree (fallback counted
        and logged — never silent). Returns ``(body, kind)`` with kind
        in {"dense", "delta", "dense_fallback_ring"}."""
        from neuroimagedisttraining_tpu.codec import wire as codec

        base = self.last_synced.get(c)
        if (c not in self.sync_delta_ok or base is None
                or base == self.version):
            self.sync_stats["sync_dense_sent"] += 1
            return self.params, "dense"
        if base not in self._ring:
            log.info(
                "ingest worker %d: delta-sync base %d for client %d "
                "left the broadcast ring (current %d, floor %d); "
                "falling back to a dense body", self.wid, base, c,
                self.version, self.version - self.max_staleness)
            self.sync_stats["sync_dense_fallback_ring"] += 1
            return self.params, "dense_fallback_ring"
        key = (int(base), self.version)
        frame = self._delta_cache.get(key)
        if frame is None:
            frame = codec.encode_sync_delta(self.params,
                                            self._ring[base],
                                            base_version=base)
            self._delta_cache[key] = frame
        self.sync_stats["sync_delta_sent"] += 1
        return frame, "delta"

    def handle_upload(self, msg: M.Message) -> str:
        """One admission decision; returns the verdict key (a
        ``upload_stats`` key). Accepted uploads are folded into the
        local partial before this returns. Stage latencies (queue /
        decode / admit / fold) land in ``nidt_upload_stage_ms`` and,
        when the tracer is armed, the whole decision is one span with
        the upload's wire trace context rendered as a flow step."""
        t0 = time.perf_counter_ns()
        self.stats["received"] += 1
        if self.done:
            self.stats["dropped_after_done"] += 1
            return "dropped_after_done"
        self._stage_ns = {}
        if obs_trace.TRACER.armed:
            with obs_trace.span("ingest_upload", worker=self.wid,
                                client=int(msg.sender_id)):
                verdict = self._admit_guarded(msg)
        else:
            verdict = self._admit_guarded(msg)
        self.stats[verdict] += 1
        if verdict != "accepted":
            # drops are rare and each is a control-plane decision the
            # post-mortem wants; accepts are counted, not recorded
            # (the hot path stays one ring append per anomaly). These
            # ship to the root with worker provenance (obs/fanin.py).
            obs_flight.record(verdict, worker=self.wid,
                              client=int(msg.sender_id),
                              version=self.version)
        t1 = time.perf_counter_ns()
        recv_ns = getattr(msg, "recv_ns", None)
        if recv_ns is not None:
            self._stage_hist.observe((t0 - recv_ns) / 1e6, stage="queue")
        decode_ns = self._stage_ns.get("decode", 0)
        fold_ns = self._stage_ns.get("fold", 0)
        if decode_ns:
            self._stage_hist.observe(decode_ns / 1e6, stage="decode")
        if fold_ns:
            self._stage_hist.observe(fold_ns / 1e6, stage="fold")
        self._stage_hist.observe(
            max(0, (t1 - t0) - decode_ns - fold_ns) / 1e6, stage="admit")
        return verdict

    def _admit_guarded(self, msg: M.Message) -> str:
        try:
            return self._admit(msg)
        except Exception as e:  # noqa: BLE001 — broken FIELDS are a
            # dropped upload, never a dead worker dispatch thread (the
            # single-process server's contract)
            log.warning("ingest worker %d: dropping malformed upload "
                        "from %s (%s: %s)", self.wid, msg.sender_id,
                        type(e).__name__, e)
            return "dropped_malformed"

    def _admit(self, msg: M.Message) -> str:
        from neuroimagedisttraining_tpu.codec import wire as codec

        c = msg.sender_id
        tag = msg.get(M.ARG_ROUND_IDX)
        v = self.version if tag is None else int(tag)
        tau = self.version - v
        if tau < 0:
            # the sender saw a fresher version than this worker knows —
            # only possible in the pipe-latency window after a root
            # advance, or after a reconnect raced a broadcast. Same
            # verdict as the single-process future gate; the reply
            # re-syncs the sender at this worker's version.
            log.warning("ingest worker %d: dropping upload from %d "
                        "tagged FUTURE version %d (worker at %d)",
                        self.wid, c, v, self.version)
            return "dropped_future"
        if tau > self.max_staleness:
            log.warning("ingest worker %d: dropping ancient upload from "
                        "%d (tag %d, current %d)", self.wid, c, v,
                        self.version)
            return "dropped_stale"
        seq = msg.get(M.ARG_UPLOAD_SEQ)
        if seq is not None:
            if int(seq) <= self._seq_seen.get(c, -1):
                return "dropped_duplicate"
            # watermark advances at the gate: a re-delivery repeats the
            # VERDICT, never the processing (server.py precedent)
            self._seq_seen[c] = int(seq)
        elif v in self._contributed.get(c, ()):
            return "dropped_duplicate"
        n = float(msg.get(M.ARG_NUM_SAMPLES))
        if not (np.isfinite(n) and n >= 0):
            raise ValueError(f"non-finite num_samples {n!r}")
        w_int = self.spec.weight_int(n, tau, self.staleness_alpha)
        fid = obs_trace.flow_id_of(msg.get(M.ARG_TRACE_CTX))
        if self.spec.quant is not None:
            from neuroimagedisttraining_tpu.privacy import secure_quant as sq

            frame = msg.get(M.ARG_MODEL_PARAMS)
            t_dec = time.perf_counter_ns()
            try:
                sq._validate_frame(frame, self.spec.quant)
                if sq.SlotAccumulator._frame_sizes(frame) != self.sizes:
                    raise ValueError("frame leaf structure differs from "
                                     "the model (version skew)")
            except (ValueError, KeyError, TypeError) as e:
                log.warning("ingest worker %d: invalid secure frame "
                            "from %d: %s", self.wid, c, e)
                return "dropped_undecodable"
            finally:
                self._stage_ns["decode"] = time.perf_counter_ns() - t_dec
            if seq is None:
                self._contributed.setdefault(c, set()).add(v)
            t_fold = time.perf_counter_ns()
            self.partial.fold_frame(frame, w_int)
            self._stage_ns["fold"] = time.perf_counter_ns() - t_fold
            self.entries.append((c, v, None, n, w_int, tau, fid))
            self._note_flow(fid, c)
            return "accepted"
        ref = self._ring[v]
        t_dec = time.perf_counter_ns()
        try:
            decoded = codec.decode_update(msg.get(M.ARG_MODEL_PARAMS),
                                          like=self.params, reference=ref,
                                          masks=self.wire_masks)
            flat_u = self.partial.flatten_upload(decoded)
        except Exception as e:  # noqa: BLE001 — undecodable = dropped
            log.warning("ingest worker %d: undecodable upload from %d "
                        "(base %d): %s", self.wid, c, v, e)
            return "dropped_undecodable"
        finally:
            self._stage_ns["decode"] = time.perf_counter_ns() - t_dec
        if not np.isfinite(flat_u).all():
            log.warning("ingest worker %d: REJECTING non-finite upload "
                        "from %d (base %d)", self.wid, c, v)
            if seq is None:
                self._contributed.setdefault(c, set()).add(v)
            return "dropped_nonfinite"
        if seq is None:
            self._contributed.setdefault(c, set()).add(v)
        anchor = self.version
        if tau != 0:
            # delta-transport to the worker's CURRENT model (the fold-
            # time anchor, recorded per entry so a replay is exact):
            # u + (params_now - params_base), f32 like the buffered
            # server's transport — three vector ops on the flat cache,
            # element-wise identical to the per-leaf tree walk
            flat_u = flat_u + (self._flat_ring[self.version]
                               - self._flat_ring[v])
        t_fold = time.perf_counter_ns()
        self.partial.fold_flat(flat_u, w_int)
        self._stage_ns["fold"] = time.perf_counter_ns() - t_fold
        self.entries.append((c, v, anchor, n, w_int, tau, fid))
        self._note_flow(fid, c)
        return "accepted"

    def _note_flow(self, fid: int | None, c: int) -> None:
        """Flow STEP for an accepted upload (inside the
        ``ingest_upload`` span ``handle_upload`` holds open) — the
        worker hop of the client->worker->root flow chain."""
        if fid is not None and obs_trace.TRACER.armed:
            obs_trace.flow("upload", fid, "t", worker=self.wid,
                           client=int(c))

    def export_partial(self) -> dict | None:
        """Swap the in-progress partial out for the root (None when
        empty). Entry metadata rides along for history + replay."""
        payload = self.partial.export()
        if payload is None:
            return None
        payload["entries"] = self.entries
        self.partial = PartialAccumulator(self.spec, self.sizes)
        self.entries = []
        return payload


# ---------------------------------------------------------------------------
# worker process
# ---------------------------------------------------------------------------


def _send_tolerant(comm, msg: M.Message) -> bool:
    """Best-effort reply; returns False on failure so the caller can
    avoid recording state the peer never received (e.g. last_synced —
    a client whose full-body sync was dropped must get the body again
    on its next upload, not a body-less sync at a version it never
    saw)."""
    try:
        comm.send_message(msg, retries=1)
        return True
    except (ConnectionError, OSError) as e:
        log.debug("ingest worker: reply to %d failed (%s)",
                  msg.receiver_id, e)
        return False


class _IngestWorkerProc(Observer):
    """The process wrapper: one ``SelectorCommManager`` (SO_REUSEPORT)
    for client frames, one pipe to the root. A single lock orders every
    fold against the verdict event that reports it and the partial
    export that ships it — the FIFO pipe then guarantees the root sees
    events strictly before the partial containing them."""

    def __init__(self, wid: int, core: IngestWorkerCore, comm, conn,
                 use_shm: bool = False, sync_delta: bool = False):
        self.wid = wid
        self.core = core
        self.comm = comm
        self.conn = conn
        self.sync_delta = bool(sync_delta)
        self._lock = threading.Lock()
        #: verdict batch (under _lock): counts per verdict + the taus of
        #: accepted entries — ONE "vb" pipe message per batch instead of
        #: one "v" per upload, flushed on size, age, or before any
        #: partial/bye so the root still sees every verdict strictly
        #: before the partial that contains it
        self._vb_counts: dict[str, int] = {}
        self._vb_taus: list[int] = []
        self._vb_n = 0
        #: accepted-upload watermark marks riding the next vb flush
        #: (ISSUE 18): c -> (incarnation, max accepted seq)
        self._vb_marks: dict[int, tuple[int, int]] = {}
        #: registers deferred until the root's seqfloor answers (the
        #: sender uploads only after its sync reply, so the floor is in
        #: place before any post-migration upload can race it)
        self._pending_reg: dict[int, bool] = {}
        #: shm partial hand-off (ISSUE 18): double-buffered slabs owned
        #: by THIS process; pipe carries control dicts, acks free slabs
        self._slabs: list[_ShmSlabWriter] = []
        self._free_slabs: list[int] = []
        if use_shm:
            self._slabs = [_ShmSlabWriter(core.partial._total_size)
                           for _ in range(_SHM_SLABS)]
            self._free_slabs = list(range(len(self._slabs)))
        #: transport accounting for the shm-vs-pipe bench cell
        self._xstats = {"shm_exports": 0, "pipe_exports": 0,
                        "shm_export_ns": 0, "pipe_export_ns": 0,
                        "shm_fallback_busy": 0}
        #: heartbeat batch (under _lock, ISSUE 13 satellite): per-client
        #: beats fold into ONE "beats" pipe message per flush interval
        #: — at cross-device scale the per-beat pipe events were the
        #: next unbatched fan-in after the verdicts; repeats from the
        #: same client within one interval are SUPPRESSED (counted)
        self._beats_pending: set[int] = set()
        self._obs_beats_suppressed = obs_metrics.gauge(
            obs_names.INGEST_HEARTBEATS_SUPPRESSED,
            "per-client heartbeats folded away by worker-side batching "
            "(duplicates within one flush interval)")
        #: telemetry shipper (ISSUE 13): registry snapshot + span/flight
        #: chunks, one "obs" pipe message per interval — never per frame
        self._shipper = obs_fanin.WorkerObsShipper()
        comm.add_observer(self)
        self._pipe_thread = threading.Thread(target=self._pipe_loop,
                                             daemon=True)

    def _vb_add_locked(self, verdict: str, tau) -> None:
        self._vb_counts[verdict] = self._vb_counts.get(verdict, 0) + 1
        if tau is not None:
            self._vb_taus.append(int(tau))
        self._vb_n += 1
        if self._vb_n >= VERDICT_BATCH_MAX:
            self._flush_verdicts_locked()

    def _flush_verdicts_locked(self) -> None:
        if self._beats_pending:
            # heartbeats ride the same flush cadence as the verdict
            # batches but are ordering-independent of the audit (only
            # vb-before-partial is an invariant)
            self.conn.send(("beats", self.wid,  # nidt: allow[lock-send] -- every caller holds self._lock (the _locked suffix contract); the one pipe has no other writer thread outside it
                            sorted(self._beats_pending)))
            self._beats_pending.clear()
        if not self._vb_n:
            return
        self.conn.send(("vb", self.wid, self._vb_counts, self._vb_taus,  # nidt: allow[lock-send] -- every caller holds self._lock (the _locked suffix contract); the one pipe has no other writer thread outside it
                        self._vb_marks))
        self._vb_counts, self._vb_taus, self._vb_n = {}, [], 0
        self._vb_marks = {}

    def _ship_obs_locked(self, force: bool = False) -> None:
        """Under ``_lock``: one batched telemetry payload per interval
        (rate-limited by the shipper; ``force`` for the pre-bye final
        ship so the root's merged artifacts include the tail)."""
        payload = self._shipper.payload(force=force)
        if payload is not None:
            self.conn.send(("obs", self.wid, payload))  # nidt: allow[lock-send] -- caller holds self._lock (the _locked suffix contract); the one pipe has no other writer thread outside it

    def run(self) -> None:
        self._pipe_thread.start()
        with self._lock:
            self.conn.send(("ready", self.wid))
            if self._slabs:
                # announced BEFORE any partial can reference a slab
                # (same FIFO pipe), so the parent attaches in time
                self.conn.send(("shm_names", self.wid,
                                [s.name for s in self._slabs],
                                self._slabs[0].total_size))
        self.comm.handle_receive_message()

    # ---- root pipe (its own thread) ----

    def _pipe_loop(self) -> None:
        while True:
            try:
                if not self.conn.poll(VERDICT_BATCH_AGE_S):
                    # quiet tick: age out a partially-filled batch so
                    # the root's pending count never lags for long;
                    # the telemetry shipper rate-limits itself to one
                    # payload per OBS_SHIP_INTERVAL_S on the same tick
                    with self._lock:
                        self._flush_verdicts_locked()
                        self._ship_obs_locked()
                    continue
                cmd = self.conn.recv()
            except (EOFError, OSError):
                # root died: nothing to aggregate into — stop serving
                log.warning("ingest worker %d: root pipe closed; "
                            "shutting down", self.wid)
                self._destroy_slabs()
                self.comm.stop_receive_message()
                return
            kind = cmd[0]
            if kind == "model":
                with self._lock:
                    self.core.set_model(cmd[1], cmd[2])
            elif kind == "flush":
                with self._lock:
                    # verdicts strictly BEFORE the partial containing
                    # them (same pipe, FIFO)
                    self._flush_verdicts_locked()
                    self._export_locked(cmd[1])
            elif kind == "shm_ack":
                # parent copied the slab out: free it for reuse
                with self._lock:
                    self._free_slabs.append(int(cmd[1]))
            elif kind == "seqfloor":
                self._on_seqfloor(cmd[1], cmd[2], cmd[3])
            elif kind == "clock":
                # spawn-time clock handshake (obs/fanin.py): echo the
                # root's t0 with this process's perf_counter reading;
                # the root estimates the offset at the pipe's midpoint
                with self._lock:
                    self.conn.send(("clock_reply", self.wid, cmd[1],
                                    time.perf_counter_ns()))
            elif kind == "finish":
                self._finish()
                return

    def _export_locked(self, seq: int) -> None:
        """Under ``_lock``: export the staged partial and ship it —
        through a free shm slab when transport is enabled and one is
        un-acked-free (O(control) pipe message), else pickled through
        the pipe (the documented cross-host fallback, also taken when
        both slabs are still in flight)."""
        t0 = time.perf_counter_ns()
        payload = self.core.export_partial()
        if payload is None:
            self.conn.send(("partial", self.wid, seq, None,  # nidt: allow[lock-send] -- caller holds self._lock (the _locked suffix contract); the one pipe has no other writer thread outside it
                            dict(self.core.stats)))
            return
        if self._slabs and self._free_slabs:
            idx = self._free_slabs.pop()
            gen = self._slabs[idx].write(
                [payload["slots"][name] for name, _ in self.core.sizes],
                payload["w_int"], payload["count"])
            ctrl = {"shm": idx, "gen": gen,
                    "entries": payload["entries"]}
            self.conn.send(("partial", self.wid, seq, ctrl,  # nidt: allow[lock-send] -- caller holds self._lock (the _locked suffix contract); the one pipe has no other writer thread outside it
                            dict(self.core.stats)))
            self._xstats["shm_exports"] += 1
            self._xstats["shm_export_ns"] += \
                time.perf_counter_ns() - t0
            return
        if self._slabs:
            self._xstats["shm_fallback_busy"] += 1
        self.conn.send(("partial", self.wid, seq, payload,  # nidt: allow[lock-send] -- caller holds self._lock (the _locked suffix contract); the one pipe has no other writer thread outside it
                        dict(self.core.stats)))
        self._xstats["pipe_exports"] += 1
        self._xstats["pipe_export_ns"] += time.perf_counter_ns() - t0

    def _destroy_slabs(self) -> None:
        """Owner teardown: close AND unlink every slab exactly once."""
        slabs, self._slabs, self._free_slabs = self._slabs, [], []
        for s in slabs:
            s.destroy()

    def _on_seqfloor(self, c: int, inc: int, floor: int) -> None:
        """Root answered a deferred register: install the surviving
        watermark, then release the held INIT/SYNC reply."""
        with self._lock:
            self.core.note_seqfloor(c, inc, floor)
            first = self._pending_reg.pop(c, None)
            if first is None:
                return
            done = self.core.done
            version, params = self.core.version, self.core.params
        if done:
            _send_tolerant(self.comm,
                           M.Message(M.MSG_TYPE_S2C_FINISH, 0, c))
            return
        self._send_reg_reply(c, first, version, params)

    def _finish(self) -> None:
        with self._lock:
            self.core.done = True
            registered = sorted(self.core.registered)
        for c in registered:
            _send_tolerant(self.comm,
                           M.Message(M.MSG_TYPE_S2C_FINISH, 0, c))
        drain = getattr(self.comm, "drain_sends", None)
        if drain is not None:
            drain(5.0)
        with self._lock:
            self._flush_verdicts_locked()
            obs_flight.record("worker_finish", worker=self.wid,
                              residual=self.core.partial.count,
                              received=self.core.stats["received"])
            # final telemetry ship BEFORE the bye (same pipe, FIFO):
            # the root drains it while waiting on byes, so the merged
            # artifacts include this worker's tail
            self._ship_obs_locked(force=True)
            residual = self.core.partial.count
            xs = {**self._xstats, **self.core.sync_stats}
            self.conn.send(("bye", self.wid, dict(self.core.stats),
                            residual, self.comm.byte_stats(),
                            self.comm.peak_connections, xs))
        # the worker's LOCAL trace dump (the .wN-suffixed secondary
        # artifact; the root's merged trace is the primary)
        obs_trace.dump()
        self._destroy_slabs()
        self.comm.stop_receive_message()

    # ---- client frames (dispatch thread) ----

    def receive_message(self, msg_type: str, msg: M.Message) -> None:
        if msg_type == M.MSG_TYPE_C2S_SEND_MODEL:
            self._on_model(msg)
        elif msg_type == M.MSG_TYPE_C2S_REGISTER:
            self._on_register(msg)
        elif msg_type == M.MSG_TYPE_C2S_HEARTBEAT:
            # batched (ISSUE 13 satellite): the beat joins the pending
            # set and crosses the pipe in ONE "beats" message at the
            # next flush tick (<= VERDICT_BATCH_AGE_S away, far inside
            # any sane heartbeat timeout); a repeat beat from the same
            # client inside one interval carries no extra liveness
            # information and is suppressed, counted in the gauge
            with self._lock:
                if msg.sender_id in self._beats_pending:
                    self._obs_beats_suppressed.inc()
                else:
                    self._beats_pending.add(msg.sender_id)
        else:
            log.warning("ingest worker %d: dropping unexpected %s from "
                        "%s", self.wid, msg_type, msg.sender_id)

    def _on_register(self, msg: M.Message) -> None:
        c = msg.sender_id
        inc = msg.get(M.ARG_CLIENT_INCARNATION)
        delta_ok = bool(msg.get(M.ARG_SYNC_DELTA_OK)) and self.sync_delta
        with self._lock:
            if self.core.done:
                _send_tolerant(self.comm,
                               M.Message(M.MSG_TYPE_S2C_FINISH, 0, c))
                return
            first = self.core.handle_register(c, incarnation=inc,
                                              delta_ok=delta_ok)
            if inc is not None:
                # the reply is DEFERRED until the root's seqfloor
                # lands: the sender uploads only after its sync reply,
                # so the cross-worker watermark is installed before any
                # post-migration upload can race it
                self._pending_reg[c] = first
                self.conn.send(("reg", self.wid, c, int(inc)))
                return
            self.conn.send(("reg", self.wid, c))
            version, params = self.core.version, self.core.params
        self._send_reg_reply(c, first, version, params)

    def _send_reg_reply(self, c: int, first: bool, version: int,
                        params) -> None:
        out = M.Message(M.MSG_TYPE_S2C_INIT_CONFIG if first
                        else M.MSG_TYPE_S2C_SYNC_MODEL, 0, c)
        out.add(M.ARG_MODEL_PARAMS, params)
        out.add(M.ARG_ROUND_IDX, version)
        if _send_tolerant(self.comm, out):
            # recorded only on DELIVERED body: a dropped sync must not
            # turn the client's next reply body-less at a version it
            # never saw
            with self._lock:
                self.core.last_synced[c] = version

    def _on_model(self, msg: M.Message) -> None:
        c = msg.sender_id
        with self._lock:
            verdict = self.core.handle_upload(msg)
            if verdict == "accepted":
                tau = self.core.entries[-1][5] if self.core.entries \
                    else 0
                self._vb_add_locked(verdict, int(tau))
                seq = msg.get(M.ARG_UPLOAD_SEQ)
                inc = self.core.incarnations.get(c)
                if seq is not None and inc is not None:
                    # accepted-seq mark rides the next vb flush so the
                    # root watermark covers a later worker hop
                    prev = self._vb_marks.get(c)
                    if (prev is None or prev[0] != inc
                            or int(seq) > prev[1]):
                        self._vb_marks[c] = (inc, int(seq))
            else:
                self._vb_add_locked(verdict, None)
            done = self.core.done
            version = self.core.version
            fresh = self.core.last_synced.get(c) != version
            body = None
            if not done and fresh:
                body, _kind = self.core.build_sync_body(c)
        if done:
            _send_tolerant(self.comm,
                           M.Message(M.MSG_TYPE_S2C_FINISH, 0, c))
            return
        out = M.Message(M.MSG_TYPE_S2C_SYNC_MODEL, 0, c)
        out.add(M.ARG_ROUND_IDX, version)
        if fresh:
            # the sender's model is behind: ship a body. At an
            # unchanged version the body is OMITTED — the sender holds
            # that exact tree already (cached-sync contract,
            # cross_silo.FedAvgClientProc) — which removes the per-
            # upload model serialization from the hot path entirely.
            # A delta-capable sender gets the lossless delta against
            # its last-synced version when that base is still in the
            # broadcast ring (build_sync_body, ISSUE 18).
            out.add(M.ARG_MODEL_PARAMS, body)
        if _send_tolerant(self.comm, out) and fresh:
            # recorded only on DELIVERED body (see _on_register)
            with self._lock:
                self.core.last_synced[c] = version


def _ingest_worker_main(wid: int, conn, wcfg: dict) -> None:
    """Spawned worker entry point (multiprocessing 'spawn' context —
    fresh interpreter, fresh obs registry, no inherited jax state)."""
    import os
    if os.environ.get("NIDT_INGEST_PROFILE"):
        import atexit
        import collections
        import sys

        samples: collections.Counter = collections.Counter()

        def _sampler():
            while True:
                for tid, frame in sys._current_frames().items():
                    if tid == threading.get_ident():
                        continue
                    stack = []
                    f = frame
                    while f is not None and len(stack) < 3:
                        stack.append(f"{f.f_code.co_filename.rsplit('/', 1)[-1]}"
                                     f":{f.f_lineno}:{f.f_code.co_name}")
                        f = f.f_back
                    samples["|".join(stack)] += 1
                time.sleep(0.002)

        threading.Thread(target=_sampler, daemon=True).start()
        atexit.register(lambda: open(
            os.environ["NIDT_INGEST_PROFILE"] + f".w{wid}", "w").write(
            "\n".join(f"{n} {s}" for s, n in samples.most_common(40))))
    from neuroimagedisttraining_tpu.asyncfl.loop import SelectorCommManager

    # per-process obs plane (ISSUE 13): a spawned worker starts with a
    # fresh registry/tracer/flight ring. Arm the tracer when the root's
    # is armed; LOCAL artifact paths are .wN-suffixed so N workers
    # inheriting one --trace_out/--flight_out never clobber one file —
    # the root's MERGED artifacts at the bare paths are the primary ones
    ocfg = wcfg.get("obs") or {}
    if ocfg.get("trace"):
        obs_trace.arm(
            obs_fanin.suffixed_path(ocfg.get("trace_path", ""), wid)
            or None,
            tags={"role": "ingest-worker", "worker": wid})
    obs_flight.configure(
        capacity=ocfg.get("flight_capacity"),
        path=obs_fanin.suffixed_path(ocfg.get("flight_path", ""), wid))

    core = IngestWorkerCore(
        wid, wcfg["spec"], wcfg["init_params"],
        max_staleness=wcfg["max_staleness"],
        staleness_alpha=wcfg["staleness_alpha"],
        wire_masks=wcfg.get("wire_masks"))
    # inline dispatch: the worker's per-frame work (admission + integer
    # fold + a body-less reply) is small and bounded, so it runs ON the
    # frame-loop thread — the queue handoff's two futex wakeups per
    # upload were the measured throughput choke on sandboxed kernels
    comm = SelectorCommManager(0, wcfg["world_size"],
                               host_map=wcfg.get("host_map"),
                               base_port=wcfg["base_port"],
                               send_timeout=2.0, reuse_port=True,
                               inline_dispatch=True)
    worker = _IngestWorkerProc(wid, core, comm, conn,
                               use_shm=bool(wcfg.get("shm")),
                               sync_delta=bool(wcfg.get("sync_delta")))
    try:
        worker.run()
    except Exception:  # noqa: BLE001 — log the real error before the
        # process dies; the root sees the sentinel either way
        log.exception("ingest worker %d crashed", wid)
        raise


# ---------------------------------------------------------------------------
# root server
# ---------------------------------------------------------------------------


class NullCommManager(QueueDispatchMixin, BaseCommManager):
    """The root's placeholder transport: the WORKERS own every client
    socket, so the root must never bind the port or dial a client."""

    rank = "ingest-root"

    def __init__(self):
        self._init_dispatch()

    def send_message(self, msg: M.Message, **kw) -> None:
        raise RuntimeError(
            "the ingest root has no client transport: worker processes "
            "own the sockets (asyncfl/ingest.py)")

    def handle_receive_message(self) -> None:  # pragma: no cover
        pass

    def stop_receive_message(self) -> None:
        self._stop_dispatch()


class ShardedIngestServer(BufferedFedAvgServer):
    """The root of the sharded ingest plane: spawns ``ingest_workers``
    selector worker processes on ONE ``SO_REUSEPORT`` port, counts their
    per-upload verdict events, and — every ``buffer_k`` accepted uploads
    (shrunk by known-gone clients, ``_k_eff``) — harvests each worker's
    partial and merges them in worker-id order. The merge is exact
    int64 addition, so the aggregated model is BITWISE what one process
    folding the same uploads would produce (module docstring; pinned).

    Inherits the buffered server's accounting/audit/obs machinery; its
    per-upload admission path is unused (workers run the gates) and
    server-side defenses/quarantine are rejected at construction — the
    root only ever sees pre-folded partials."""

    #: audit key for uploads buffered at a child when it died — the
    #: hierarchical tier's children are whole REGIONS, so it overrides
    #: this to "lost_with_region" (asyncfl/region.py)
    _lost_key = "lost_with_worker"

    def __init__(self, init_params, comm_round: int, num_clients: int,
                 ingest_workers: int = 2, buffer_k: int = 0,
                 staleness_alpha: float = 0.5, max_staleness: int = 20,
                 base_port: int | None = None,
                 world_size: int | None = None, secure_quant=None,
                 ingest_weight_ref: float = 32.0,
                 heartbeat_timeout: float = 0.0, wire_masks=None,
                 host_map: dict[int, str] | None = None,
                 spawn_timeout: float = 180.0, trace_out: str = "",
                 flight_out: str = "", use_shm: bool = False,
                 sync_delta: bool = False, **kw):
        if ingest_workers < 1:
            raise ValueError(
                f"ingest_workers must be >= 1, got {ingest_workers}")
        if kw.get("defense", "none") != "none" \
                or kw.get("quarantine_rounds", 0):
            raise ValueError(
                "the sharded ingest plane supports neither server-side "
                "defenses nor quarantine: workers fold uploads into "
                "partial aggregates, so the root never sees per-client "
                "updates to select over or score (matrix precedent: the "
                "buffered secure path; use the single-process plane or "
                "client-side clipping)")
        self.ingest_workers = int(ingest_workers)
        # the parent ctor must not run its one-phase secure capacity
        # checks (the ingest fold has its own geometry) and must not
        # build a listening comm — workers own the port
        super().__init__(init_params, comm_round, num_clients,
                         buffer_k=buffer_k,
                         staleness_alpha=staleness_alpha,
                         max_staleness=max_staleness,
                         world_size=world_size, comm=NullCommManager(),
                         heartbeat_timeout=heartbeat_timeout, **kw)
        self.upload_stats["lost_with_worker"] = 0
        self.upload_stats[self._lost_key] = 0
        self.fold_spec = make_fold_spec(self.params, quant=secure_quant,
                                        weight_ref=ingest_weight_ref)
        self.ingest_quant = secure_quant
        self.wire_masks_ingest = wire_masks
        self.base_port = BASE_PORT if base_port is None else int(base_port)
        # ---- per-worker obs (ISSUE 9 labels) + merge flight events ----
        self._obs_pending = obs_metrics.gauge(
            obs_names.INGEST_PENDING_UPLOADS,
            "accepted uploads buffered at ingest workers, awaiting "
            "harvest")
        self._obs_workers = obs_metrics.gauge(
            obs_names.INGEST_WORKERS_LIVE, "ingest worker processes alive")
        self._obs_partials = obs_metrics.counter(
            obs_names.INGEST_PARTIALS,
            "partials harvested per ingest worker",
            labelnames=("worker",))
        self._obs_worker_uploads = obs_metrics.counter(
            obs_names.INGEST_WORKER_UPLOADS,
            "per-worker upload verdict events at the root",
            labelnames=("worker", "outcome"))
        # ---- federation-wide telemetry fan-in (ISSUE 13) ----
        # workers ship registry snapshots / span chunks / flight events
        # over the verdict pipes; this merges them into ONE exposition
        # (metrics_view), ONE trace and ONE flight dump (dump_obs). The
        # BARE --trace_out/--flight_out paths are the merged artifacts;
        # workers write .wN-suffixed local secondaries.
        self.trace_out = trace_out
        self.flight_out = flight_out
        self.fanin = self._make_fanin()
        self._stage_hist = obs_fanin.stage_histogram()
        self._obs_dumped = False
        # ---- cross-worker exactly-once (ISSUE 18) ----
        # root-held accepted-seq watermarks, advanced by vb marks and
        # answered to deferred registers so a worker/region-hopping
        # client cannot double-contribute
        self._watermarks = SeqWatermarks()
        # cached flat layout for rebuilding shm partial slots
        self._fold_sizes = model_sizes(self.params)
        self._fold_splits = np.cumsum(
            [n for _, n in self._fold_sizes])[:-1]
        # ---- worker processes ----
        ctx = mp.get_context("spawn")
        wcfg = {"spec": self.fold_spec, "init_params": self.params,
                "max_staleness": self.max_staleness,
                "staleness_alpha": self.staleness_alpha,
                "wire_masks": wire_masks,
                "host_map": host_map,
                "world_size": world_size or num_clients + 1,
                "base_port": self.base_port,
                "shm": bool(use_shm),
                "sync_delta": bool(sync_delta),
                "obs": {"trace": bool(trace_out) or obs_trace.TRACER.armed,
                        "trace_path": trace_out,
                        "flight_path": flight_out,
                        "flight_capacity": obs_flight.FLIGHT.capacity}}
        self._workers: dict[int, dict] = {}
        for wid in range(self.ingest_workers):
            proc, parent = self._spawn_child(ctx, wid, wcfg)
            self._workers[wid] = {
                "proc": proc, "conn": parent, "alive": True,
                "acc": 0, "folded": 0, "partials": 0,
                "stats": None, "residual": 0, "bye": False,
                "byte_stats": None, "peak_conns": 0,
                "xstats": None, "shm": None, "last_partial_t": None,
            }
        deadline = time.monotonic() + spawn_timeout
        ready: set[int] = set()
        while len(ready) < self.ingest_workers:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._kill_workers()
                raise RuntimeError(
                    f"ingest workers not ready within {spawn_timeout}s "
                    f"({sorted(ready)} of {self.ingest_workers})")
            for wid, w in self._workers.items():
                if wid in ready:
                    continue
                try:
                    if w["conn"].poll(0.05):
                        msg = w["conn"].recv()
                        if msg[0] == "ready":
                            ready.add(wid)
                except (EOFError, OSError) as e:
                    # a worker that died during spawn (bind failure,
                    # import error) must surface as the named startup
                    # failure, with no orphan siblings left running
                    self._kill_workers()
                    raise RuntimeError(
                        f"ingest worker {wid} died during startup "
                        f"({type(e).__name__}); see its log output"
                    ) from e
        self._obs_workers.set(self.ingest_workers)
        self._harvest_waiting: set[int] | None = None
        self._harvest_parts: list[tuple[int, dict]] = []
        self._harvest_seq = 0
        self._staged: list[tuple[int, dict]] = []
        self._finishing = False
        # spawn-time clock handshake: probe, then collect the replies
        # HERE rather than on the event loop — run() may start seconds
        # after this ctor returns (loadgen spawns its fleet shards in
        # between), and a reply aging in the pipe would inflate t1 by
        # that gap, so the estimated offset would absorb half of it
        # and misalign every worker timeline in the merged trace
        for wid, w in self._workers.items():
            self._register_fanin(wid)
            try:
                w["conn"].send(("clock", time.perf_counter_ns()))  # nidt: allow[lock-send] -- ctor is single-threaded: the event loop and monitor threads do not exist yet
            except (BrokenPipeError, OSError):
                pass
        deadline = time.monotonic() + 2.0
        pending = set(self._workers)
        while pending and time.monotonic() < deadline:
            for wid in sorted(pending):
                w = self._workers[wid]
                try:
                    while w["conn"].poll(0.02):
                        ev = w["conn"].recv()
                        with self._rlock:
                            # early frames (a fast client's reg/vb) are
                            # dispatched normally, never dropped
                            self._handle_event(wid, ev)
                        if ev[0] == "clock_reply":
                            pending.discard(wid)
                            break
                except (EOFError, OSError):
                    pending.discard(wid)  # death surfaces in run()
        if pending:
            log.warning("ingest root: no clock reply from workers %s "
                        "within 2s; their merged-trace timelines fall "
                        "back to offset 0", sorted(pending))
        log.info("ingest root: %d workers ready on port %d",
                 self.ingest_workers, self.base_port)

    def _make_fanin(self) -> obs_fanin.TelemetryFanIn:
        """Fan-in label tiers — one ``worker`` tier here; the
        hierarchical root overrides with ``("region", "worker")``."""
        return obs_fanin.TelemetryFanIn()

    def _register_fanin(self, wid: int) -> None:
        """Register the fan-in key(s) one direct child contributes —
        a region child registers every (region, worker) pair."""
        self.fanin.register_worker(wid)

    def _spawn_child(self, ctx, wid: int, wcfg: dict):
        """Spawn ONE direct child (an ingest worker here; a regional
        sub-aggregator in ``HierarchicalIngestServer``) and return
        ``(process, parent_conn)`` — the override point that lets the
        hierarchical tier reuse the whole root event loop, because a
        region speaks the exact worker pipe protocol upstream."""
        parent, child = ctx.Pipe(duplex=True)
        proc = ctx.Process(target=_ingest_worker_main,
                           args=(wid, child, wcfg), daemon=True,
                           name=f"nidt-ingest-w{wid}")
        proc.start()
        child.close()
        return proc, parent

    # ---- introspection (tests / loadgen) ----

    @property
    def worker_pids(self) -> list[int]:
        return [w["proc"].pid for w in self._workers.values()]

    def worker_xstats(self) -> dict[str, int]:
        """Summed per-worker transport/sync accounting from the byes
        (shm vs pipe export counts + ns, delta-sync counts) — the bench
        cells' raw material."""
        out: dict[str, int] = {}
        for w in self._workers.values():
            xs = w["xstats"]
            if xs:
                for k, v in xs.items():
                    out[k] = out.get(k, 0) + int(v)
        return out

    def live_workers(self) -> list[int]:
        return [wid for wid, w in self._workers.items() if w["alive"]]

    def peak_connection_estimate(self) -> int:
        return sum(w["peak_conns"] for w in self._workers.values())

    def worker_byte_stats(self) -> dict[str, int]:
        out = {"bytes_sent": 0, "bytes_recv": 0, "frames_sent": 0,
               "frames_recv": 0}
        for w in self._workers.values():
            bs = w["byte_stats"]
            if bs:
                for k in out:
                    out[k] += bs.get(k, 0)
        return out

    def _observe_health_boundary(self) -> None:
        """Anomaly rules on the sharded root evaluate the fan-in-MERGED
        snapshot (obs/fanin.py): root cells plus every worker's cells
        re-labeled ``worker="N"`` — a rule's label-subset selector fires
        on a worker's series exactly as on a local one (ISSUE 15)."""
        if obs_rules.active() is None:
            # unarmed (loadgen soaks): skip the O(metrics x workers)
            # merge on the aggregation hot path, not just the verdict
            return
        obs_rules.observe_boundary(self.round_idx,
                                   snapshot=self.fanin.merged_snapshot())

    def metrics_view(self):
        """The MERGED registry view ``--metrics_port`` should serve
        under ``--ingest_workers``: root samples + worker samples
        (``worker`` label) + snapshot-staleness gauges. Pass as the
        ``registry`` of ``obs.http.start_metrics_server``."""
        return self.fanin.metrics_view()

    def dump_obs(self, reason: str = "end of run"
                 ) -> dict[str, str | None]:
        """Write the MERGED obs artifacts at the bare configured paths
        (idempotent; called at end of run and on the crash path with a
        truthful ``reason``). The merged trace is the primary
        ``--trace_out`` artifact — workers only write ``.wN``-suffixed
        local secondaries."""
        with self._rlock:
            if self._obs_dumped:
                return {}
            self._obs_dumped = True
        out: dict[str, str | None] = {}
        if self.trace_out:
            out["trace"] = self.fanin.dump_trace(self.trace_out)
            log.info("ingest root: merged trace -> %s", out["trace"])
        if self.flight_out:
            out["flight"] = self.fanin.dump_flight(self.flight_out,
                                                   reason=reason)
            log.info("ingest root: merged flight dump -> %s (%s)",
                     out["flight"], reason)
        return out

    # ---- the root event loop ----

    def run(self) -> None:
        if self.heartbeat_timeout > 0:
            threading.Thread(target=self._monitor_loop,
                             daemon=True).start()
        try:
            while not self._done.is_set():
                self._poll_once()
        finally:
            crashed = sys.exc_info()[0] is not None
            if not self._done.is_set():
                # crashed out of the loop: leave no orphan processes
                self._kill_workers()
                self._done.set()
            # merged obs artifacts even on the crash/all-workers-dead
            # paths (idempotent: the clean path dumped in _finish_join)
            self.dump_obs(reason="failure" if crashed else "end of run")
            if crashed and self.flight_out:
                # the caller's failure_context is about to dump the
                # ROOT ring (with its "failure" event) to the default
                # flight path — point it at a sibling so it cannot
                # clobber the merged artifact with a root-only view;
                # both post-mortems survive, truthfully labeled
                obs_flight.configure(path=self.flight_out + ".root")

    def _poll_once(self, timeout: float = 0.1) -> None:
        conns = {w["conn"]: wid for wid, w in self._workers.items()
                 if w["alive"]}
        sentinels = {w["proc"].sentinel: wid
                     for wid, w in self._workers.items() if w["alive"]}
        if not conns:
            # every worker is gone: nothing can ever arrive again (the
            # normal FINISH path sets _done from _finish_join; this is
            # the all-workers-crashed case)
            with self._rlock:
                if not self._done.is_set() and not self._finishing:
                    log.error("ingest root: every worker died; "
                              "finishing with %d aggregations",
                              len(self.history))
                    self._done.set()
            time.sleep(timeout)
            return
        try:
            ready = mp.connection.wait(
                list(conns) + list(sentinels), timeout=timeout)
        except OSError:
            ready = []
        # pipes BEFORE sentinels: a worker that exited may have verdict/
        # partial events still buffered in its pipe — processing the
        # sentinel first would count those uploads lost_with_worker and
        # then double-count them when the pipe drains
        for obj in ready:
            if obj in conns:
                self._drain_conn(conns[obj])
        for obj in ready:
            if obj in sentinels:
                self._mark_worker_dead(sentinels[obj], "process exited")
        with self._rlock:
            self._maybe_harvest()

    def _drain_conn(self, wid: int) -> None:
        w = self._workers[wid]
        while True:
            try:
                if not w["conn"].poll():
                    return
                ev = w["conn"].recv()
            except (EOFError, OSError):
                self._mark_worker_dead(wid, "pipe closed")
                return
            with self._rlock:
                self._handle_event(wid, ev)

    def _handle_event(self, wid: int, ev: tuple) -> None:
        """Under ``_rlock``: one worker event."""
        w = self._workers[wid]
        kind = ev[0]
        if kind == "vb":
            # one "vb" event per worker-side BATCH of processed frames:
            # received and the verdict bumps land in LOCKSTEP at the
            # root, so the received == accepted + dropped audit holds
            # across processes exactly as it does in-process — at a
            # per-batch, not per-upload, fan-in cost
            counts, taus = ev[2], ev[3]
            self._stat("received", sum(counts.values()))
            for verdict, n in counts.items():
                self._stat(verdict, n)
                self._obs_worker_uploads.inc(n, worker=str(wid),
                                             outcome=verdict)
            acc_n = counts.get("accepted", 0)
            if acc_n:
                w["acc"] += acc_n
                for tau in taus:
                    self._obs_staleness.observe(tau)
                self._obs_pending.set(self._pending())
            if len(ev) > 4 and ev[4]:
                # accepted-seq marks (ISSUE 18): advance the root
                # watermark so a later re-register on ANY worker
                # inherits the floor
                for c, (inc, seq) in ev[4].items():
                    self._watermarks.advance(c, inc, seq)
        elif kind == "reg":
            c = ev[2]
            self._registered.add(c)
            self._suspect.discard(c)
            self._last_beat[c] = time.monotonic()
            if len(ev) > 3 and ev[3] is not None:
                # incarnation-carrying register: answer the surviving
                # watermark — the worker holds the client's reply until
                # this seqfloor lands (exactly-once across hops)
                inc = int(ev[3])
                floor = self._watermarks.register(c, inc)
                try:
                    w["conn"].send(("seqfloor", c, inc, floor))  # nidt: allow[lock-send] -- caller holds _rlock (method contract) and the event loop is the ONLY thread that ever writes a worker pipe
                except (BrokenPipeError, OSError):
                    self._mark_worker_dead_locked(wid,
                                                  "seqfloor send failed")
        elif kind == "beat":
            c = ev[2]
            self._last_beat[c] = time.monotonic()
            self._suspect.discard(c)
        elif kind == "beats":
            # worker-side batched heartbeats (ISSUE 13 satellite): one
            # pipe event per flush interval carrying every client that
            # beat in it — liveness granularity is the flush interval,
            # far inside any sane heartbeat timeout
            now = time.monotonic()
            for c in ev[2]:
                self._last_beat[c] = now
                self._suspect.discard(c)
        elif kind == "obs":
            # batched telemetry payload -> the fan-in (snapshots, span
            # chunks, flight events); ordering-independent of the
            # vb-before-partial audit invariant
            self.fanin.ingest(wid, ev[2])
        elif kind == "clock_reply":
            self.fanin.note_clock(wid, ev[2], ev[3],
                                  time.perf_counter_ns())
        elif kind == "shm_names":
            # worker announced its slabs (FIFO-before any shm partial):
            # attach read-only views; NEVER unlinked here — the worker
            # owns the segments and unlinks on ITS teardown
            w["shm"] = [_ShmSlabReader(name, ev[3]) for name in ev[2]]
        elif kind == "partial":
            seq, payload, stats = ev[2], ev[3], ev[4]
            w["stats"] = stats
            if isinstance(payload, dict) and "shm" in payload:
                payload = self._resolve_shm_partial(wid, payload)
            if payload is not None:
                w["last_partial_t"] = time.monotonic()
                w["folded"] += int(payload["count"])
                w["partials"] += 1
                self._obs_partials.inc(worker=str(wid))
                if seq == self._harvest_seq and \
                        self._harvest_waiting is not None:
                    self._harvest_parts.append((wid, payload))
                else:
                    # unsolicited (headroom) or late partial: stage it
                    # for the next merge — never dropped
                    self._staged.append((wid, payload))
            if self._harvest_waiting is not None \
                    and seq == self._harvest_seq:
                self._harvest_waiting.discard(wid)
                if not self._harvest_waiting:
                    self._complete_harvest()
        elif kind == "bye":
            w["stats"], w["residual"] = ev[2], ev[3]
            w["byte_stats"], w["peak_conns"] = ev[4], ev[5]
            if len(ev) > 6:
                w["xstats"] = ev[6]
            w["bye"] = True
        elif kind == "ready":
            pass
        else:  # pragma: no cover
            log.warning("ingest root: unknown worker event %r", kind)

    def _resolve_shm_partial(self, wid: int, ctrl: dict) -> dict:
        """Under ``_rlock``: materialize a shm-transported partial —
        copy the flat int64 vector out of the slab (seqlock-checked),
        ack the slab back to the worker for reuse, rebuild the
        per-leaf slots from the cached flat layout."""
        w = self._workers[wid]
        idx = int(ctrl["shm"])
        flat, w_int, count = w["shm"][idx].read(ctrl["gen"])
        try:
            w["conn"].send(("shm_ack", idx))  # nidt: allow[lock-send] -- caller holds _rlock (method contract) and the event loop is the ONLY thread that ever writes a worker pipe
        except (BrokenPipeError, OSError):
            pass  # death surfaces on the sentinel; the copy is ours
        segs = np.split(flat, self._fold_splits)
        slots = {name: seg
                 for (name, _), seg in zip(self._fold_sizes, segs)}
        return {"slots": slots, "w_int": int(w_int),
                "count": int(count), "entries": ctrl["entries"]}

    def _pending(self) -> int:
        """Under ``_rlock``: accepted uploads not yet merged, lost, or
        reported residual — the buffer occupancy of the sharded plane."""
        return sum(max(0, w["acc"] - w["folded"] - w["residual"])
                   for w in self._workers.values())

    def _maybe_harvest(self) -> None:
        """Under ``_rlock``: start a harvest when the distributed buffer
        has filled (or finish the run when the target is reached)."""
        if self._done.is_set() or self._finishing:
            return
        if self._harvest_waiting is not None:
            # a dead worker can never answer; don't wait for it
            self._harvest_waiting &= set(self.live_workers())
            if not self._harvest_waiting:
                self._complete_harvest()
            return
        if self._pending() >= self._k_eff() or self._staged:
            self._begin_harvest()

    def _begin_harvest(self) -> None:
        self._harvest_seq += 1
        self._harvest_parts = []
        waiting = set()
        for wid in self.live_workers():
            try:
                self._workers[wid]["conn"].send(  # nidt: allow[lock-send] -- caller holds _rlock (method contract) and the event loop is the ONLY thread that ever writes a worker pipe
                    ("flush", self._harvest_seq))
                waiting.add(wid)
            except (BrokenPipeError, OSError):
                self._mark_worker_dead_locked(wid, "flush send failed")
        self._harvest_waiting = waiting
        if not waiting:
            self._complete_harvest()

    def _complete_harvest(self) -> None:
        """Under ``_rlock``: merge the harvested partials in worker-id
        order and advance the version. Partials staged from headroom
        flushes ride the same merge."""
        parts = sorted(self._staged + self._harvest_parts,
                       key=lambda p: p[0])
        self._staged, self._harvest_parts = [], []
        self._harvest_waiting = None
        if not parts:
            return
        t_merge = time.perf_counter_ns()
        acc = PartialAccumulator(self.fold_spec, model_sizes(self.params))
        entries: list[tuple] = []
        for wid, payload in parts:
            acc.merge_payload(payload)
            entries.extend(payload["entries"])
        self._stage_hist.observe(
            (time.perf_counter_ns() - t_merge) / 1e6, stage="merge")
        if acc.w_int_total > self.fold_spec.mass_bound():
            # int64 exactness no longer provable: discard the buffer
            # loudly (the secure path's aggregation_discarded contract),
            # never merge values that may have wrapped
            log.error("ingest root: merged weight mass %d exceeds the "
                      "exactness bound %d - discarding %d uploads, "
                      "model unchanged", acc.w_int_total,
                      self.fold_spec.mass_bound(), acc.count)
            self._stat("aggregation_discarded", acc.count)
            obs_flight.record("aggregation_discarded",
                              version=self.round_idx, uploads=acc.count,
                              error="ingest mass bound exceeded")
            return
        entries.sort(key=lambda e: (e[0], e[1]))
        t_agg = time.perf_counter_ns()
        self.params = acc.finalize(self.params)
        self._stage_hist.observe(
            (time.perf_counter_ns() - t_agg) / 1e6, stage="aggregate")
        self.round_idx += 1
        if obs_trace.TRACER.armed:
            # flow ENDS for the merged uploads' wire trace contexts
            # (entry element 6), inside an aggregate span so Perfetto
            # has a slice to bind the arrows to; capped per merge
            with obs_trace.span("aggregate", version=self.round_idx,
                                clients=acc.count):
                flows = 0
                for e in entries:
                    fid = e[6] if len(e) > 6 else None
                    if fid is None:
                        continue
                    obs_trace.flow("upload", fid, "f",
                                   version=self.round_idx)
                    flows += 1
                    if flows >= _FLOW_ENDS_MAX:
                        break
        self._ring[self.round_idx] = self.params
        floor = self.round_idx - self.max_staleness
        for old in [k for k in self._ring if k < floor]:
            del self._ring[old]
        senders = [e[0] for e in entries]
        obs_flight.record(
            "partial_merge", version=self.round_idx,
            workers={str(wid): int(p["count"]) for wid, p in parts},
            clients=len(senders), w_int=acc.w_int_total)
        obs_flight.record("aggregate", version=self.round_idx,
                          clients=len(senders),
                          taus=[int(e[5]) for e in entries])
        self._obs_round_gauge.set(self.round_idx)
        self._obs_k_eff.set(self._k_eff())
        self._obs_pending.set(self._pending())
        self.history.append({
            "version": self.round_idx, "clients": len(senders),
            "contributors": senders,
            "taus": [int(e[5]) for e in entries],
            "weights": [float(e[4]) for e in entries],
            "entries": entries,
            "workers": {int(wid): int(p["count"]) for wid, p in parts},
            "t": time.monotonic()})
        if self.round_idx >= self.comm_round:
            self._begin_finish()
            return
        for wid in self.live_workers():
            try:
                self._workers[wid]["conn"].send(  # nidt: allow[lock-send] -- caller holds _rlock (method contract) and the event loop is the ONLY thread that ever writes a worker pipe
                    ("model", self.round_idx, self.params))
            except (BrokenPipeError, OSError):
                self._mark_worker_dead_locked(wid, "model send failed")

    def _mark_worker_dead(self, wid: int, why: str) -> None:
        """Takes ``_rlock``; event-loop callers that already hold it use
        ``_mark_worker_dead_locked`` directly (the lock is not
        reentrant)."""
        with self._rlock:
            self._mark_worker_dead_locked(wid, why)

    def _mark_worker_dead_locked(self, wid: int, why: str) -> None:
        w = self._workers[wid]
        if not w["alive"]:
            return
        # drain whatever the worker managed to ship before dying: a
        # SIGKILLed process's pipe still holds its written events, and
        # every event drained here is an upload that is NOT lost
        try:
            while w["conn"].poll():
                self._handle_event(wid, w["conn"].recv())
        except (EOFError, OSError):
            pass
        w["alive"] = False
        if w["shm"]:
            # attach-side teardown: close our mappings ONLY — the
            # (dead) worker owned the segments; unlink is its job (or
            # the resource tracker's, for a SIGKILL)
            readers, w["shm"] = w["shm"], None
            for r in readers:
                r.close()
        self.fanin.mark_dead(wid)  # last snapshot stays, marked stale
        lost = max(0, w["acc"] - w["folded"] - w["residual"])
        if lost and not w["bye"]:
            # accepted uploads that died WITH the child: accounted
            # explicitly so the audit reconciles instead of leaking
            # (lost_with_worker on a flat root, lost_with_region when
            # the dead child is a whole region)
            self.upload_stats[self._lost_key] += lost
            self._obs_uploads.inc(lost, outcome=self._lost_key)
            w["folded"] += lost
        self._obs_workers.set(len(self.live_workers()))
        obs_flight.record("worker_dead", worker=wid, why=why,
                          lost=lost, version=self.round_idx)
        log.warning("ingest root: worker %d dead (%s); %d buffered "
                    "uploads lost with it", wid, why, lost)
        if self._harvest_waiting is not None:
            self._harvest_waiting.discard(wid)
            if not self._harvest_waiting:
                self._complete_harvest()

    # ---- finish ----

    def _begin_finish(self) -> None:
        """Under ``_rlock``: tell every worker to FINISH its clients,
        then collect byes on the event loop until they exit."""
        self._finishing = True
        for wid in self.live_workers():
            try:
                self._workers[wid]["conn"].send(  # nidt: allow[lock-send] -- caller holds _rlock (method contract) and the event loop is the ONLY thread that ever writes a worker pipe
                    ("finish",))
            except (BrokenPipeError, OSError):
                self._mark_worker_dead_locked(wid, "finish send failed")
        threading.Thread(target=self._finish_join, daemon=True).start()

    def _finish_join(self) -> None:
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            with self._rlock:
                if all(w["bye"] or not w["alive"]
                       for w in self._workers.values()):
                    break
            time.sleep(0.05)
        self._kill_workers(join_first=True)
        # every worker's final pre-bye obs payload has been drained by
        # the event loop by now — write the merged artifacts
        self.dump_obs()
        self._done.set()
        self.finish()

    def _kill_workers(self, join_first: bool = False) -> None:
        for w in self._workers.values():
            p = w["proc"]
            if join_first:
                p.join(timeout=5.0)
            if p.is_alive():
                p.terminate()
                p.join(timeout=2.0)
            w["alive"] = False
            if w["shm"]:
                readers, w["shm"] = w["shm"], None
                for r in readers:
                    r.close()

    def _maybe_complete(self) -> None:
        """The heartbeat monitor's nudge: a fresh suspect may have
        lowered ``_k_eff`` below the pending count — the event loop's
        next tick (<= 100 ms) runs ``_maybe_harvest``, so nothing to do
        here beyond keeping the gauge honest."""
        self._obs_k_eff.set(self._k_eff())

    # ---- audit ----

    def upload_audit(self) -> dict:
        """Cross-worker frame accounting: verdict events make
        ``received == accepted + dropped`` hold at the root in real
        time, and every accepted upload is in a merged aggregation,
        still buffered at a worker, reported residual at FINISH, or
        explicitly ``lost_with_worker`` — zero silently lost, zero
        double-counted, across processes."""
        with self._rlock:
            s = dict(self.upload_stats)
            dropped = sum(v for k, v in s.items()
                          if k.startswith("dropped_"))
            aggregated = sum(h["clients"] for h in self.history
                             if "version" in h)
            buffered = self._pending() + sum(
                w["residual"] for w in self._workers.values())
            audit = {
                **s,
                "aggregated": aggregated,
                "buffered": buffered,
                "workers": {wid: {"alive": w["alive"], "acc": w["acc"],
                                  "folded": w["folded"],
                                  "partials": w["partials"]}
                            for wid, w in self._workers.items()},
                "received_accounted":
                    s["received"] == s["accepted"] + dropped,
                "accepted_accounted":
                    s["accepted"] == (aggregated + buffered
                                      + s.get("lost_with_worker", 0)
                                      + s.get("lost_with_region", 0)
                                      + s["aggregation_discarded"]),
            }
        if not (audit["received_accounted"]
                and audit["accepted_accounted"]):
            obs_flight.record("audit_failure", version=self.round_idx,
                              audit={k: v for k, v in audit.items()
                                     if isinstance(v, (int, bool))})
            out = obs_flight.dump(reason="ingest upload_audit failure")
            log.error("ingest root: upload audit FAILED (%s)%s", audit,
                      f" - flight recorder dumped to {out}" if out
                      else "")
        return audit
