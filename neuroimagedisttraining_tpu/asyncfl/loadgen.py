"""Load harness: thousands of lightweight simulated clients, one server.

The point of the asynchronous control plane is a population no
thread-per-connection server can hold; this module proves it ON THIS BOX
with an asyncio client fleet in one thread — each simulated client is a
coroutine holding one persistent connection, speaking the real protocol
(register -> version-tagged sync -> upload echoing the tag), uploading a
canned update pytree instead of training. Churn comes from the seeded
``FaultSchedule``: ``crash:RANK@ROUND`` disconnects the client when it
observes that version, ``rejoin:RANK@ROUND`` reconnects and re-registers
once the server's version reaches the rejoin point, ``straggle:P:MAX_S``
sleeps before uploads. One ``--fault_spec`` string therefore drives the
same deterministic churn trace against both servers.

Two modes on the SAME cohort:

- ``async`` — ``BufferedFedAvgServer`` on the selector core: aggregate
  every ``buffer_k`` arrivals, staleness-weighted.
- ``sync`` — the round-synchronous ``FedAvgServer`` on the SAME selector
  core (so the A/B isolates the control-plane discipline, not the socket
  implementation), deadline + quorum armed so churn cannot deadlock the
  barrier.

Metrics per mode: sustained uploads/s (accepted), aggregations/s, p99
version-advance latency, peak concurrent connections, byte/frame
counters, and the accounting audits (every received upload accounted
exactly once; accepted == aggregated + still-buffered; sent-vs-received
reconciles to at most one in-flight upload per client).
``main()`` writes the sync-vs-async cell to
``bench_matrix/async_bench.json`` (scripts/run_async_bench.sh).
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import struct
import threading
import time

import numpy as np

from neuroimagedisttraining_tpu.asyncfl.loop import SelectorCommManager
from neuroimagedisttraining_tpu.asyncfl.server import BufferedFedAvgServer
from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.cross_silo import FedAvgServer
from neuroimagedisttraining_tpu.distributed.ports import free_port_block
from neuroimagedisttraining_tpu.faults.schedule import (
    FaultSchedule,
    parse_fault_spec,
)


def canned_update_tree(rank: int, leaf_elems: int = 256) -> dict:
    """A small deterministic per-client update pytree (the model payload
    stand-in). Structure must match the server's init template."""
    rng = np.random.default_rng(9973 * rank + 17)
    return {"params": {
        "dense": {"kernel": rng.standard_normal(leaf_elems,
                                                dtype=np.float32),
                  "bias": rng.standard_normal(8, dtype=np.float32)}}}


@dataclasses.dataclass
class ClientStats:
    """Aggregated across the fleet by the harness."""

    sent: int = 0
    bytes_sent: int = 0
    syncs_seen: int = 0
    crashes: int = 0
    rejoins: int = 0
    finished: int = 0
    errors: int = 0


def _frame(msg: M.Message) -> bytes:
    return M.frame_bytes(msg)


async def _read_msg(reader: asyncio.StreamReader) -> M.Message:
    header = await reader.readexactly(8)
    (length,) = struct.unpack("!Q", header)
    return M.Message.from_bytes(await reader.readexactly(length))


async def _connect_and_register(rank: int, port: int, server_done
                                ) -> tuple[asyncio.StreamReader,
                                           asyncio.StreamWriter] | None:
    """Connect with patience — a 1k-client connect storm can transiently
    overflow the accept backlog. Returns None once the server has
    finished (a tiny fast cohort can complete every aggregation before
    the staggered tail ever connects; retrying a closed listener
    forever would hang the fleet)."""
    delay = 0.05
    while True:
        if server_done():
            return None
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1",
                                                           port)
            break
        except OSError:
            await asyncio.sleep(delay)
            delay = min(1.0, delay * 2)
    reg = M.Message(M.MSG_TYPE_C2S_REGISTER, rank, 0)
    # promise a persistent connection: the selector core routes every
    # reply to this rank back on this very socket
    reg.add(M.ARG_CONN_PERSISTENT, True)
    writer.write(_frame(reg))
    await writer.drain()
    return reader, writer


async def _run_client(rank: int, port: int, update: dict,
                      num_samples: float, stats: ClientStats,
                      schedule: FaultSchedule | None,
                      version_probe, server_done, train_delay: float,
                      start_stagger: float, report_corpse=None) -> None:
    """One simulated client: persistent connection, real protocol, canned
    uploads, schedule-driven churn. ``version_probe``/``server_done``
    peek at the in-process server so a crashed client knows when its
    rejoin round has arrived without holding a connection."""
    if start_stagger > 0:
        await asyncio.sleep(start_stagger)
    conn = await _connect_and_register(rank, port, server_done)
    if conn is None:
        stats.finished += 1
        return
    reader, writer = conn
    seq = 0
    while True:
        try:
            msg = await _read_msg(reader)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            if server_done():
                stats.finished += 1
            else:
                stats.errors += 1
            return
        if msg.msg_type == M.MSG_TYPE_S2C_FINISH:
            stats.finished += 1
            writer.close()
            return
        version = int(msg.get(M.ARG_ROUND_IDX, 0))
        stats.syncs_seen += 1
        if schedule is not None and schedule.crashed(version, rank):
            # simulated SIGKILL: drop the connection, then wait out the
            # crash window (rejoin directive) by watching the server's
            # version advance — or leave for good
            stats.crashes += 1
            writer.close()
            if report_corpse is not None:
                # report_corpse takes the server's _rlock — run it on a
                # worker thread so a dispatch-held lock (jit compile,
                # drain) never freezes the event loop
                await asyncio.get_running_loop().run_in_executor(
                    None, report_corpse, rank)
            while not server_done():
                v = version_probe()
                if not schedule.crashed(v, rank):
                    conn = await _connect_and_register(rank, port,
                                                       server_done)
                    if conn is None:
                        break  # finished while reconnecting
                    stats.rejoins += 1
                    reader, writer = conn
                    break
                await asyncio.sleep(0.02)
            else:
                stats.finished += 1
                return
            if conn is None:
                stats.finished += 1
                return
            continue
        delay = train_delay
        if schedule is not None:
            delay += schedule.straggle_seconds(version, rank)
        if delay > 0:
            await asyncio.sleep(delay)
        out = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, rank, 0)
        out.add(M.ARG_MODEL_PARAMS, update)
        out.add(M.ARG_NUM_SAMPLES, num_samples)
        out.add(M.ARG_ROUND_IDX, version)
        out.add(M.ARG_UPLOAD_SEQ, seq)
        seq += 1
        buf = _frame(out)
        try:
            writer.write(buf)
            await writer.drain()
        except (ConnectionError, OSError):
            if server_done():
                stats.finished += 1
            else:
                stats.errors += 1
            return
        stats.sent += 1
        stats.bytes_sent += len(buf)


class _TimedSyncServer(FedAvgServer):
    """The round-synchronous baseline with advance timestamps, so both
    modes report the same p99 version-advance metric."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.advance_t: list[float] = []

    def _complete_round(self, n_clients, survivors=None):
        self.advance_t.append(time.monotonic())
        super()._complete_round(n_clients, survivors=survivors)


def run_load(mode: str = "async", num_clients: int = 200,
             aggregations: int = 20, buffer_k: int = 0,
             staleness_alpha: float = 0.5, max_staleness: int = 50,
             fault_spec: str = "", seed: int = 0,
             train_delay: float = 0.0, leaf_elems: int = 256,
             sync_round_deadline: float = 5.0,
             base_port: int | None = None) -> dict:
    """Drive ``num_clients`` simulated clients against one server and
    return the metrics dict. ``mode="async"`` runs the buffered server
    for ``aggregations`` aggregations of ``buffer_k`` uploads each;
    ``mode="sync"`` runs the round-synchronous server for the number of
    rounds that consumes a comparable upload volume."""
    if mode not in ("async", "sync"):
        raise ValueError(f"mode must be async|sync, got {mode!r}")
    port = base_port if base_port is not None else free_port_block(2)
    k = int(buffer_k) if buffer_k else num_clients
    init = canned_update_tree(0, leaf_elems)
    schedule = None
    if fault_spec:
        schedule = FaultSchedule(parse_fault_spec(fault_spec), seed)
    # send_timeout mirrors the server's own hardening: a simulated
    # client that stops draining must stall the dispatch thread for at
    # most 2 s, not the 30 s default — the p99 numbers exist to measure
    # the control plane, not one stuck peer
    comm = SelectorCommManager(0, num_clients + 1, base_port=port,
                               send_timeout=2.0)
    if mode == "async":
        server = BufferedFedAvgServer(
            init, aggregations, num_clients, buffer_k=k,
            staleness_alpha=staleness_alpha, max_staleness=max_staleness,
            comm=comm)
        rounds = aggregations
    else:
        rounds = max(2, (aggregations * k) // num_clients)
        server = _TimedSyncServer(
            init, rounds, num_clients, comm=comm,
            round_deadline=sync_round_deadline,
            quorum=max(1, int(num_clients * 0.6)))
    server_thread = threading.Thread(target=server.run, daemon=True)

    stats = [ClientStats() for _ in range(num_clients + 1)]

    def version_probe():
        # LOCK-FREE by design: this is polled from the asyncio loop
        # every 20 ms by crashed clients, and taking the server's
        # _rlock here would freeze the whole fleet whenever the
        # dispatch thread holds it (jit compile, dial-out retries,
        # drain). A torn int read cannot happen in CPython, and the
        # poll only needs eventual progress, not a consistent snapshot.
        return server.round_idx

    server_done = server._done.is_set

    def report_corpse(rank):
        # stand-in for the heartbeat monitor's verdict: the harness
        # KNOWS the schedule just killed this client, so it marks the
        # corpse suspect directly instead of flooding the GIL-bound box
        # with per-client beat frames. Without this, a cohort-sized
        # buffer (buffer_k=0) plus one permanent crash can never fill —
        # _k_eff only shrinks on suspicion. Real deployments arm
        # --heartbeat_interval/--heartbeat_timeout for the same signal.
        if mode == "async":
            with server._rlock:
                server._suspect.add(rank)
                server._maybe_complete()
        # the sync server's deadline/quorum path handles corpses itself

    async def _fleet():
        # ~500 connects/s ramp: enough to dodge backlog overflow, short
        # against the measured window
        tasks = [asyncio.create_task(_run_client(
            r, port, canned_update_tree(r, leaf_elems), float(8 + r % 5),
            stats[r], schedule, version_probe, server_done, train_delay,
            start_stagger=r * 0.002, report_corpse=report_corpse))
            for r in range(1, num_clients + 1)]
        await asyncio.gather(*tasks)

    t0 = time.monotonic()
    server_thread.start()
    asyncio.run(_fleet())
    server_thread.join(timeout=60.0)
    wall = time.monotonic() - t0

    fleet = ClientStats()
    for s in stats:
        for f in dataclasses.fields(ClientStats):
            setattr(fleet, f.name,
                    getattr(fleet, f.name) + getattr(s, f.name))
    if mode == "async":
        adv_t = [h["t"] for h in server.history]
        accepted = server.upload_stats["accepted"]
        audit = server.upload_audit()
        received = server.upload_stats["received"]
    else:
        adv_t = server.advance_t
        accepted = sum(h["clients"] for h in server.history)
        # the sync server keeps no received/drop counters: a deadline-
        # advanced round legitimately drops late uploads as stale, so
        # `accepted` is a LOWER bound on received, not a proxy for it —
        # only the one-sided bound below is provable in sync mode
        received = None
        audit = {"received_accounted": True, "accepted_accounted": True}
    deltas_ms = (1e3 * np.diff(np.asarray(adv_t))
                 if len(adv_t) >= 2 else np.asarray([]))
    result = {
        "mode": mode,
        "num_clients": num_clients,
        "buffer_k": k if mode == "async" else None,
        "staleness_alpha": staleness_alpha if mode == "async" else None,
        "max_staleness": max_staleness if mode == "async" else None,
        "rounds_or_aggregations": len(server.history),
        "target": aggregations if mode == "async" else rounds,
        "fault_spec": fault_spec,
        "wall_s": round(wall, 3),
        "uploads_sent": fleet.sent,
        "uploads_accepted": accepted,
        "uploads_per_s": round(accepted / wall, 2) if wall else 0.0,
        "sent_per_s": round(fleet.sent / wall, 2) if wall else 0.0,
        "aggregations_per_s": (round(len(server.history) / wall, 3)
                               if wall else 0.0),
        "version_advance_p50_ms": (round(float(
            np.percentile(deltas_ms, 50)), 2) if deltas_ms.size else None),
        "version_advance_p99_ms": (round(float(
            np.percentile(deltas_ms, 99)), 2) if deltas_ms.size else None),
        "peak_connections": comm.peak_connections,
        "client_stats": dataclasses.asdict(fleet),
        "byte_stats": comm.byte_stats(),
        "upload_audit": audit,
        # async: every client has at most one upload in flight when the
        # server stops reading, so sent can exceed received by at most
        # the fleet size — anything else is a lost or double-counted
        # frame. Sync: the server keeps no received counter (deadline
        # rounds drop stale uploads by design), so only accepted <= sent
        # is provable.
        "frames_reconciled": bool(
            audit["received_accounted"] and audit["accepted_accounted"]
            and (accepted <= fleet.sent if received is None
                 else (received <= fleet.sent
                       and fleet.sent - received <= num_clients))),
        "staleness_hist": (_staleness_hist(server.history)
                           if mode == "async" else None),
    }
    return result


def _staleness_hist(history: list[dict]) -> dict[str, int]:
    hist: dict[str, int] = {}
    for h in history:
        for tau in h.get("taus", ()):
            hist[str(tau)] = hist.get(str(tau), 0) + 1
    return hist


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="neuroimagedisttraining_tpu.asyncfl.loadgen",
        description=__doc__.split("\n\n")[0])
    ap.add_argument("--clients", type=int, default=1000)
    ap.add_argument("--mode", choices=("async", "sync", "both"),
                    default="both")
    ap.add_argument("--aggregations", type=int, default=30,
                    help="async: buffered aggregations to run; the sync "
                         "baseline runs the round count consuming a "
                         "comparable upload volume")
    ap.add_argument("--buffer_k", type=int, default=50,
                    help="aggregate every K accepted uploads (0 = "
                         "cohort size)")
    ap.add_argument("--staleness_alpha", type=float, default=0.5)
    ap.add_argument("--max_staleness", type=int, default=50)
    ap.add_argument("--fault_spec", type=str, default="",
                    help="seeded churn, e.g. 'crash:7@3,rejoin:7@10,"
                         "straggle:0.1:0.05'")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--train_delay", type=float, default=0.0,
                    help="seconds each client 'trains' per round")
    ap.add_argument("--leaf_elems", type=int, default=256)
    ap.add_argument("--out", type=str, default="",
                    help="write the JSON cell here (bench_matrix/"
                         "async_bench.json)")
    args = ap.parse_args(argv)

    cells = {}
    modes = ("async", "sync") if args.mode == "both" else (args.mode,)
    for mode in modes:
        cells[mode] = run_load(
            mode=mode, num_clients=args.clients,
            aggregations=args.aggregations, buffer_k=args.buffer_k,
            staleness_alpha=args.staleness_alpha,
            max_staleness=args.max_staleness,
            fault_spec=args.fault_spec, seed=args.seed,
            train_delay=args.train_delay, leaf_elems=args.leaf_elems)
        print(json.dumps(cells[mode]), flush=True)
    out = {"bench": "async_control_plane", **cells}
    if "async" in cells and "sync" in cells:
        a, s = cells["async"], cells["sync"]
        out["summary"] = {
            "uploads_per_s_ratio": (round(a["uploads_per_s"]
                                          / s["uploads_per_s"], 2)
                                    if s["uploads_per_s"] else None),
            "p99_advance_ratio": (round(s["version_advance_p99_ms"]
                                        / a["version_advance_p99_ms"], 2)
                                  if a["version_advance_p99_ms"]
                                  and s["version_advance_p99_ms"]
                                  else None),
        }
        print(json.dumps({"summary": out["summary"]}), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
    ok = all(c["frames_reconciled"] for c in cells.values())
    return 0 if ok else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
