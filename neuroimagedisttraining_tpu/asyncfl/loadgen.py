"""Load harness: thousands of lightweight simulated clients, one server.

The point of the asynchronous control plane is a population no
thread-per-connection server can hold; this module proves it ON THIS BOX
with an asyncio client fleet — each simulated client is a coroutine
holding one persistent connection, speaking the real protocol
(register -> version-tagged sync -> upload echoing the tag), uploading a
canned update pytree instead of training. For bench cells the fleet
shards across ``fleet_procs`` PROCESSES (one asyncio loop is ~a core of
socket syscalls on this box; an unsharded generator caps near the
server's own throughput and measures itself). Churn comes from the seeded
``FaultSchedule``: ``crash:RANK@ROUND`` disconnects the client when it
observes that version, ``rejoin:RANK@ROUND`` reconnects and re-registers
once the server's version reaches the rejoin point, ``straggle:P:MAX_S``
sleeps before uploads. One ``--fault_spec`` string therefore drives the
same deterministic churn trace against both servers.

Two modes on the SAME cohort:

- ``async`` — ``BufferedFedAvgServer`` on the selector core: aggregate
  every ``buffer_k`` arrivals, staleness-weighted.
- ``sync`` — the round-synchronous ``FedAvgServer`` on the SAME selector
  core (so the A/B isolates the control-plane discipline, not the socket
  implementation), deadline + quorum armed so churn cannot deadlock the
  barrier.

Metrics per mode: sustained uploads/s (accepted), aggregations/s, p99
version-advance latency, peak concurrent connections, byte/frame
counters, and the accounting audits (every received upload accounted
exactly once; accepted == aggregated + still-buffered; sent-vs-received
reconciles to at most one in-flight upload per client).
``main()`` writes the sync-vs-async cell to
``bench_matrix/async_bench.json`` (scripts/run_async_bench.sh).
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import logging
import multiprocessing as mp
import os
import signal
import struct
import threading
import time

import numpy as np

from neuroimagedisttraining_tpu.asyncfl.loop import SelectorCommManager
from neuroimagedisttraining_tpu.asyncfl.server import BufferedFedAvgServer
from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.cross_silo import FedAvgServer
from neuroimagedisttraining_tpu.distributed.ports import free_port_block
from neuroimagedisttraining_tpu.faults.schedule import (
    FaultSchedule,
    parse_fault_spec,
)
from neuroimagedisttraining_tpu.obs import fanin as obs_fanin
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.obs import names as obs_names


log = logging.getLogger("neuroimagedisttraining_tpu.asyncfl")


def canned_update_tree(rank: int, leaf_elems: int = 256) -> dict:
    """A small deterministic per-client update pytree (the model payload
    stand-in). Structure must match the server's init template."""
    rng = np.random.default_rng(9973 * rank + 17)
    return {"params": {
        "dense": {"kernel": rng.standard_normal(leaf_elems,
                                                dtype=np.float32),
                  "bias": rng.standard_normal(8, dtype=np.float32)}}}


@dataclasses.dataclass
class ClientStats:
    """Aggregated across the fleet by the harness."""

    sent: int = 0
    bytes_sent: int = 0
    syncs_seen: int = 0
    crashes: int = 0
    rejoins: int = 0
    finished: int = 0
    errors: int = 0
    #: changed-version SYNC_MODEL replies that carried a body, and the
    #: total received frame bytes of those replies — the downlink-bytes
    #: denominator/numerator of the delta-sync cell (ISSUE 18); the
    #: register bootstrap (INIT_CONFIG) is excluded: it is always dense
    #: by design, not a changed-version sync
    sync_bodies: int = 0
    sync_body_bytes: int = 0
    #: sync bodies that arrived as lossless delta frames and decoded
    #: against the client-held base
    delta_syncs: int = 0
    #: delta frames whose named base did NOT match the client-held
    #: version (protocol error — the client recovers by re-registering
    #: for a dense resync, never by applying a wrong-base delta)
    delta_errors: int = 0
    #: sampled upload->sync round-trips (ms, every 4th), fleet-merged
    #: by list concatenation in run_load's aggregation loop
    rtt_ms: list = dataclasses.field(default_factory=list)


def _frame(msg: M.Message) -> bytes:
    return M.frame_bytes(msg)


async def _read_msg(reader: asyncio.StreamReader) -> M.Message:
    header = await reader.readexactly(8)
    (length,) = struct.unpack("!Q", header)
    msg = M.Message.from_bytes(await reader.readexactly(length))
    # received wire size (header + body) — the downlink byte accounting
    # of the delta-sync cell reads it off the reply it measures
    msg.recv_len = 8 + length
    return msg


async def _connect_and_register(rank: int, port: int, server_done,
                                incarnation: int | None = None,
                                delta_ok: bool = False
                                ) -> tuple[asyncio.StreamReader,
                                           asyncio.StreamWriter] | None:
    """Connect with patience — a 1k-client connect storm can transiently
    overflow the accept backlog. Returns None once the server has
    finished (a tiny fast cohort can complete every aggregation before
    the staggered tail ever connects; retrying a closed listener
    forever would hang the fleet)."""
    delay = 0.05
    while True:
        if server_done():
            return None
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1",
                                                           port)
            break
        except OSError:
            await asyncio.sleep(delay)
            delay = min(1.0, delay * 2)
    reg = M.Message(M.MSG_TYPE_C2S_REGISTER, rank, 0)
    # promise a persistent connection: the selector core routes every
    # reply to this rank back on this very socket
    reg.add(M.ARG_CONN_PERSISTENT, True)
    if incarnation is not None:
        # exactly-once dedup (ISSUE 18): the SAME incarnation rides
        # every reconnect of this client process, so a post-migration
        # worker learns the root's accepted-seq floor before replying
        reg.add(M.ARG_CLIENT_INCARNATION, int(incarnation))
    if delta_ok:
        reg.add(M.ARG_SYNC_DELTA_OK, True)
    writer.write(_frame(reg))
    await writer.drain()
    return reader, writer


async def _run_client(rank: int, port: int, update: dict,
                      num_samples: float, stats: ClientStats,
                      schedule: FaultSchedule | None,
                      version_probe, server_done, train_delay: float,
                      start_stagger: float, report_corpse=None,
                      reconnect: bool = False,
                      incarnation: int | None = None,
                      sync_delta: bool = False,
                      local_scale: float = 0.0) -> None:
    """One simulated client: persistent connection, real protocol, canned
    uploads, schedule-driven churn. ``version_probe``/``server_done``
    peek at the in-process server so a crashed client knows when its
    rejoin round has arrived without holding a connection.

    ``incarnation`` (constant across this coroutine's reconnects — the
    upload ``seq`` below never resets either) arms the ingest root's
    exactly-once watermarks; ``sync_delta`` opts into lossless delta
    sync bodies and decodes them against the tracked base;
    ``local_scale > 0`` uploads ``synced_params + local_scale * canned``
    instead of the bare canned tree — the small-local-update regime of
    real federated training, where consecutive model versions are
    correlated enough for a delta to beat the dense body (the canned
    random walk is not)."""
    if start_stagger > 0:
        await asyncio.sleep(start_stagger)
    conn = await _connect_and_register(rank, port, server_done,
                                       incarnation, sync_delta)
    if conn is None:
        stats.finished += 1
        return
    reader, writer = conn
    seq = 0
    t_sent = None
    track_model = sync_delta or local_scale > 0
    model = None        # last synced dense-equivalent tree (tracked)
    model_version = -1  # the version that tree corresponds to
    wire = None
    if sync_delta:
        from neuroimagedisttraining_tpu.codec import wire

    async def _lost_connection() -> bool:
        """Unexpected connection loss. Returns True when the client
        should keep running (reconnected — the sharded ingest plane's
        kill-one-worker story: the kernel re-balances the fresh
        connection onto a surviving listener), False to stop."""
        nonlocal reader, writer
        if server_done():
            stats.finished += 1
            return False
        if not reconnect:
            stats.errors += 1
            return False
        stats.errors += 1
        c = await _connect_and_register(rank, port, server_done,
                                        incarnation, sync_delta)
        if c is None:
            stats.finished += 1
            return False
        stats.rejoins += 1
        reader, writer = c
        return True

    while True:
        try:
            msg = await _read_msg(reader)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            if await _lost_connection():
                continue
            return
        if msg.msg_type == M.MSG_TYPE_S2C_FINISH:
            stats.finished += 1
            writer.close()
            return
        version = int(msg.get(M.ARG_ROUND_IDX, 0))
        stats.syncs_seen += 1
        if t_sent is not None:
            if seq % 4 == 0:
                rtt = 1e3 * (time.monotonic() - t_sent)
                stats.rtt_ms.append(rtt)
                # live registry mirror (ISSUE 13 satellite): the RTT
                # percentiles used to exist only as ingest_bench.json
                # notes. LIVE for the in-process fleet; spawned fleet
                # shards observe into private registries no shipper
                # sends home, so run_load backfills their samples at
                # fleet-merge time instead (end-of-run visibility).
                obs_fanin.rtt_histogram().observe(rtt)
            t_sent = None
        body = msg.get(M.ARG_MODEL_PARAMS) if track_model else None
        if body is not None:
            if msg.msg_type == M.MSG_TYPE_S2C_SYNC_MODEL:
                stats.sync_bodies += 1
                stats.sync_body_bytes += msg.recv_len
            if wire is not None and wire.is_sync_delta_frame(body):
                if model is None or int(body["base"]) != model_version:
                    # protocol error, handled LOUDLY: never apply a
                    # delta to a base the encoder did not name —
                    # re-register for a dense resync instead
                    stats.delta_errors += 1
                    log.error(
                        "client %d: sync delta names base %s but the "
                        "client holds %d — re-registering for a dense "
                        "resync", rank, body.get("base"), model_version)
                    writer.close()
                    if await _lost_connection():
                        continue
                    return
                model = wire.decode_sync_delta(body, model)
                stats.delta_syncs += 1
            else:
                model = body
            model_version = version
        if schedule is not None and schedule.crashed(version, rank):
            # simulated SIGKILL: drop the connection, then wait out the
            # crash window (rejoin directive) by watching the server's
            # version advance — or leave for good
            stats.crashes += 1
            writer.close()
            if report_corpse is not None:
                # report_corpse takes the server's _rlock — run it on a
                # worker thread so a dispatch-held lock (jit compile,
                # drain) never freezes the event loop
                await asyncio.get_running_loop().run_in_executor(
                    None, report_corpse, rank)
            while not server_done():
                v = version_probe()
                if not schedule.crashed(v, rank):
                    conn = await _connect_and_register(
                        rank, port, server_done, incarnation, sync_delta)
                    if conn is None:
                        break  # finished while reconnecting
                    stats.rejoins += 1
                    reader, writer = conn
                    break
                await asyncio.sleep(0.02)
            else:
                stats.finished += 1
                return
            if conn is None:
                stats.finished += 1
                return
            continue
        delay = train_delay
        if schedule is not None:
            delay += schedule.straggle_seconds(version, rank)
        if delay > 0:
            await asyncio.sleep(delay)
        out = M.Message(M.MSG_TYPE_C2S_SEND_MODEL, rank, 0)
        out.add(M.ARG_MODEL_PARAMS,
                _local_update_tree(model, update, local_scale)
                if local_scale > 0 and model is not None else update)
        out.add(M.ARG_NUM_SAMPLES, num_samples)
        out.add(M.ARG_ROUND_IDX, version)
        out.add(M.ARG_UPLOAD_SEQ, seq)
        # wire trace context (ISSUE 13): the flow STARTS here; the
        # worker's admission and the root's aggregate link to the same
        # id, so the merged trace reads one upload end to end
        ctx = obs_trace.make_trace_ctx(rank, seq)
        out.add(M.ARG_TRACE_CTX, ctx)
        seq += 1
        buf = _frame(out)
        try:
            if obs_trace.TRACER.armed:
                with obs_trace.span("client_upload", client=rank):
                    obs_trace.flow("upload", obs_trace.flow_id_of(ctx),
                                   "s", client=rank)
                    writer.write(buf)
                    await writer.drain()
            else:
                writer.write(buf)
                await writer.drain()
        except (ConnectionError, OSError):
            if await _lost_connection():
                continue
            return
        stats.sent += 1
        stats.bytes_sent += len(buf)
        t_sent = time.monotonic()


def _local_update_tree(base: dict, canned: dict, scale: float):
    """``base + scale * canned``, leaf-wise, dtype-preserving — one
    simulated local training step from the last synced model. Keeps the
    upload structurally identical to the canned tree the servers'
    templates expect."""
    if isinstance(base, dict):
        return {k: _local_update_tree(base[k], canned[k], scale)
                for k in base}
    b = np.asarray(base)
    return b + b.dtype.type(scale) * np.asarray(canned)


def bench_payload(r: int, leaf_elems: int, quant, seed: int):
    """The canned upload of one simulated client — shared by the
    in-process fleet and the spawned fleet shards so the two generators
    stay byte-identical. Secure path: ONE field-element frame (masks
    cancel inside the frame, so reusing it upload-to-upload is sound;
    seq dedups)."""
    if quant is not None:
        from neuroimagedisttraining_tpu.privacy import encode_secure_quant

        rng = np.random.default_rng(31337 * (seed + 1) + r)
        return encode_secure_quant(canned_update_tree(r, leaf_elems),
                                   1.0, quant, rng)
    return canned_update_tree(r, leaf_elems)


def _fleet_proc_main(conn, ranks, port, leaf_elems, secure, seed,
                     train_delay, ready_go, done_ev, reconnect,
                     use_inc=False, sync_delta=False,
                     local_scale=0.0) -> None:
    """Spawned fleet shard (loadgen scale-out). One asyncio client loop
    is ~one core of SYSCALL work on this box (socket.send alone profiles
    at ~0.5 ms in this kernel), so a single-process fleet caps near the
    server's own throughput and would measure ITSELF. The bench drives
    the server from several fleet processes instead: each shard runs the
    same ``_run_client`` coroutines over its rank slice and ships its
    ``ClientStats`` home over the pipe. The shard imports and builds its
    payloads BEFORE signalling ready, and starts connecting only on the
    go event — interpreter spawn never leaks into the measured window."""
    quant = None
    if secure:
        from neuroimagedisttraining_tpu.privacy import QuantSpec

        quant = QuantSpec.from_bits(32, 10, 3)

    payloads = {r: bench_payload(r, leaf_elems, quant, seed)
                for r in ranks}
    stats = {r: ClientStats() for r in ranks}

    async def fleet():
        tasks = [asyncio.create_task(_run_client(
            r, port, payloads[r], float(8 + r % 5), stats[r], None,
            lambda: -1, done_ev.is_set, train_delay,
            start_stagger=r * 0.002, report_corpse=None,
            reconnect=reconnect,
            incarnation=(r if use_inc else None),
            sync_delta=sync_delta, local_scale=local_scale))
            for r in ranks]
        await asyncio.gather(*tasks)

    conn.send("ready")  # nidt: allow[lock-send] -- the shard's end of the pipe has exactly one writer: this function, sequentially
    ready_go.wait()
    asyncio.run(fleet())
    conn.send([dataclasses.asdict(s) for s in stats.values()])  # nidt: allow[lock-send] -- same single sequential writer
    conn.close()


# ---------------------------------------------------------------------------
# serve mode (ISSUE 17): seeded open-loop request fleet against the
# sharded serving plane
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ServeClientStats:
    """Per-client serving-fleet counters; every request attempt lands in
    exactly ONE of ok/rejected/errors (the client half of the
    zero-unaccounted-requests audit)."""

    sent: int = 0
    ok: int = 0
    rejected: int = 0
    errors: int = 0
    reconnects: int = 0
    #: client-observed request RTTs (ms), published through the SAME
    #: ``nidt_client_rtt_ms`` path as the ingest fleet at merge
    rtt_ms: list = dataclasses.field(default_factory=list)
    #: site -> {model digest -> count} from /predict replies — the
    #: routing proof (two sites must observe different digests)
    routes: dict = dataclasses.field(default_factory=dict)


def _merge_serve_stats(all_stats) -> ServeClientStats:
    m = ServeClientStats()
    for s in all_stats:
        m.sent += s.sent
        m.ok += s.ok
        m.rejected += s.rejected
        m.errors += s.errors
        m.reconnects += s.reconnects
        m.rtt_ms.extend(s.rtt_ms)
        for site, digests in s.routes.items():
            dst = m.routes.setdefault(site, {})
            for d, n in digests.items():
                dst[d] = dst.get(d, 0) + n
    return m


def _publish_fleet_rtt(rtt_ms) -> None:
    """ONE ``nidt_client_rtt_ms`` publication path for every fleet
    (ISSUE 17 satellite): spawned shards (and the serve clients, which
    never observe live) collected their samples in ``rtt_ms`` lists —
    backfill them into THIS process's histogram so the merged scrape
    carries the distribution without re-measuring."""
    if not rtt_ms:
        return
    h = obs_fanin.rtt_histogram()
    for v in rtt_ms:
        h.observe(float(v))


async def _read_http_response(reader) -> tuple[int, bytes]:
    """Minimal HTTP/1.1 keep-alive response read (status, body)."""
    line = await reader.readline()
    if not line:
        raise ConnectionError("server closed connection")
    status = int(line.split()[1])
    clen = 0
    while True:
        h = await reader.readline()
        if h in (b"\r\n", b"\n", b""):
            break
        if h.lower().startswith(b"content-length:"):
            clen = int(h.split(b":", 1)[1])
    body = await reader.readexactly(clen) if clen else b""
    return status, body


async def _serve_client(rank: int, port: int, shape: tuple,
                        n_requests: int, site: str | None,
                        stats: ServeClientStats, pace_s: float,
                        seed: int, start_stagger: float) -> None:
    """One serving client: a persistent keep-alive connection sending
    ``n_requests`` raw-array /predict POSTs with seeded pacing gaps; on
    a transport error (e.g. its SO_REUSEPORT listener was SIGKILLed) it
    counts the attempt as an error and reconnects — the kernel lands
    the new connection on a surviving listener."""
    await asyncio.sleep(start_stagger)
    rng = np.random.default_rng(100003 * seed + rank)
    body = rng.standard_normal(shape).astype(np.float32).tobytes()
    head = (f"POST /predict HTTP/1.1\r\nHost: nidt\r\n"
            f"Content-Type: application/octet-stream\r\n"
            f"X-NIDT-Shape: {','.join(str(d) for d in shape)}\r\n"
            + (f"X-NIDT-Site: {site}\r\n" if site is not None else "")
            + f"Content-Length: {len(body)}\r\n\r\n").encode() + body
    reader = writer = None
    for i in range(n_requests):
        stats.sent += 1
        t0 = time.perf_counter()
        try:
            if writer is None:
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port)
                if i:
                    stats.reconnects += 1
            writer.write(head)
            await writer.drain()
            status, payload = await _read_http_response(reader)
            stats.rtt_ms.append((time.perf_counter() - t0) * 1e3)
            if status == 200:
                stats.ok += 1
                reply = json.loads(payload)
                key = site if site is not None else ""
                digests = stats.routes.setdefault(key, {})
                digests[reply["digest"]] = \
                    digests.get(reply["digest"], 0) + 1
            elif 400 <= status < 500:
                stats.rejected += 1
            else:
                stats.errors += 1
        except (OSError, ConnectionError, ValueError,
                asyncio.IncompleteReadError):
            stats.errors += 1
            if writer is not None:
                try:
                    writer.close()
                except OSError:
                    pass
            reader = writer = None
            await asyncio.sleep(0.02)
        if pace_s > 0:
            await asyncio.sleep(float(rng.exponential(pace_s)))
    if writer is not None:
        try:
            writer.close()
            await writer.wait_closed()
        except (OSError, ConnectionError):
            pass


def _serve_fleet_proc_main(conn, ranks, port, shape, n_by_rank, sites,
                           seed, pace_s, ready_go) -> None:
    """Spawned serving fleet shard (same scale-out rationale as
    ``_fleet_proc_main``): run the rank slice's clients, ship their
    ``ServeClientStats`` home over the pipe."""
    stats = {r: ServeClientStats() for r in ranks}

    async def fleet():
        tasks = [asyncio.create_task(_serve_client(
            r, port, tuple(shape), n_by_rank[r],
            sites[r % len(sites)] if sites else None, stats[r], pace_s,
            seed, start_stagger=r * 0.002))
            for r in ranks]
        await asyncio.gather(*tasks)

    conn.send("ready")  # nidt: allow[lock-send] -- the shard's end of the pipe has exactly one writer: this function, sequentially
    ready_go.wait()
    asyncio.run(fleet())
    conn.send([dataclasses.asdict(s) for s in stats.values()])  # nidt: allow[lock-send] -- same single sequential writer
    conn.close()


def _run_serve_load(*, num_clients: int, serve_bundle: str,
                    serve_workers: int, serve_requests: int,
                    serve_kill_at: int, batch_buckets, max_queue_ms,
                    serve_precision: str, seed: int, fleet_procs: int,
                    base_port, metrics_port: int, trace_out: str,
                    flight_out: str) -> dict:
    """``mode="serve"``: drive a seeded open-loop request fleet against
    the sharded serving plane (serve/server.py) and return the bench
    cell. ``serve_kill_at >= 0`` SIGKILLs serve worker 0 once that many
    requests were served (the chaos cell — clients reconnect onto the
    surviving SO_REUSEPORT listeners; the admission audit plus the
    client-side accounting bound every request)."""
    from neuroimagedisttraining_tpu.serve.bundle import read_manifest
    from neuroimagedisttraining_tpu.serve.server import ShardedServeServer

    if not serve_bundle:
        raise ValueError(
            "mode='serve' requires serve_bundle: a bundle directory "
            "(build one with python -m neuroimagedisttraining_tpu.serve "
            "--from_checkpoint ... --build_only)")
    manifest = read_manifest(serve_bundle)
    shape = tuple(manifest["input_shape"])
    #: route the fleet across the first two personalized site models
    #: (the routing-distinctness proof); a site-less bundle serves the
    #: global model to everyone
    sites = [str(s) for s in manifest["sites"][:2]]
    total = serve_requests if serve_requests > 0 else 2 * num_clients
    n_by_rank = {r: total // num_clients
                 + (1 if r <= total % num_clients else 0)
                 for r in range(1, num_clients + 1)}
    pace_s = 0.01  # seeded exponential think-time between requests

    if trace_out:
        obs_trace.arm(trace_out, tags={"role": "loadgen-serve-root"})
    server = ShardedServeServer(
        serve_bundle, port=int(base_port or 0),
        serve_workers=serve_workers, batch_buckets=tuple(batch_buckets),
        max_queue_ms=max_queue_ms, precision=serve_precision,
        trace_out=trace_out, flight_out=flight_out)
    msrv = None
    if metrics_port:
        from neuroimagedisttraining_tpu.obs.http import MetricsServer

        msrv = MetricsServer(max(0, int(metrics_port)),
                             registry=server.metrics_view(),
                             health_probe=server.health)

    stats = [ServeClientStats() for _ in range(num_clients + 1)]
    fleet_workers: list[tuple] = []
    ready_go = None
    if fleet_procs > 1:
        ctx = mp.get_context("spawn")
        ready_go = ctx.Event()
        slices = np.array_split(np.arange(1, num_clients + 1),
                                fleet_procs)
        for sl in slices:
            parent_c, child_c = ctx.Pipe(duplex=False)
            p = ctx.Process(
                target=_serve_fleet_proc_main,
                args=(child_c, [int(r) for r in sl], server.port,
                      shape, {int(r): n_by_rank[int(r)] for r in sl},
                      sites, seed, pace_s, ready_go),
                daemon=True, name="nidt-loadgen-serve-fleet")
            p.start()
            child_c.close()
            fleet_workers.append((p, parent_c))
        for p, c in fleet_workers:
            if not c.poll(300.0) or c.recv() != "ready":
                raise RuntimeError(
                    "loadgen serve fleet shard failed to start")

    killed = threading.Event()
    fleet_done = threading.Event()
    if serve_kill_at >= 0:
        def _kill_watch():
            while not fleet_done.is_set():
                if server.total("served") >= serve_kill_at:
                    try:
                        os.kill(server.worker_pids[0], signal.SIGKILL)
                        killed.set()
                    except (OSError, IndexError):
                        pass
                    return
                time.sleep(0.02)

        threading.Thread(target=_kill_watch, daemon=True,
                         name="serve-kill-watch").start()

    t0 = time.monotonic()
    if fleet_procs > 1:
        ready_go.set()
        for p, c in fleet_workers:
            if c.poll(600.0):
                for d in c.recv():
                    stats.append(ServeClientStats(**d))
            p.join(timeout=10.0)
            if p.is_alive():
                p.terminate()
    else:
        async def _fleet():
            tasks = [asyncio.create_task(_serve_client(
                r, server.port, shape, n_by_rank[r],
                sites[r % len(sites)] if sites else None, stats[r],
                pace_s, seed, start_stagger=r * 0.002))
                for r in range(1, num_clients + 1)]
            await asyncio.gather(*tasks)

        asyncio.run(_fleet())
    wall = time.monotonic() - t0
    fleet_done.set()
    fleet = _merge_serve_stats(stats)
    # the one nidt_client_rtt_ms publication path (shared helper with
    # the ingest fleet backfill)
    _publish_fleet_rtt(fleet.rtt_ms)

    audit = server.stop()
    # ---- compile pin: ONE program per (model, bucket), no recompiles;
    #      a SIGKILLed worker ships no bye, so its pin is unknowable
    #      and skipped (the root's counts still bound its requests) ----
    pin_ok = True
    total_compiles = total_recompiles = dispatches = 0
    requests_dispatched = slots = 0
    batches: dict[str, int] = {}
    compiled_programs: dict[str, list] = {}
    for wid, pw in sorted(audit["per_worker"].items()):
        eng = pw.get("engine")
        if eng is None:
            pin_ok = pin_ok and not pw["alive"]
            continue
        compiled_programs[wid] = eng["compiled"]
        total_compiles += eng["compiles"]
        total_recompiles += eng["recompiles"]
        dispatches += eng["dispatches"]
        requests_dispatched += eng["requests_dispatched"]
        for b, n in eng["batches"].items():
            batches[b] = batches.get(b, 0) + n
            slots += int(b) * n
        pin_ok = (pin_ok and eng["recompiles"] == 0
                  and eng["compiles"] == len(set(eng["compiled"])))
    if audit["dead_workers"] == 0:
        # cross-pin against the fan-in-merged compute-plane counter —
        # worker-labeled cells only: the root registry may carry serve
        # compiles of engines run in THIS process (tests), and a
        # killed worker's stale snapshot would skew it (hence the
        # dead_workers guard)
        snap = server.fanin.merged_snapshot().get(
            obs_names.COMPILES_TOTAL)
        metric_compiles = sum(
            c["value"] for c in (snap or {"values": []})["values"]
            if (c["labels"].get("engine") == "serve"
                and "worker" in c["labels"]))
        pin_ok = pin_ok and int(metric_compiles) == total_compiles

    # ---- routing proof: each site observed exactly one digest, and
    #      the digests differ across sites ----
    per_site = {site: sorted(d) for site, d in fleet.routes.items()}
    distinct = (len(per_site) >= 2
                and all(len(d) == 1 for d in per_site.values())
                and len({d[0] for d in per_site.values()})
                == len(per_site))

    received = audit["received"]
    client_exact = (fleet.sent
                    == fleet.ok + fleet.rejected + fleet.errors)
    # every client-confirmed reply had a server verdict; a killed
    # worker's unflushed tail (<= one flush interval) is the only
    # legitimate gap and is reported, not hidden
    unflushed = max(0, fleet.ok + fleet.rejected - received)
    reconciled = bool(
        audit["reconciled"] and client_exact
        and received <= fleet.sent
        and (unflushed == 0 or killed.is_set()))

    merged_text = server.fanin.prometheus_text()
    import re as _re

    result = {
        "mode": "serve",
        "bundle": serve_bundle,
        "model": manifest["model"],
        "model_version": manifest["source_round"],
        "precision": serve_precision or manifest["precision"],
        "num_clients": num_clients,
        "serve_workers": int(serve_workers),
        "batch_buckets": [int(b) for b in batch_buckets],
        "max_queue_ms": float(max_queue_ms),
        "serve_kill_at": (int(serve_kill_at) if serve_kill_at >= 0
                          else None),
        "worker_killed": killed.is_set(),
        "workers_live_at_end": server.live_workers(),
        "wall_s": round(wall, 3),
        "requests_target": total,
        "requests_sent": fleet.sent,
        "requests_ok": fleet.ok,
        "requests_rejected": fleet.rejected,
        "client_errors": fleet.errors,
        "client_reconnects": fleet.reconnects,
        "requests_per_s": round(fleet.ok / wall, 2) if wall else 0.0,
        "rtt_ms_p50": (round(float(np.percentile(fleet.rtt_ms, 50)), 2)
                       if fleet.rtt_ms else None),
        "rtt_ms_p99": (round(float(np.percentile(fleet.rtt_ms, 99)), 2)
                       if fleet.rtt_ms else None),
        "dispatches": dispatches,
        "batches": batches,
        "batch_occupancy": (round(requests_dispatched / slots, 3)
                            if slots else None),
        "compiled_programs": compiled_programs,
        "compiles_total": total_compiles,
        "recompiles_total": total_recompiles,
        "compile_pin_ok": bool(pin_ok),
        "routing": {"per_site": per_site,
                    "distinct_site_models": bool(distinct)},
        "serve_audit": audit,
        "unflushed_with_worker": unflushed,
        "frames_reconciled": reconciled,
        "obs_fanin": server.fanin.summary(),
        "merged_metrics": {
            "port": msrv.port if msrv is not None else None,
            "lines": len(merged_text.splitlines()),
            "worker_labeled": sorted(
                {int(m) for m in _re.findall(r'worker="(\d+)"',
                                             merged_text)}),
            "has_serve_latency":
                (obs_names.SERVE_LATENCY_MS + "_bucket") in merged_text,
            "has_rtt_samples":
                (obs_names.CLIENT_RTT_MS + "_bucket") in merged_text,
        },
    }
    if trace_out:
        obs_trace.disarm()
    if msrv is not None:
        msrv.close()
    return result


class _TimedSyncServer(FedAvgServer):
    """The round-synchronous baseline with advance timestamps, so both
    modes report the same p99 version-advance metric."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.advance_t: list[float] = []

    def _complete_round(self, n_clients, survivors=None):
        self.advance_t.append(time.monotonic())
        super()._complete_round(n_clients, survivors=survivors)


def run_load(mode: str = "async", num_clients: int = 200,
             aggregations: int = 20, buffer_k: int = 0,
             staleness_alpha: float = 0.5, max_staleness: int = 50,
             fault_spec: str = "", seed: int = 0,
             train_delay: float = 0.0, leaf_elems: int = 256,
             sync_round_deadline: float = 5.0,
             base_port: int | None = None,
             ingest_workers: int = 2,
             ingest_kill_at: int = -1,
             ingest_secure_quant: bool = False,
             regions: int = 0,
             ingest_shm: bool = False,
             sync_delta: bool = False,
             upload_local_scale: float = 0.0,
             fleet_procs: int = 1,
             trace_out: str = "",
             flight_out: str = "",
             metrics_port: int = 0,
             serve_bundle: str = "",
             serve_workers: int = 2,
             serve_requests: int = 0,
             serve_kill_at: int = -1,
             batch_buckets=(1, 2, 4, 8),
             max_queue_ms: float = 2.0,
             serve_precision: str = "") -> dict:
    """Drive ``num_clients`` simulated clients against one server and
    return the metrics dict. ``mode="async"`` runs the buffered server
    for ``aggregations`` aggregations of ``buffer_k`` uploads each;
    ``mode="sync"`` runs the round-synchronous server for the number of
    rounds that consumes a comparable upload volume; ``mode="ingest"``
    runs the SHARDED ingest plane (asyncfl/ingest.py):
    ``ingest_workers`` selector worker processes on one SO_REUSEPORT
    port folding partials into the root. ``ingest_kill_at >= 0``
    SIGKILLs worker 0 once the version reaches that value (the chaos
    cell — clients reconnect onto the surviving listeners and the
    audit must stay green, lost uploads accounted). ``fleet_procs > 1``
    shards the CLIENT fleet across that many processes (bench cells
    only — fault schedules need the in-process server probes and pin
    ``fleet_procs=1``); the same fleet drives every mode, so the
    comparison stays generator-fair.

    ISSUE 18 knobs (``mode="ingest"``): ``regions > 0`` runs the
    HIERARCHICAL tier — that many region sub-aggregator processes, each
    owning ``ingest_workers`` workers on the shared SO_REUSEPORT port
    (``ingest_kill_at`` then SIGKILLs REGION 0, the region chaos cell);
    ``ingest_shm`` hands partials to the parent over double-buffered
    shared-memory slabs instead of the pickled pipe; ``sync_delta``
    lets clients opt into lossless delta sync bodies;
    ``upload_local_scale > 0`` uploads ``synced + scale * canned``
    (the correlated-model regime the downlink-bytes cell measures)."""
    if mode == "serve":
        if fault_spec:
            raise ValueError(
                "mode='serve' does not take fault_spec; use "
                "serve_kill_at for the serving chaos cell")
        return _run_serve_load(
            num_clients=num_clients, serve_bundle=serve_bundle,
            serve_workers=serve_workers,
            serve_requests=serve_requests,
            serve_kill_at=serve_kill_at, batch_buckets=batch_buckets,
            max_queue_ms=max_queue_ms,
            serve_precision=serve_precision, seed=seed,
            fleet_procs=fleet_procs, base_port=base_port,
            metrics_port=metrics_port, trace_out=trace_out,
            flight_out=flight_out)
    if mode not in ("async", "sync", "ingest"):
        raise ValueError(
            f"mode must be async|sync|ingest|serve, got {mode!r}")
    port = base_port if base_port is not None else free_port_block(2)
    k = int(buffer_k) if buffer_k else num_clients
    init = canned_update_tree(0, leaf_elems)
    schedule = None
    if fault_spec:
        schedule = FaultSchedule(parse_fault_spec(fault_spec), seed)
    # send_timeout mirrors the server's own hardening: a simulated
    # client that stops draining must stall the dispatch thread for at
    # most 2 s, not the 30 s default — the p99 numbers exist to measure
    # the control plane, not one stuck peer
    comm = None
    quant = None
    if mode == "ingest":
        from neuroimagedisttraining_tpu.asyncfl.ingest import (
            ShardedIngestServer,
        )

        if ingest_secure_quant:
            from neuroimagedisttraining_tpu.privacy import QuantSpec

            quant = QuantSpec.from_bits(32, 10, 3)
            if upload_local_scale > 0:
                raise ValueError(
                    "upload_local_scale needs plaintext uploads built "
                    "from the synced model; secure_quant clients ship "
                    "pre-encoded field-element frames")
        if trace_out:
            # the harness process hosts BOTH the in-process client
            # fleet and the ingest root, so arming here captures the
            # client flow starts AND the root merge/aggregate spans;
            # workers arm their own tracers from the wcfg obs config
            obs_trace.arm(trace_out, tags={"role": "loadgen-root"})
        common_kw = dict(
            buffer_k=k, staleness_alpha=staleness_alpha,
            max_staleness=max_staleness, base_port=port,
            secure_quant=quant, trace_out=trace_out,
            flight_out=flight_out, use_shm=ingest_shm,
            sync_delta=sync_delta)
        if regions > 0:
            from neuroimagedisttraining_tpu.asyncfl.region import (
                HierarchicalIngestServer,
            )

            server = HierarchicalIngestServer(
                init, aggregations, num_clients, regions=regions,
                workers_per_region=ingest_workers, **common_kw)
        else:
            server = ShardedIngestServer(
                init, aggregations, num_clients,
                ingest_workers=ingest_workers, **common_kw)
        rounds = aggregations
    elif mode == "async":
        comm = SelectorCommManager(0, num_clients + 1, base_port=port,
                                   send_timeout=2.0)
        server = BufferedFedAvgServer(
            init, aggregations, num_clients, buffer_k=k,
            staleness_alpha=staleness_alpha, max_staleness=max_staleness,
            comm=comm)
        rounds = aggregations
    else:
        comm = SelectorCommManager(0, num_clients + 1, base_port=port,
                                   send_timeout=2.0)
        rounds = max(2, (aggregations * k) // num_clients)
        server = _TimedSyncServer(
            init, rounds, num_clients, comm=comm,
            round_deadline=sync_round_deadline,
            quorum=max(1, int(num_clients * 0.6)))
    server_thread = threading.Thread(target=server.run, daemon=True)

    stats = [ClientStats() for _ in range(num_clients + 1)]

    def version_probe():
        # LOCK-FREE by design: this is polled from the asyncio loop
        # every 20 ms by crashed clients, and taking the server's
        # _rlock here would freeze the whole fleet whenever the
        # dispatch thread holds it (jit compile, dial-out retries,
        # drain). A torn int read cannot happen in CPython, and the
        # poll only needs eventual progress, not a consistent snapshot.
        return server.round_idx

    server_done = server._done.is_set

    def report_corpse(rank):
        # stand-in for the heartbeat monitor's verdict: the harness
        # KNOWS the schedule just killed this client, so it marks the
        # corpse suspect directly instead of flooding the GIL-bound box
        # with per-client beat frames. Without this, a cohort-sized
        # buffer (buffer_k=0) plus one permanent crash can never fill —
        # _k_eff only shrinks on suspicion. Real deployments arm
        # --heartbeat_interval/--heartbeat_timeout for the same signal.
        # The ingest root keeps the same _suspect/_k_eff machinery; its
        # event loop re-checks the harvest trigger on its next tick.
        if mode in ("async", "ingest"):
            with server._rlock:
                server._suspect.add(rank)
                server._maybe_complete()
        # the sync server's deadline/quorum path handles corpses itself

    def client_payload(r):
        return bench_payload(r, leaf_elems, quant, seed)

    async def _fleet():
        # ~500 connects/s ramp: enough to dodge backlog overflow, short
        # against the measured window
        tasks = [asyncio.create_task(_run_client(
            r, port, client_payload(r), float(8 + r % 5),
            stats[r], schedule, version_probe, server_done, train_delay,
            start_stagger=r * 0.002, report_corpse=report_corpse,
            reconnect=(mode == "ingest"),
            incarnation=(r if mode == "ingest" else None),
            sync_delta=(sync_delta and mode == "ingest"),
            local_scale=upload_local_scale))
            for r in range(1, num_clients + 1)]
        await asyncio.gather(*tasks)

    if fleet_procs > 1 and (schedule is not None or mode == "sync"):
        raise ValueError(
            "fleet_procs > 1 drives bench cells only: fault schedules "
            "need the in-process server probes (version_probe/"
            "report_corpse) and the sync server's barrier needs the "
            "single fleet's completion semantics")
    fleet_workers: list[tuple] = []
    ready_go = done_ev = None
    if fleet_procs > 1:
        # spawn + import + payload build happen BEFORE t0 (children
        # signal ready, then wait for go) — interpreter startup never
        # leaks into the measured accept window
        ctx = mp.get_context("spawn")
        ready_go, done_ev = ctx.Event(), ctx.Event()
        slices = np.array_split(np.arange(1, num_clients + 1),
                                fleet_procs)
        for sl in slices:
            parent_c, child_c = ctx.Pipe(duplex=False)
            p = ctx.Process(
                target=_fleet_proc_main,
                args=(child_c, [int(r) for r in sl], port, leaf_elems,
                      quant is not None, seed, train_delay, ready_go,
                      done_ev, mode == "ingest", mode == "ingest",
                      sync_delta and mode == "ingest",
                      upload_local_scale),
                daemon=True, name="nidt-loadgen-fleet")
            p.start()
            child_c.close()
            fleet_workers.append((p, parent_c))
        for p, c in fleet_workers:
            if not c.poll(300.0) or c.recv() != "ready":
                raise RuntimeError("loadgen fleet shard failed to start")

    msrv = None
    if metrics_port and mode == "ingest":
        # the MERGED view (root + worker-labeled samples + staleness
        # gauges) — what a live scrape of the sharded plane should see
        from neuroimagedisttraining_tpu.obs.http import MetricsServer

        msrv = MetricsServer(max(0, int(metrics_port)),
                             registry=server.metrics_view())

    t0 = time.monotonic()
    server_thread.start()
    if mode == "ingest" and ingest_kill_at >= 0:
        def _kill_watch():
            # the chaos cell: SIGKILL worker 0 (region 0 in the
            # hierarchical tier — worker_pids[0] is the region process)
            # once the version reaches the trigger — its clients
            # reconnect onto the surviving SO_REUSEPORT listeners and
            # the audit must stay green
            while not server_done():
                if server.round_idx >= ingest_kill_at:
                    try:
                        os.kill(server.worker_pids[0], signal.SIGKILL)
                    except (OSError, IndexError):
                        pass
                    return
                time.sleep(0.02)

        threading.Thread(target=_kill_watch, daemon=True).start()
    if fleet_procs > 1:
        ready_go.set()
        if not server._done.wait(timeout=600.0):
            log.warning("loadgen: server not done after 600s; "
                        "collecting what the fleet has")
        done_ev.set()
        for p, c in fleet_workers:
            if c.poll(60.0):
                for d in c.recv():
                    stats.append(ClientStats(**d))
            p.join(timeout=10.0)
            if p.is_alive():
                p.terminate()
    else:
        asyncio.run(_fleet())
    server_thread.join(timeout=60.0)
    wall = time.monotonic() - t0

    fleet = ClientStats()
    for s in stats:
        for f in dataclasses.fields(ClientStats):
            setattr(fleet, f.name,
                    getattr(fleet, f.name) + getattr(s, f.name))
    if fleet_procs > 1:
        # sharded fleets ran EVERY client in spawned processes whose
        # registries never ship home — backfill their RTT samples into
        # this process's histogram so the merged scrape still carries
        # the distribution (in-process fleets observed live above, and
        # run exactly one of the two paths, so no double count); the
        # serve fleet reuses the same publication path
        _publish_fleet_rtt(fleet.rtt_ms)
    if mode in ("async", "ingest"):
        adv_t = [h["t"] for h in server.history]
        accepted = server.upload_stats["accepted"]
        audit = server.upload_audit()
        received = server.upload_stats["received"]
    else:
        adv_t = server.advance_t
        accepted = sum(h["clients"] for h in server.history)
        # the sync server keeps no received/drop counters: a deadline-
        # advanced round legitimately drops late uploads as stale, so
        # `accepted` is a LOWER bound on received, not a proxy for it —
        # only the one-sided bound below is provable in sync mode
        received = None
        audit = {"received_accounted": True, "accepted_accounted": True}
    deltas_ms = (1e3 * np.diff(np.asarray(adv_t))
                 if len(adv_t) >= 2 else np.asarray([]))
    # sustained ingest throughput: uploads that reached an aggregation,
    # over the window from fleet start to the LAST aggregation — the
    # teardown tail (FINISH fan-out, worker joins) measures shutdown,
    # not the ingest plane, and its variance would swamp short cells
    aggregated_hist = sum(h["clients"] for h in server.history)
    accept_window = (adv_t[-1] - t0) if adv_t else None
    sustained = (round(aggregated_hist / accept_window, 2)
                 if accept_window else None)
    buffered_modes = ("async", "ingest")
    result = {
        "mode": mode,
        "num_clients": num_clients,
        "buffer_k": k if mode in buffered_modes else None,
        "staleness_alpha": (staleness_alpha if mode in buffered_modes
                            else None),
        "max_staleness": (max_staleness if mode in buffered_modes
                          else None),
        "rounds_or_aggregations": len(server.history),
        "target": aggregations if mode == "async" else rounds,
        "fault_spec": fault_spec,
        "wall_s": round(wall, 3),
        "uploads_sent": fleet.sent,
        "uploads_accepted": accepted,
        "uploads_per_s": round(accepted / wall, 2) if wall else 0.0,
        "uploads_per_s_sustained": sustained,
        "accept_window_s": (round(accept_window, 3)
                            if accept_window else None),
        "sent_per_s": round(fleet.sent / wall, 2) if wall else 0.0,
        "aggregations_per_s": (round(len(server.history) / wall, 3)
                               if wall else 0.0),
        "version_advance_p50_ms": (round(float(
            np.percentile(deltas_ms, 50)), 2) if deltas_ms.size else None),
        "version_advance_p99_ms": (round(float(
            np.percentile(deltas_ms, 99)), 2) if deltas_ms.size else None),
        # client-observed upload->sync round-trip (sampled every 4th):
        # the per-upload service latency of the whole plane, the number
        # that localizes a throughput ceiling (queueing at the server
        # side shows here long before any process pegs a core)
        "rtt_ms_p50": (round(float(np.percentile(fleet.rtt_ms, 50)), 2)
                       if fleet.rtt_ms else None),
        "rtt_ms_p99": (round(float(np.percentile(fleet.rtt_ms, 99)), 2)
                       if fleet.rtt_ms else None),
        "peak_connections": (server.peak_connection_estimate()
                             if mode == "ingest"
                             else comm.peak_connections),
        "client_stats": {k: v for k, v in
                         dataclasses.asdict(fleet).items()
                         if k != "rtt_ms"},
        "byte_stats": (server.worker_byte_stats() if mode == "ingest"
                       else comm.byte_stats()),
        "upload_audit": audit,
        # async: every client has at most one upload in flight when the
        # server stops reading, so sent can exceed received by at most
        # the fleet size — anything else is a lost or double-counted
        # frame. Ingest: a killed worker's socket buffers can hold any
        # number of sent-but-never-read frames, so only the one-sided
        # received <= sent bound is provable (the audit itself is the
        # zero-lost/zero-double-counted check). Sync: the server keeps
        # no received counter (deadline rounds drop stale uploads by
        # design), so only accepted <= sent is provable.
        "frames_reconciled": bool(
            audit["received_accounted"] and audit["accepted_accounted"]
            and (accepted <= fleet.sent if received is None
                 else (received <= fleet.sent
                       and (mode == "ingest"
                            or fleet.sent - received <= num_clients)))),
        "staleness_hist": (_staleness_hist(server.history)
                           if mode in buffered_modes else None),
    }
    if mode == "ingest":
        result["ingest_workers"] = int(ingest_workers)
        result["ingest_kill_at"] = (int(ingest_kill_at)
                                    if ingest_kill_at >= 0 else None)
        result["workers_live_at_end"] = server.live_workers()
        result["secure_quant"] = bool(ingest_secure_quant)
        result["lost_with_worker"] = int(
            server.upload_stats["lost_with_worker"])
        # ---- hierarchical tier / transport cells (ISSUE 18) ----
        result["ingest_shm"] = bool(ingest_shm)
        result["sync_delta"] = bool(sync_delta)
        result["upload_local_scale"] = (float(upload_local_scale)
                                        if upload_local_scale else None)
        xstats = server.worker_xstats()
        result["worker_xstats"] = xstats
        for kind in ("shm", "pipe"):
            n = xstats.get(f"{kind}_exports", 0)
            result[f"{kind}_export_us_mean"] = (
                round(xstats.get(f"{kind}_export_ns", 0) / n / 1e3, 1)
                if n else None)
        if regions > 0:
            result["regions"] = int(regions)
            result["workers_per_region"] = int(ingest_workers)
            result["lost_with_region"] = int(
                server.upload_stats["lost_with_region"])
        # ---- federation-wide obs summary (ISSUE 13) ----
        result["obs_fanin"] = server.fanin.summary()
        merged_text = server.fanin.prometheus_text()
        import re as _re

        result["merged_metrics"] = {
            "port": msrv.port if msrv is not None else None,
            "lines": len(merged_text.splitlines()),
            "worker_labeled": sorted(
                {int(m) for m in _re.findall(r'worker="(\d+)"',
                                             merged_text)}),
            "region_labeled": sorted(
                {int(m) for m in _re.findall(r'region="(\d+)"',
                                             merged_text)}),
            "has_stage_samples":
                (obs_names.UPLOAD_STAGE_MS + "_bucket") in merged_text,
            "has_rtt_samples": (obs_names.CLIENT_RTT_MS + "_bucket") in merged_text,
        }
        if trace_out:
            flows = obs_fanin.linked_flow_ids(
                server.fanin.merged_trace_events())
            result["merged_trace"] = {
                "path": trace_out,
                "flow_started": len(flows["s"]),
                "flow_stepped": len(flows["t"]),
                "flow_ended": len(flows["f"]),
                "flow_linked": len(flows["linked"]),
            }
            obs_trace.disarm()
    if msrv is not None:
        msrv.close()
    return result


def _staleness_hist(history: list[dict]) -> dict[str, int]:
    hist: dict[str, int] = {}
    for h in history:
        for tau in h.get("taus", ()):
            hist[str(tau)] = hist.get(str(tau), 0) + 1
    return hist


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="neuroimagedisttraining_tpu.asyncfl.loadgen",
        description=__doc__.split("\n\n")[0])
    ap.add_argument("--clients", type=int, default=1000)
    ap.add_argument("--mode", choices=("async", "sync", "both", "ingest",
                                       "ingest_bench", "region_bench",
                                       "serve"),
                    default="both",
                    help="ingest = one sharded-plane run at "
                         "--ingest_workers; ingest_bench = the headline "
                         "sweep (single-process async baseline, then "
                         "ingest at N in {1, 2, 4} workers) -> "
                         "bench_matrix/ingest_bench.json; region_bench "
                         "= the hierarchical-tier matrix (tree "
                         "throughput, shm-vs-pipe hand-off, downlink "
                         "delta-sync bytes) -> "
                         "bench_matrix/region_bench.json; serve = "
                         "open-loop request fleet against the serving "
                         "plane (--serve_bundle) -> "
                         "bench_matrix/serve_bench.json")
    ap.add_argument("--aggregations", type=int, default=30,
                    help="async: buffered aggregations to run; the sync "
                         "baseline runs the round count consuming a "
                         "comparable upload volume")
    ap.add_argument("--buffer_k", type=int, default=50,
                    help="aggregate every K accepted uploads (0 = "
                         "cohort size)")
    ap.add_argument("--staleness_alpha", type=float, default=0.5)
    ap.add_argument("--max_staleness", type=int, default=50)
    ap.add_argument("--fault_spec", type=str, default="",
                    help="seeded churn, e.g. 'crash:7@3,rejoin:7@10,"
                         "straggle:0.1:0.05'")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--train_delay", type=float, default=0.0,
                    help="seconds each client 'trains' per round")
    ap.add_argument("--leaf_elems", type=int, default=256)
    ap.add_argument("--ingest_workers", type=int, default=2,
                    help="selector worker processes for --mode ingest")
    ap.add_argument("--ingest_kill_at", type=int, default=-1,
                    help="SIGKILL ingest worker 0 at this version "
                         "(chaos cell; -1 = never)")
    ap.add_argument("--ingest_secure_quant", action="store_true",
                    help="clients ship secure-quant field-element "
                         "frames; workers fold SlotAccumulator chunks")
    ap.add_argument("--regions", type=int, default=0,
                    help="mode ingest/region_bench: run the "
                         "HIERARCHICAL tier with this many region "
                         "sub-aggregator processes, each owning "
                         "--ingest_workers workers (0 = flat root)")
    ap.add_argument("--ingest_shm", action="store_true",
                    help="hand worker partials to the parent over "
                         "double-buffered shared-memory slabs instead "
                         "of the pickled pipe")
    ap.add_argument("--sync_delta", action="store_true",
                    help="clients opt into lossless delta sync bodies "
                         "(changed-version replies ship the byte delta "
                         "against the client's last-synced version)")
    ap.add_argument("--upload_local_scale", type=float, default=0.0,
                    help="clients upload synced + SCALE * canned "
                         "instead of the bare canned tree (the "
                         "correlated-model regime of the downlink-"
                         "bytes cell); 0 = canned uploads")
    ap.add_argument("--downlink_clients", type=int, default=600,
                    help="mode region_bench: fleet size of the two "
                         "downlink-bytes cells (they measure bytes "
                         "per changed-version sync, not throughput)")
    ap.add_argument("--downlink_aggregations", type=int, default=80,
                    help="mode region_bench: aggregation count of the "
                         "two downlink-bytes cells")
    ap.add_argument("--downlink_leaf_elems", type=int, default=4096,
                    help="mode region_bench: model size of the two "
                         "downlink-bytes cells (large enough that the "
                         "message envelope does not dominate)")
    ap.add_argument("--serve_bundle", type=str, default="",
                    help="mode serve: deployment-bundle directory "
                         "(python -m neuroimagedisttraining_tpu.serve "
                         "--from_checkpoint ... --build_only)")
    ap.add_argument("--serve_workers", type=int, default=2,
                    help="mode serve: HTTP worker processes on the "
                         "shared SO_REUSEPORT port")
    ap.add_argument("--serve_requests", type=int, default=0,
                    help="mode serve: total requests across the fleet "
                         "(0 = 2 per client)")
    ap.add_argument("--serve_kill_at", type=int, default=-1,
                    help="SIGKILL serve worker 0 once this many "
                         "requests were served (chaos cell; -1 = "
                         "never)")
    ap.add_argument("--batch_buckets", type=str, default="1,2,4,8",
                    help="mode serve: declared batch sizes, e.g. "
                         "1,2,4,8")
    ap.add_argument("--max_queue_ms", type=float, default=2.0,
                    help="mode serve: max wait of the oldest queued "
                         "request for batch-mates")
    ap.add_argument("--serve_precision", type=str, default="",
                    choices=("", "bf16", "fp32"),
                    help="mode serve: serving precision override "
                         "('' = as stored in the bundle)")
    ap.add_argument("--metrics_port", type=int, default=0,
                    help="ingest modes: serve the MERGED /metrics "
                         "(root + worker-labeled samples + staleness "
                         "gauges, obs/fanin.py) on this port during "
                         "the run; 0 = off")
    ap.add_argument("--trace_out", type=str, default="",
                    help="ingest modes: write the MERGED Chrome trace "
                         "(client flow starts + worker admission spans "
                         "+ root aggregate spans, clock-aligned) to "
                         "this path; workers write .wN-suffixed local "
                         "secondaries")
    ap.add_argument("--flight_out", type=str, default="",
                    help="ingest modes: write the MERGED flight dump "
                         "(per-worker provenance) to this path")
    ap.add_argument("--fleet_procs", type=int, default=0,
                    help="shard the client fleet across N processes "
                         "(one asyncio loop is ~a core of syscalls on "
                         "this box — a single-process fleet measures "
                         "itself); 0 = 3 for the bench modes, 1 "
                         "otherwise. Incompatible with --fault_spec")
    ap.add_argument("--out", type=str, default="",
                    help="write the JSON cell here (bench_matrix/"
                         "async_bench.json)")
    args = ap.parse_args(argv)

    fleet_procs = args.fleet_procs
    if fleet_procs == 0:
        fleet_procs = (3 if args.mode in ("ingest_bench", "region_bench",
                                          "serve")
                       and not args.fault_spec else 1)
    common = dict(
        num_clients=args.clients, aggregations=args.aggregations,
        buffer_k=args.buffer_k, staleness_alpha=args.staleness_alpha,
        max_staleness=args.max_staleness, fault_spec=args.fault_spec,
        seed=args.seed, train_delay=args.train_delay,
        leaf_elems=args.leaf_elems, fleet_procs=fleet_procs)
    cells = {}
    if args.mode == "ingest_bench":
        # the headline sweep (ISSUE 12): the committed single-process
        # selector baseline, then the sharded plane at N in {1, 2, 4}
        # workers on the SAME cohort/churn/buffer configuration
        cells["async"] = run_load(mode="async", **common)
        print(json.dumps(cells["async"]), flush=True)
        for n in (1, 2, 4):
            cells[f"ingest_w{n}"] = run_load(
                mode="ingest", ingest_workers=n,
                ingest_secure_quant=args.ingest_secure_quant, **common)
            print(json.dumps(cells[f"ingest_w{n}"]), flush=True)
    elif args.mode == "region_bench":
        # the hierarchical-tier matrix (ISSUE 18). The two TREE cells
        # run the committed ingest_bench configuration so the headline
        # number is comparable to the committed single-root cells, and
        # differ ONLY in the partial hand-off transport (the shm-vs-
        # pipe A/B). The two DOWNLINK cells measure bytes per changed-
        # version sync reply in the small-local-update regime
        # (synced + 1e-6 * canned uploads): that is the federated-
        # training dynamics where consecutive versions correlate and a
        # lossless delta can beat the dense body — the stock canned
        # uploads drive the aggregate on a random walk whose version-
        # to-version XOR is incompressible and would measure nothing
        # about the transport.
        tree = dict(regions=(args.regions or 2),
                    ingest_workers=args.ingest_workers)
        cells["tree_shm"] = run_load(mode="ingest", ingest_shm=True,
                                     **tree, **common)
        print(json.dumps(cells["tree_shm"]), flush=True)
        cells["tree_pipe"] = run_load(mode="ingest", **tree, **common)
        print(json.dumps(cells["tree_pipe"]), flush=True)
        dl = dict(common)
        dl.update(num_clients=args.downlink_clients,
                  aggregations=args.downlink_aggregations,
                  leaf_elems=args.downlink_leaf_elems,
                  upload_local_scale=(args.upload_local_scale or 1e-6))
        cells["downlink_delta"] = run_load(mode="ingest",
                                           sync_delta=True, **tree, **dl)
        print(json.dumps(cells["downlink_delta"]), flush=True)
        cells["downlink_dense"] = run_load(mode="ingest", **tree, **dl)
        print(json.dumps(cells["downlink_dense"]), flush=True)
    else:
        modes = (("async", "sync") if args.mode == "both"
                 else (args.mode,))
        for mode in modes:
            kw = dict(common)
            if mode == "ingest":
                kw.update(ingest_workers=args.ingest_workers,
                          ingest_kill_at=args.ingest_kill_at,
                          ingest_secure_quant=args.ingest_secure_quant,
                          regions=args.regions,
                          ingest_shm=args.ingest_shm,
                          sync_delta=args.sync_delta,
                          upload_local_scale=args.upload_local_scale,
                          metrics_port=args.metrics_port,
                          trace_out=args.trace_out,
                          flight_out=args.flight_out)
            elif mode == "serve":
                kw.update(
                    serve_bundle=args.serve_bundle,
                    serve_workers=args.serve_workers,
                    serve_requests=args.serve_requests,
                    serve_kill_at=args.serve_kill_at,
                    batch_buckets=tuple(
                        int(b) for b in args.batch_buckets.split(",")
                        if b.strip()),
                    max_queue_ms=args.max_queue_ms,
                    serve_precision=args.serve_precision,
                    metrics_port=args.metrics_port,
                    trace_out=args.trace_out,
                    flight_out=args.flight_out)
            cells[mode] = run_load(mode=mode, **kw)
            print(json.dumps(cells[mode]), flush=True)
    bench_name = ("ingest_plane" if args.mode == "ingest_bench"
                  else "region_tier" if args.mode == "region_bench"
                  else "serve_plane" if args.mode == "serve"
                  else "async_control_plane")
    out = {"bench": bench_name, **cells}
    if args.mode == "serve":
        c = cells["serve"]
        out["summary"] = {
            "audits_green": bool(c["serve_audit"]["reconciled"]
                                 and c["frames_reconciled"]),
            "requests_per_s": c["requests_per_s"],
            "compile_pin_ok": c["compile_pin_ok"],
            "distinct_site_models":
                c["routing"]["distinct_site_models"],
            "fleet_procs": fleet_procs,
        }
        print(json.dumps({"summary": out["summary"]}), flush=True)
    if args.mode == "ingest_bench":
        base = cells["async"]["uploads_per_s_sustained"]
        # the ISSUE's yardstick is the COMMITTED single-process selector
        # baseline (bench_matrix/async_bench.json, PR 7 — the "~250
        # uploads/s GIL saturation" the motivation cites); the in-run
        # async cell is also reported, but it already carries this PR's
        # selector-core optimizations (wake dedup, lock-free-flush,
        # optimistic send) and the sharded loadgen fleet, so it is a
        # moving target, not the committed one
        committed = None
        try:
            with open("bench_matrix/async_bench.json") as f:
                committed = json.load(f)["async"]["uploads_per_s"]
        except (OSError, KeyError, ValueError):
            pass
        out["summary"] = {
            "baseline_uploads_per_s": base,
            "committed_baseline_uploads_per_s": committed,
            **{f"speedup_w{n}": (round(
                cells[f"ingest_w{n}"]["uploads_per_s_sustained"] / base,
                2) if base else None) for n in (1, 2, 4)},
            **{f"speedup_w{n}_vs_committed": (round(
                cells[f"ingest_w{n}"]["uploads_per_s_sustained"]
                / committed, 2) if committed else None)
               for n in (1, 2, 4)},
            "audits_green": all(c["upload_audit"]["received_accounted"]
                                and c["upload_audit"]["accepted_accounted"]
                                for c in cells.values()),
            "fleet_procs": fleet_procs,
            "notes": (
                "2-core box, sandboxed kernel (~0.5-1 ms per socket "
                "syscall measured): a raw asyncio echo of the same "
                "1k-connection ping-pong pattern ceilings at ~1500-1800 "
                "roundtrips/s with ZERO application logic, and the "
                "client fleet is the binding constraint above ~2 "
                "workers (sharded across fleet_procs processes so the "
                "generator does not measure itself). Worker counts "
                "above the core count oversubscribe; the knee on this "
                "box is N=2."),
        }
        print(json.dumps({"summary": out["summary"]}), flush=True)
    if args.mode == "region_bench":
        # yardstick: the best COMMITTED flat single-root cell — the
        # number the tree must not regress (ISSUE 18 acceptance)
        committed = None
        try:
            with open("bench_matrix/ingest_bench.json") as f:
                ib = json.load(f)
            committed = max(
                ib[f"ingest_w{n}"]["uploads_per_s_sustained"]
                for n in (1, 2, 4) if f"ingest_w{n}" in ib)
        except (OSError, KeyError, ValueError):
            pass
        ts, tp = cells["tree_shm"], cells["tree_pipe"]
        dd, dn = cells["downlink_delta"], cells["downlink_dense"]

        def _per_changed_sync(c):
            cs = c["client_stats"]
            return (round(cs["sync_body_bytes"] / cs["sync_bodies"], 1)
                    if cs["sync_bodies"] else None)

        delta_b = _per_changed_sync(dd)
        dense_b = _per_changed_sync(dn)
        ratio = (round(dense_b / delta_b, 2)
                 if delta_b and dense_b else None)
        tree_sustained = ts["uploads_per_s_sustained"]
        out["summary"] = {
            "regions": ts["regions"],
            "workers_per_region": ts["workers_per_region"],
            "committed_single_root_uploads_per_s": committed,
            "tree_uploads_per_s_sustained": tree_sustained,
            "tree_at_least_committed_single_root": bool(
                committed and tree_sustained
                and tree_sustained >= committed),
            "shm_export_us_mean": ts["shm_export_us_mean"],
            "pipe_export_us_mean": tp["pipe_export_us_mean"],
            "shm_fallback_busy": ts["worker_xstats"].get(
                "shm_fallback_busy", 0),
            "shm_beats_pipe": bool(
                ts["shm_export_us_mean"] and tp["pipe_export_us_mean"]
                and ts["shm_export_us_mean"]
                < tp["pipe_export_us_mean"]),
            "sync_body_bytes_per_changed_sync_delta": delta_b,
            "sync_body_bytes_per_changed_sync_dense": dense_b,
            "delta_sync_bytes_ratio": ratio,
            "delta_sync_3x": bool(ratio and ratio >= 3.0),
            # HONEST fallback accounting: every changed-version reply
            # the delta cell shipped dense anyway, and every delta the
            # clients had to reject, are right here — a 3x claim that
            # hid them behind the mean would be a lie
            "delta_syncs": dd["client_stats"]["delta_syncs"],
            "delta_errors": dd["client_stats"]["delta_errors"],
            "sync_delta_sent": dd["worker_xstats"].get(
                "sync_delta_sent", 0),
            "sync_dense_sent": dd["worker_xstats"].get(
                "sync_dense_sent", 0),
            "sync_dense_fallback_ring": dd["worker_xstats"].get(
                "sync_dense_fallback_ring", 0),
            "lost_with_region": ts["lost_with_region"],
            "audits_green": all(
                c["upload_audit"]["received_accounted"]
                and c["upload_audit"]["accepted_accounted"]
                and c["frames_reconciled"] for c in cells.values()),
            "fleet_procs": fleet_procs,
        }
        print(json.dumps({"summary": out["summary"]}), flush=True)
    if "async" in cells and "sync" in cells:
        a, s = cells["async"], cells["sync"]
        out["summary"] = {
            "uploads_per_s_ratio": (round(a["uploads_per_s"]
                                          / s["uploads_per_s"], 2)
                                    if s["uploads_per_s"] else None),
            "p99_advance_ratio": (round(s["version_advance_p99_ms"]
                                        / a["version_advance_p99_ms"], 2)
                                  if a["version_advance_p99_ms"]
                                  and s["version_advance_p99_ms"]
                                  else None),
        }
        print(json.dumps({"summary": out["summary"]}), flush=True)
    if args.out:
        d = os.path.dirname(args.out)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
    ok = all(c["frames_reconciled"] for c in cells.values())
    return 0 if ok else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
