"""Hierarchical aggregation tier: regional sub-aggregators (ISSUE 18).

The sharded ingest plane (asyncfl/ingest.py) tops out at one root
merging N workers' partials. This module promotes the exact int64
partial-fold algebra ONE level — ROADMAP item 2's "the fold IS the
sub-aggregator contract" made literal:

    clients -> ingest workers -> REGION sub-aggregators -> root

Each region is a PROCESS owning its own SO_REUSEPORT worker fleet (the
same ``_ingest_worker_main`` workers, gate for gate — admission and the
int64 ``PartialAccumulator`` fold run at the edge, dense and
``--secure_quant`` alike). The region merges its workers' partials
locally and ships ONE merged partial upstream per root flush, with
headroom pulls in between (``flush_interval``) so worker accumulators
stay small. Because int64 addition is exact, commutative and
associative, the root's merge of region partials in region-id order is
BITWISE the single-root fold for ANY (region x worker) partitioning —
the PR 12 pin, promoted one level (tests/test_region.py).

Topology contract: a region speaks the EXACT worker pipe protocol
upstream (ready/vb/beats/obs/clock_reply/reg/partial/bye + the
region-only ``wdead``), so ``HierarchicalIngestServer`` reuses the
whole ``ShardedIngestServer`` event loop — the only override points are
child spawning, a few event kinds, and the region-labeled telemetry.
The upstream link is a multiprocessing pipe today but carries only
pickled control/partial frames (never shm handles), so a region can
later live on another host behind a socket shim without protocol
changes.

Transport: worker->region partials ride the double-buffered
shared-memory slabs when ``use_shm`` is on (the region is the workers'
parent and attaches their slabs exactly as the flat root does);
region->root partials stay pickled — the documented cross-host
fallback path, exercised by construction.

Failure plane: a SIGKILLed REGION takes its workers with it (they see
pipe EOF and exit); its clients reconnect onto the surviving regions'
listeners (same port, SO_REUSEPORT) and the root accounts the buffered
loss as ``lost_with_region``. A worker dying INSIDE a region is
reported upstream as ``wdead`` and accounted ``lost_with_worker`` —
the audit reconciles both, zero silently lost, zero double-counted.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import time

import numpy as np

from neuroimagedisttraining_tpu.asyncfl.ingest import (
    PartialAccumulator, ShardedIngestServer, _ingest_worker_main,
    _ShmSlabReader, model_sizes)
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as obs_names
from neuroimagedisttraining_tpu.obs import fanin as obs_fanin

__all__ = ["HierarchicalIngestServer", "REGION_FLUSH_INTERVAL_S"]

log = logging.getLogger("neuroimagedisttraining_tpu.asyncfl")

#: how often a region pulls its workers' partials into the staged
#: accumulator between root flushes — keeps worker-held state (and the
#: loss window of a worker crash) bounded without ever shipping
#: upstream on its own (the ROOT owns buffer_k; an unsolicited region
#: partial would double-trigger harvests)
REGION_FLUSH_INTERVAL_S = 0.25


# ---------------------------------------------------------------------------
# region process
# ---------------------------------------------------------------------------


def _region_main(rid: int, conn, rcfg: dict) -> None:
    """Spawned region entry point (spawn context — fresh interpreter).
    NON-daemonic: a region spawns its own worker fleet, which a
    daemonic process may not; it exits on upstream pipe EOF instead."""
    relay = _RegionRelay(rid, conn, rcfg)
    try:
        relay.run()
    except Exception:  # noqa: BLE001 — log the real error before the
        # process dies; the root sees the sentinel either way
        log.exception("ingest region %d crashed", rid)
        raise


class _RegionRelay:
    """One regional sub-aggregator: worker fleet owner downstream, a
    protocol-faithful 'worker' upstream. Single-threaded event loop —
    every pipe is written from exactly one thread by construction."""

    def __init__(self, rid: int, conn, rcfg: dict):
        self.rid = int(rid)
        self.conn = conn
        self.wpr = int(rcfg["workers_per_region"])
        self.flush_interval = float(
            rcfg.get("flush_interval", REGION_FLUSH_INTERVAL_S))
        self.spawn_timeout = float(rcfg.get("spawn_timeout", 180.0))
        wcfg = rcfg["wcfg"]
        self.spec = wcfg["spec"]
        self.sizes = model_sizes(wcfg["init_params"])
        self._fold_splits = np.cumsum(
            [n for _, n in self.sizes])[:-1]
        #: worker partials merged here between upstream flushes; reset
        #: on every upstream ship
        self.staged = PartialAccumulator(self.spec, self.sizes)
        self.staged_entries: list[tuple] = []
        #: root-triggered collection in flight:
        #: {"rseq": root's flush seq, "seq": internal flush seq,
        #:  "waiting": live wids yet to answer}
        self._pending: dict | None = None
        self._flush_seq = 0
        self._last_headroom = time.monotonic()
        #: c -> wid that last registered it (seqfloor routing)
        self._route: dict[int, int] = {}
        self._announced = False
        self._upq: list[tuple] = []
        self._finishing = False
        self._finish_deadline = 0.0
        self._stop = False
        # ---- worker fleet (global wids: rid*wpr + k) ----
        ctx = mp.get_context("spawn")
        self._workers: dict[int, dict] = {}
        for k in range(self.wpr):
            wid = self.rid * self.wpr + k
            parent, child = ctx.Pipe(duplex=True)
            proc = ctx.Process(
                target=_ingest_worker_main, args=(wid, child, wcfg),
                daemon=True, name=f"nidt-ingest-r{self.rid}-w{wid}")
            proc.start()
            child.close()
            self._workers[wid] = {
                "proc": proc, "conn": parent, "alive": True,
                "ready": False, "acc": 0, "folded": 0, "residual": 0,
                "bye": False, "stats": None, "byte_stats": None,
                "peak_conns": 0, "xstats": None, "shm": None,
            }

    # ---- upstream (buffered until the region's own ready) ----

    def _send_up(self, ev: tuple) -> None:
        if not self._announced:
            self._upq.append(ev)
            return
        try:
            self.conn.send(ev)  # nidt: allow[lock-send] -- the region relay is single-threaded: one loop thread owns every pipe end, sequentially
        except (BrokenPipeError, OSError):
            self._on_root_gone("upstream send failed")

    def _announce_ready(self) -> None:
        self.conn.send(("ready", self.rid))  # nidt: allow[lock-send] -- the region relay is single-threaded: one loop thread owns every pipe end, sequentially
        self._announced = True
        for ev in self._upq:
            self.conn.send(ev)  # nidt: allow[lock-send] -- the region relay is single-threaded: one loop thread owns every pipe end, sequentially
        self._upq = []
        log.info("ingest region %d: %d workers ready", self.rid,
                 self.wpr)

    # ---- event loop ----

    def run(self) -> None:
        deadline = time.monotonic() + self.spawn_timeout
        while not all(w["ready"] for w in self._workers.values()):
            if time.monotonic() > deadline:
                self._kill_workers()
                raise RuntimeError(
                    f"region {self.rid}: workers not ready within "
                    f"{self.spawn_timeout}s")
            self._wait_once(timeout=0.1)
            if self._stop:
                return
        self._announce_ready()
        while not self._stop:
            self._wait_once(timeout=0.05)
            self._tick()

    def _wait_once(self, timeout: float) -> None:
        conns = {w["conn"]: wid for wid, w in self._workers.items()
                 if w["alive"]}
        sentinels = {w["proc"].sentinel: wid
                     for wid, w in self._workers.items() if w["alive"]}
        try:
            ready = mp.connection.wait(
                [self.conn] + list(conns) + list(sentinels),
                timeout=timeout)
        except OSError:
            ready = []
        # worker pipes BEFORE sentinels (the root's rule): a dead
        # worker's buffered events are uploads that are NOT lost
        for obj in ready:
            if obj in conns:
                self._drain_worker(conns[obj])
        for obj in ready:
            if obj in sentinels:
                self._mark_worker_dead(sentinels[obj],
                                       "process exited")
        for obj in ready:
            if obj is self.conn:
                self._drain_root()

    def _tick(self) -> None:
        now = time.monotonic()
        if (not self._finishing and self._pending is None
                and now - self._last_headroom >= self.flush_interval):
            # headroom pull: flush workers into the staged accumulator
            # WITHOUT shipping upstream (the root owns buffer_k)
            self._last_headroom = now
            self._flush_seq += 1
            self._broadcast(("flush", self._flush_seq))
        if self._finishing:
            done = all(w["bye"] or not w["alive"]
                       for w in self._workers.values())
            if done or now > self._finish_deadline:
                self._send_merged_bye()

    def _broadcast(self, cmd: tuple) -> list[int]:
        sent = []
        for wid, w in self._workers.items():
            if not w["alive"]:
                continue
            try:
                w["conn"].send(cmd)  # nidt: allow[lock-send] -- the region relay is single-threaded: one loop thread owns every pipe end, sequentially
                sent.append(wid)
            except (BrokenPipeError, OSError):
                self._mark_worker_dead(wid, "downstream send failed")
        return sent

    # ---- root -> region ----

    def _drain_root(self) -> None:
        while True:
            try:
                if not self.conn.poll():
                    return
                cmd = self.conn.recv()
            except (EOFError, OSError):
                self._on_root_gone("root pipe closed")
                return
            kind = cmd[0]
            if kind == "model":
                self._broadcast(("model", cmd[1], cmd[2]))
            elif kind == "flush":
                self._flush_seq += 1
                waiting = set(self._broadcast(
                    ("flush", self._flush_seq)))
                self._pending = {"rseq": cmd[1],
                                 "seq": self._flush_seq,
                                 "waiting": waiting,
                                 "parts": []}
                if not waiting:
                    self._ship_pending()
            elif kind == "clock":
                # answer for the region itself, then fan the probe down
                # — worker replies are re-tagged upstream with the wid
                # so the root rebases every tier onto its own clock
                self._send_up(("clock_reply", self.rid, cmd[1],
                               time.perf_counter_ns()))
                self._broadcast(("clock", cmd[1]))
            elif kind == "seqfloor":
                c = int(cmd[1])
                wid = self._route.get(c)
                if wid is not None and self._workers[wid]["alive"]:
                    try:
                        self._workers[wid]["conn"].send(cmd)  # nidt: allow[lock-send] -- the region relay is single-threaded: one loop thread owns every pipe end, sequentially
                    except (BrokenPipeError, OSError):
                        self._mark_worker_dead(
                            wid, "downstream send failed")
                else:
                    # route unknown (e.g. the registering worker died):
                    # broadcast — note_seqfloor is incarnation-guarded
                    # and a pending register pops on one worker only
                    self._broadcast(cmd)
            elif kind == "finish":
                self._finishing = True
                self._finish_deadline = time.monotonic() + 12.0
                self._broadcast(("finish",))
            else:  # pragma: no cover
                log.warning("ingest region %d: unknown root command %r",
                            self.rid, kind)

    def _on_root_gone(self, why: str) -> None:
        if self._stop:
            return
        log.warning("ingest region %d: %s; shutting down", self.rid,
                    why)
        self._kill_workers()
        self._stop = True

    # ---- workers -> region ----

    def _drain_worker(self, wid: int) -> None:
        w = self._workers[wid]
        while True:
            try:
                if not w["conn"].poll():
                    return
                ev = w["conn"].recv()
            except (EOFError, OSError):
                self._mark_worker_dead(wid, "pipe closed")
                return
            self._on_worker_event(wid, ev)

    def _on_worker_event(self, wid: int, ev: tuple) -> None:
        w = self._workers[wid]
        kind = ev[0]
        if kind == "vb":
            w["acc"] += ev[2].get("accepted", 0)
            self._send_up(("vb", self.rid) + tuple(ev[2:]))
        elif kind == "reg":
            self._route[int(ev[2])] = wid
            self._send_up(("reg", self.rid) + tuple(ev[2:]))
        elif kind == "beats":
            self._send_up(("beats", self.rid, ev[2]))
        elif kind == "obs":
            self._send_up(("obs", self.rid, ev[2], wid))
        elif kind == "clock_reply":
            self._send_up(("clock_reply", self.rid, ev[2], ev[3], wid))
        elif kind == "shm_names":
            w["shm"] = [_ShmSlabReader(name, ev[3]) for name in ev[2]]
        elif kind == "partial":
            seq, payload, stats = ev[2], ev[3], ev[4]
            w["stats"] = stats
            if isinstance(payload, dict) and "shm" in payload:
                payload = self._resolve_shm_partial(wid, payload)
            if payload is not None:
                w["folded"] += int(payload["count"])
                self.staged.merge_payload(payload)
                self.staged_entries.extend(payload["entries"])
            if (self._pending is not None
                    and seq == self._pending["seq"]):
                self._pending["waiting"].discard(wid)
                if not self._pending["waiting"]:
                    self._ship_pending()
        elif kind == "bye":
            w["stats"], w["residual"] = ev[2], ev[3]
            w["byte_stats"], w["peak_conns"] = ev[4], ev[5]
            if len(ev) > 6:
                w["xstats"] = ev[6]
            w["bye"] = True
        elif kind == "ready":
            w["ready"] = True
        else:  # pragma: no cover
            log.warning("ingest region %d: unknown worker event %r",
                        self.rid, kind)

    def _resolve_shm_partial(self, wid: int, ctrl: dict) -> dict:
        """The region is its workers' parent: copy the flat vector out
        of the slab, ack it free, rebuild the per-leaf slots (mirrors
        the flat root's resolution, one tier down)."""
        w = self._workers[wid]
        idx = int(ctrl["shm"])
        flat, w_int, count = w["shm"][idx].read(ctrl["gen"])
        try:
            w["conn"].send(("shm_ack", idx))  # nidt: allow[lock-send] -- the region relay is single-threaded: one loop thread owns every pipe end, sequentially
        except (BrokenPipeError, OSError):
            pass  # death surfaces on the sentinel; the copy is ours
        segs = np.split(flat, self._fold_splits)
        slots = {name: seg
                 for (name, _), seg in zip(self.sizes, segs)}
        return {"slots": slots, "w_int": int(w_int),
                "count": int(count), "entries": ctrl["entries"]}

    # ---- merge/ship ----

    def _merged_stats(self) -> dict:
        out: dict[str, int] = {}
        for w in self._workers.values():
            if w["stats"]:
                for k, v in w["stats"].items():
                    out[k] = out.get(k, 0) + int(v)
        return out

    def _ship_pending(self) -> None:
        """Answer the root's flush: ONE merged partial for everything
        staged (worker partials merged in wid order on arrival — order
        is irrelevant to the int64 totals and the root re-sorts entry
        metadata anyway)."""
        rseq = self._pending["rseq"]
        self._pending = None
        self._last_headroom = time.monotonic()
        payload = self.staged.export()
        if payload is not None:
            payload["entries"] = self.staged_entries
            self.staged = PartialAccumulator(self.spec, self.sizes)
            self.staged_entries = []
        self._send_up(("partial", self.rid, rseq, payload,
                       self._merged_stats()))

    def _send_merged_bye(self) -> None:
        """One bye upstream: summed worker stats, the region's TOTAL
        residual (staged-but-unshipped + every worker's own residual),
        summed byte/transport accounting."""
        residual = self.staged.count + sum(
            w["residual"] for w in self._workers.values())
        byte_stats: dict[str, int] = {}
        xstats: dict[str, int] = {}
        peak = 0
        for w in self._workers.values():
            for k, v in (w["byte_stats"] or {}).items():
                byte_stats[k] = byte_stats.get(k, 0) + int(v)
            for k, v in (w["xstats"] or {}).items():
                xstats[k] = xstats.get(k, 0) + int(v)
            peak += int(w["peak_conns"])
        self._send_up(("bye", self.rid, self._merged_stats(), residual,
                       byte_stats, peak, xstats))
        self._kill_workers(join_first=True)
        self._stop = True

    # ---- worker lifecycle ----

    def _mark_worker_dead(self, wid: int, why: str) -> None:
        w = self._workers[wid]
        if not w["alive"]:
            return
        # drain what it shipped before dying — those uploads are safe
        try:
            while w["conn"].poll():
                self._on_worker_event(wid, w["conn"].recv())
        except (EOFError, OSError):
            pass
        w["alive"] = False
        if w["shm"]:
            readers, w["shm"] = w["shm"], None
            for r in readers:
                r.close()
        lost = max(0, w["acc"] - w["folded"] - w["residual"])
        if lost and not w["bye"]:
            w["folded"] += lost
        log.warning("ingest region %d: worker %d dead (%s); %d "
                    "buffered uploads lost with it", self.rid, wid,
                    why, lost if not w["bye"] else 0)
        self._send_up(("wdead", self.rid, wid,
                       lost if not w["bye"] else 0))
        if self._pending is not None:
            self._pending["waiting"].discard(wid)
            if not self._pending["waiting"]:
                self._ship_pending()

    def _kill_workers(self, join_first: bool = False) -> None:
        for w in self._workers.values():
            p = w["proc"]
            if join_first:
                p.join(timeout=5.0)
            if p.is_alive():
                p.terminate()
                p.join(timeout=2.0)
            w["alive"] = False
            if w["shm"]:
                readers, w["shm"] = w["shm"], None
                for r in readers:
                    r.close()


# ---------------------------------------------------------------------------
# hierarchical root
# ---------------------------------------------------------------------------


class HierarchicalIngestServer(ShardedIngestServer):
    """The root of the region tree: ``regions`` sub-aggregator
    processes, each owning ``workers_per_region`` ingest workers on the
    SHARED SO_REUSEPORT port. Every ``ShardedIngestServer`` mechanism —
    harvest/merge in child-id order, verdict accounting, watermarks,
    byes, audits — applies verbatim because regions speak the worker
    pipe protocol; the overrides below are the spawn hook, the three
    region-only event shapes, and the region-labeled telemetry
    (``region="R"`` + ``worker="N"`` fan-in tiers, the
    ``nidt_region_staleness`` / ``nidt_region_partial_age_s`` gauges
    the ``region-staleness-runaway`` rule evaluates)."""

    #: a dead CHILD here is a whole region: its buffered-upload loss is
    #: accounted under this key (the audit reconciles it alongside
    #: ``lost_with_worker`` from intra-region worker deaths)
    _lost_key = "lost_with_region"

    def __init__(self, init_params, comm_round: int, num_clients: int,
                 regions: int = 2, workers_per_region: int = 2,
                 flush_interval: float = REGION_FLUSH_INTERVAL_S,
                 **kw):
        if regions < 1:
            raise ValueError(f"regions must be >= 1, got {regions}")
        if workers_per_region < 1:
            raise ValueError(
                f"workers_per_region must be >= 1, got "
                f"{workers_per_region}")
        # read by hooks the parent ctor calls (_spawn_child,
        # _make_fanin, _register_fanin) — set BEFORE super().__init__
        self.regions = int(regions)
        self.workers_per_region = int(workers_per_region)
        self.flush_interval = float(flush_interval)
        super().__init__(init_params, comm_round, num_clients,
                         ingest_workers=regions, **kw)
        self._obs_region_staleness = obs_metrics.gauge(
            obs_names.REGION_STALENESS,
            "max staleness (tau) in the region's last shipped partial "
            "batch", labelnames=("region",))
        self._obs_region_age = obs_metrics.gauge(
            obs_names.REGION_PARTIAL_AGE,
            "seconds since this region last shipped a partial to the "
            "root (a dead or wedged region's age grows forever)",
            labelnames=("region",))

    # ---- hooks the parent ctor calls ----

    def _make_fanin(self) -> obs_fanin.TelemetryFanIn:
        return obs_fanin.TelemetryFanIn(
            labelnames=("region", "worker"))

    def _register_fanin(self, rid: int) -> None:
        for k in range(self.workers_per_region):
            self.fanin.register_worker(
                (rid, rid * self.workers_per_region + k))

    def _spawn_child(self, ctx, rid: int, wcfg: dict):
        rcfg = {"workers_per_region": self.workers_per_region,
                "flush_interval": self.flush_interval,
                "wcfg": wcfg}
        parent, child = ctx.Pipe(duplex=True)
        # NOT daemonic: a region spawns its own worker fleet, which a
        # daemonic process may not; regions exit on root pipe EOF and
        # _kill_workers() reaps them on every root teardown path
        proc = ctx.Process(target=_region_main,
                           args=(rid, child, rcfg), daemon=False,
                           name=f"nidt-ingest-region{rid}")
        proc.start()
        child.close()
        return proc, parent

    # ---- region-only event shapes ----

    def _handle_event(self, rid: int, ev: tuple) -> None:
        kind = ev[0]
        if kind == "obs" and len(ev) > 3:
            # a worker's telemetry payload, region-routed: keyed by
            # BOTH tiers so the merged exposition reads region="R",
            # worker="N"
            self.fanin.ingest((rid, int(ev[3])), ev[2])
            return
        if kind == "clock_reply":
            if len(ev) > 4:
                self.fanin.note_clock((rid, int(ev[4])), ev[2], ev[3],
                                      time.perf_counter_ns())
            # a 4-tuple is the region's own echo — it carries no
            # telemetry of its own, so there is nothing to rebase
            return
        if kind == "wdead":
            # a worker died INSIDE a surviving region: the region
            # already drained what it could; the remainder is a
            # WORKER loss (the region child stays alive and accounted)
            wid, lost = int(ev[2]), int(ev[3])
            w = self._workers[rid]
            if lost:
                self.upload_stats["lost_with_worker"] += lost
                self._obs_uploads.inc(lost, outcome="lost_with_worker")
                w["folded"] += lost
            self.fanin.mark_dead((rid, wid))
            obs_flight.record("region_worker_dead", region=rid,
                              worker=wid, lost=lost,
                              version=self.round_idx)
            log.warning("ingest root: worker %d of region %d died; %d "
                        "uploads lost", wid, rid, lost)
            return
        if kind == "partial":
            payload = ev[3]
            if isinstance(payload, dict) and payload.get("entries"):
                self._obs_region_staleness.set(
                    max(int(e[5]) for e in payload["entries"]),
                    region=str(rid))
            super()._handle_event(rid, ev)
            return
        super()._handle_event(rid, ev)

    def _maybe_harvest(self) -> None:
        now = time.monotonic()
        for rid, w in self._workers.items():
            if w["last_partial_t"] is not None:
                self._obs_region_age.set(
                    round(now - w["last_partial_t"], 3),
                    region=str(rid))
        super()._maybe_harvest()

    # ---- audit ----

    def upload_audit(self) -> dict:
        audit = super().upload_audit()
        # the per-child table IS the per-region table here; aliased so
        # callers reading the tree topology don't need to know the
        # parent class calls its children "workers"
        audit["regions"] = dict(audit["workers"])
        return audit
