from neuroimagedisttraining_tpu.data.synthetic import (  # noqa: F401
    generate_synthetic_abcd,
    write_synthetic_hdf5,
)
