"""FederatedData: the device-resident, client-stacked dataset container.

The reference returns an 8-tuple of per-client DataLoaders
(ABCD/data_loader.py:211-212) iterated sequentially. TPU-first, the whole
federation's data is a pair of padded stacked arrays ``X[C, Nmax, ...]`` /
``y[C, Nmax]`` with true counts ``n[C]``, sharded over the mesh's client
axis — so a round touches it with gathers inside one jitted program and no
host round-trips. Voxels stay uint8 in HBM (the reference stores 8-bit
quantized volumes on disk, Preprocess_ABCD.ipynb cell 37) and are cast to
f32 per batch on device.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.data import partition as P

PyTree = Any


@flax.struct.dataclass
class FederatedData:
    X_train: jax.Array   # [C, Ntr_max, ...] uint8/float
    y_train: jax.Array   # [C, Ntr_max]
    n_train: jax.Array   # [C] true sample counts (0 for padding clients)
    X_test: jax.Array
    y_test: jax.Array
    n_test: jax.Array
    X_val: jax.Array | None = None
    y_val: jax.Array | None = None
    n_val: jax.Array | None = None

    @property
    def num_clients(self) -> int:
        return self.X_train.shape[0]

    def test_valid_mask(self) -> jax.Array:
        return (jnp.arange(self.X_test.shape[1])[None, :]
                < self.n_test[:, None])


def _stack_pad(X: np.ndarray, y: np.ndarray,
               idx_map: dict[int, np.ndarray], pad_clients: int):
    C = len(idx_map)
    nmax = max(1, max(len(v) for v in idx_map.values()))
    total = C + pad_clients
    Xs = np.zeros((total, nmax) + X.shape[1:], dtype=X.dtype)
    ys = np.zeros((total, nmax), dtype=np.int32)
    ns = np.zeros((total,), dtype=np.int32)
    for c in range(C):
        idx = idx_map[c]
        Xs[c, : len(idx)] = X[idx]
        ys[c, : len(idx)] = y[idx]
        ns[c] = len(idx)
    return Xs, ys, ns


def build_federated_data(
    X: np.ndarray, y: np.ndarray,
    train_map: dict[int, np.ndarray], test_map: dict[int, np.ndarray],
    mesh=None, val_map: dict[int, np.ndarray] | None = None,
    X_eval: np.ndarray | None = None, y_eval: np.ndarray | None = None,
) -> FederatedData:
    """Assemble + (optionally) shard the federation over a mesh. The client
    count is padded up to a multiple of the mesh size with zero-sample
    clients (their aggregation weight is always 0).

    ``X_eval``/``y_eval``: separate pool that ``test_map`` indexes into —
    vision datasets ship distinct train/test arrays (cifar10
    data_loader.py:63-72); ABCD-style cohorts index one pool for both."""
    C = len(train_map)
    pad = 0
    if mesh is not None:
        d = mesh.devices.size
        pad = (d - C % d) % d
    Xev = X if X_eval is None else X_eval
    yev = y if y_eval is None else y_eval
    Xtr, ytr, ntr = _stack_pad(X, y, train_map, pad)
    Xte, yte, nte = _stack_pad(Xev, yev, test_map, pad)
    parts = dict(X_train=Xtr, y_train=ytr, n_train=ntr,
                 X_test=Xte, y_test=yte, n_test=nte)
    if val_map is not None:
        Xv, yv, nv = _stack_pad(X, y, val_map, pad)
        parts.update(X_val=Xv, y_val=yv, n_val=nv)
    if mesh is not None:
        from neuroimagedisttraining_tpu.parallel.mesh import client_sharding
        sh = client_sharding(mesh)
        parts = {k: jax.device_put(v, sh) for k, v in parts.items()}
    else:
        parts = {k: jnp.asarray(v) for k, v in parts.items()}
    return FederatedData(**parts)


#: Seed for the site-partition/val-carve data split. The STREAMING branch
#: of __main__.build_experiment must derive its split from the same seed
#: as this module's resident path, or a streamed run would train on rows
#: the resident run holds out — keep both on this one constant.
DATA_SPLIT_SEED = 42


def carve_val_split(train_map: dict[int, np.ndarray], val_fraction: float,
                    seed: int) -> tuple[dict, dict]:
    """Carve a validation split out of each client's train shard (FedFomo
    9-tuple, cifar10/data_val_loader.py:83-260). Returns (val_map,
    new_train_map); shared by the resident and streaming data paths so a
    streamed FedFomo run sees the SAME split as the resident one."""
    val_map, new_train = {}, {}
    rs = np.random.RandomState(seed + 1)  # one stream across clients
    for c, idx in train_map.items():
        idx = np.array(idx, copy=True)
        rs.shuffle(idx)
        nv = max(1, int(len(idx) * val_fraction))
        val_map[c], new_train[c] = idx[:nv], idx[nv:]
    return val_map, new_train


def federate_cohort(data: dict[str, np.ndarray], partition_method: str = "site",
                    client_number: int | None = None, alpha: float = 0.5,
                    seed: int = DATA_SPLIT_SEED, mesh=None,
                    val_fraction: float = 0.0
                    ) -> tuple[FederatedData, dict]:
    """Partition a cohort dict {X, y, site} into a FederatedData using the
    reference's partition modes (SURVEY.md §2.6)."""
    X, y = data["X"], data["y"]
    info: dict = {"partition_method": partition_method}
    if partition_method == "site":
        train_map, test_map, sites = P.site_partition(data["site"], seed=seed)
        info["sites"] = sites.tolist()
    elif partition_method == "rescale":
        assert client_number is not None
        train_map, test_map = P.rescale_partition(len(y), client_number,
                                                  seed=seed)
    elif partition_method in ("dir", "hetero"):
        assert client_number is not None
        idx_map = P.dirichlet_partition(y, client_number, alpha, seed=seed)
        train_map, test_map = P.train_test_split_per_client(idx_map, seed=seed)
    elif partition_method == "homo":
        assert client_number is not None
        idx_map = P.homo_partition(len(y), client_number, seed=seed)
        train_map, test_map = P.train_test_split_per_client(idx_map, seed=seed)
    else:
        raise ValueError(f"unknown partition_method {partition_method!r}")

    val_map = None
    if val_fraction > 0:
        val_map, train_map = carve_val_split(train_map, val_fraction, seed)
    info["client_num"] = len(train_map)
    info["train_counts"] = [int(len(train_map[c])) for c in sorted(train_map)]
    info["stats"] = P.record_data_stats(y, train_map)
    fed = build_federated_data(X, y, train_map, test_map, mesh=mesh,
                               val_map=val_map)
    return fed, info
