"""Client partitioners.

- ``site_partition``: ABCD acquisition-site clients with per-site 80/20
  train/test split, np.random.seed(42)-shuffle parity
  (reference ABCD/data_loader.py:67-99: per site, seed reset to 42, shuffle,
  first n - int(0.2*n) train, rest test).
- ``rescale_partition``: merge-all-then-contiguous-shard cross-silo scale-out
  path (data_loader.py:216-315 ``load_partition_data_abcd_rescale``).
- ``dirichlet_partition``: LDA non-IID partitioner ported semantically from
  fedml_core/non_iid_partition/noniid_partition.py:6-73, including the
  min-10-samples retry loop and the capacity correction
  ``p * (len(idx_j) < N/num_clients)``.
- ``homo_partition``: IID equal random split (cifar10/data_loader.py homo mode).
- ``record_data_stats``: per-client class histogram (noniid_partition.py:76-103).
"""

from __future__ import annotations

import numpy as np


def site_partition(site: np.ndarray, seed: int = 42, test_frac: float = 0.2
                   ) -> tuple[dict[int, np.ndarray], dict[int, np.ndarray], np.ndarray]:
    """Returns (train_idx_by_client, test_idx_by_client, site_values)."""
    unique_sites = np.unique(site)
    train_map, test_map = {}, {}
    for client, s in enumerate(unique_sites):
        idx = np.where(site == s)[0]
        n_test = int(len(idx) * test_frac)
        n_train = len(idx) - n_test
        rs = np.random.RandomState(seed)
        rs.shuffle(idx)
        train_map[client] = idx[:n_train]
        test_map[client] = idx[n_train:]
    return train_map, test_map, unique_sites


def rescale_partition(n: int, client_number: int, seed: int = 42,
                      test_frac: float = 0.2
                      ) -> tuple[dict[int, np.ndarray], dict[int, np.ndarray]]:
    """Global shuffle + 80/20 split + contiguous equal shards per client
    (data_loader.py:216-315)."""
    idx = np.arange(n)
    rs = np.random.RandomState(seed)
    rs.shuffle(idx)
    n_test = int(n * test_frac)
    train_idx, test_idx = idx[: n - n_test], idx[n - n_test:]
    train_map = {c: np.sort(a) for c, a in
                 enumerate(np.array_split(train_idx, client_number))}
    test_map = {c: np.sort(a) for c, a in
                enumerate(np.array_split(test_idx, client_number))}
    return train_map, test_map


def dirichlet_partition(labels: np.ndarray, client_number: int, alpha: float,
                        seed: int = 0, min_size_floor: int = 10
                        ) -> dict[int, np.ndarray]:
    """LDA partition of sample indices over clients
    (noniid_partition.py:6-73 semantics)."""
    rs = np.random.RandomState(seed)
    n = len(labels)
    classes = np.unique(labels)
    min_size = 0
    idx_batch: list[list[int]] = [[] for _ in range(client_number)]
    while min_size < min_size_floor:
        idx_batch = [[] for _ in range(client_number)]
        for k in classes:
            idx_k = np.where(labels == k)[0]
            rs.shuffle(idx_k)
            p = rs.dirichlet(np.repeat(alpha, client_number))
            # capacity correction: zero out clients already at quota
            # (noniid_partition.py:31-35)
            p = np.array([pi * (len(ib) < n / client_number)
                          for pi, ib in zip(p, idx_batch)])
            p = p / p.sum()
            cuts = (np.cumsum(p) * len(idx_k)).astype(int)[:-1]
            idx_batch = [ib + part.tolist()
                         for ib, part in zip(idx_batch, np.split(idx_k, cuts))]
        min_size = min(len(ib) for ib in idx_batch)
    return {c: np.array(sorted(ib), dtype=np.int64)
            for c, ib in enumerate(idx_batch)}


def homo_partition(n: int, client_number: int, seed: int = 0
                   ) -> dict[int, np.ndarray]:
    rs = np.random.RandomState(seed)
    idx = rs.permutation(n)
    return {c: np.sort(a) for c, a in
            enumerate(np.array_split(idx, client_number))}


def train_test_split_per_client(idx_map: dict[int, np.ndarray], seed: int = 42,
                                test_frac: float = 0.2
                                ) -> tuple[dict[int, np.ndarray], dict[int, np.ndarray]]:
    """80/20 split inside each client's shard (for non-site partitions)."""
    train_map, test_map = {}, {}
    for c, idx in idx_map.items():
        idx = np.array(idx, copy=True)
        rs = np.random.RandomState(seed)
        rs.shuffle(idx)
        n_test = int(len(idx) * test_frac)
        train_map[c] = idx[: len(idx) - n_test]
        test_map[c] = idx[len(idx) - n_test:]
    return train_map, test_map


def record_data_stats(labels: np.ndarray, idx_map: dict[int, np.ndarray]
                      ) -> dict[int, dict[int, int]]:
    """Per-client {class: count} census (noniid_partition.py:76-103)."""
    stats = {}
    for c, idx in idx_map.items():
        uniq, counts = np.unique(labels[idx], return_counts=True)
        stats[c] = {int(u): int(cnt) for u, cnt in zip(uniq, counts)}
    return stats
