"""Host-streaming federation: cohorts larger than HBM.

The real ABCD cohort (11,573 x 121x145x121 uint8 ~ 24.5 GB) does not fit in
one chip's HBM; the reference's whole data design is lazy index tensors +
per-batch host fetch (ABCD/data_loader.py:117-119,
my_model_trainer.py:185-199). TPU-first, per-BATCH host fetches would stall
the device, so the streaming granularity is a ROUND: only the sampled
clients' train shards are read from the (HDF5 or mmap) source, stacked into
the same padded ``[S, Nmax, ...]`` layout the device-resident path uses, and
``device_put`` while the previous round still computes (double-buffering via
a background reader thread). Evaluation streams the cohort through in
client chunks.

Metric parity: rows are placed in exactly the order the device-resident
``_stack_pad`` uses, so a streamed round program sees bitwise-identical
inputs and produces bitwise-identical metrics (tested in
tests/test_stream.py).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, NamedTuple

import jax
import numpy as np

from neuroimagedisttraining_tpu.data.hdf5 import fetch_rows
from neuroimagedisttraining_tpu.utils import native


class EvalChunk(NamedTuple):
    """One streamed client chunk: ``ids`` are the real client ids,
    ``padded_ids`` repeat the last id up to the static chunk size (the
    arrays below are always chunk-sized; pad clients carry n=0)."""

    ids: np.ndarray
    padded_ids: np.ndarray
    X: jax.Array
    y: jax.Array
    n: jax.Array


class StreamingFederation:
    """Round-granular host->device feed over a lazy voxel source.

    Parameters
    ----------
    X_source : h5py.Dataset | np.ndarray — lazy row-sliceable voxel store.
    y : np.ndarray — labels (host-resident, tiny).
    train_map / test_map : dict[int, np.ndarray] — per-client sample indices
        (same maps the device-resident ``build_federated_data`` consumes).
    """

    def __init__(self, X_source, y: np.ndarray,
                 train_map: dict[int, np.ndarray],
                 test_map: dict[int, np.ndarray], mesh=None):
        """``mesh``: optional 1-D client mesh — round/eval buffers are then
        device_put SHARDED over their leading (client) axis, so a streamed
        round feeds a multi-chip federation directly (one sampled client
        per core at the flagship layout); requires the sampled-set size to
        tile the mesh."""
        self.X = X_source
        self.mesh = mesh
        self.y = np.asarray(y)
        self.train_map = {c: np.asarray(v) for c, v in train_map.items()}
        self.test_map = {c: np.asarray(v) for c, v in test_map.items()}
        self.num_clients = len(train_map)
        self.n_train = np.array([len(self.train_map[c])
                                 for c in range(self.num_clients)], np.int32)
        self.n_test = np.array([len(self.test_map[c])
                                for c in range(self.num_clients)], np.int32)
        # static pad sizes over the WHOLE federation so every round compiles
        # to one program
        self.nmax_train = max(1, int(self.n_train.max()))
        self.nmax_test = max(1, int(self.n_test.max()))
        self.sample_shape = tuple(self.X.shape[1:])
        self.dtype = self.X.dtype
        self._pool = ThreadPoolExecutor(max_workers=1)
        self._pending: tuple[tuple, object] | None = None

    def _put(self, x: np.ndarray):
        """Host -> device; sharded over the leading client axis when a
        mesh is attached (the jitted round program then runs SPMD over the
        client axis with no resharding)."""
        if self.mesh is None:
            return jax.device_put(x)
        from jax.sharding import NamedSharding, PartitionSpec

        spec = PartitionSpec(self.mesh.axis_names[0],
                             *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    # ---------- raw fetch (host thread) ----------

    def _fetch(self, client_ids: np.ndarray, split: str):
        idx_map = self.train_map if split == "train" else self.test_map
        nmax = self.nmax_train if split == "train" else self.nmax_test
        S = len(client_ids)
        Xs = np.zeros((S, nmax) + self.sample_shape, self.dtype)
        ys = np.zeros((S, nmax), np.int32)
        ns = np.zeros((S,), np.int32)
        for j, c in enumerate(client_ids):
            idx = idx_map[int(c)]
            if len(idx):
                if isinstance(self.X, np.ndarray):
                    # native multithreaded gather straight into the padded
                    # round buffer (no intermediate copy)
                    native.gather_rows(self.X, idx, out=Xs[j])
                else:
                    Xs[j, : len(idx)] = fetch_rows(self.X, idx)
                ys[j, : len(idx)] = self.y[idx]
            ns[j] = len(idx)
        return Xs, ys, ns

    # ---------- double-buffered round feed ----------

    def prefetch_train(self, client_ids: np.ndarray) -> None:
        """Kick off the next round's read on the background thread."""
        key = ("train", tuple(int(c) for c in client_ids))
        if self._pending is not None and self._pending[0] == key:
            return
        self._pending = (key, self._pool.submit(self._fetch,
                                                np.asarray(client_ids),
                                                "train"))

    def get_train(self, client_ids: np.ndarray):
        """Device-put padded arrays for the sampled clients; uses the
        prefetched buffer when it matches."""
        key = ("train", tuple(int(c) for c in client_ids))
        if self._pending is not None and self._pending[0] == key:
            Xs, ys, ns = self._pending[1].result()
            self._pending = None
        else:
            Xs, ys, ns = self._fetch(np.asarray(client_ids), "train")
        return (self._put(Xs), self._put(ys), self._put(ns))

    # ---------- streamed evaluation ----------

    def eval_chunks(self, chunk_clients: int, split: str = "test"
                    ) -> Iterator[EvalChunk]:
        """Yield ``EvalChunk`` device chunks covering the cohort.

        The final chunk is padded with zero-sample clients so every chunk
        has the same static shape (one compiled eval program). Chunk k+1's
        host read is submitted to the background reader BEFORE chunk k is
        yielded, so host I/O overlaps the caller's device compute (same
        double-buffering as the round feed)."""
        metas = []
        for start in range(0, self.num_clients, chunk_clients):
            ids = np.arange(start, min(start + chunk_clients,
                                       self.num_clients))
            padded = np.concatenate(
                [ids, np.full(chunk_clients - len(ids), ids[-1])])
            metas.append((ids, padded))
        fut = self._pool.submit(self._fetch, metas[0][1], split)
        for i, (ids, padded) in enumerate(metas):
            Xs, ys, ns = fut.result()
            if i + 1 < len(metas):
                fut = self._pool.submit(self._fetch, metas[i + 1][1], split)
            ns[len(ids):] = 0  # pad clients contribute nothing
            yield EvalChunk(ids, padded, self._put(Xs), self._put(ys),
                            self._put(ns))

    def close(self):
        self._pool.shutdown(wait=False)
