"""Host-streaming federation: cohorts larger than HBM.

The real ABCD cohort (11,573 x 121x145x121 uint8 ~ 24.5 GB) does not fit in
one chip's HBM; the reference's whole data design is lazy index tensors +
per-batch host fetch (ABCD/data_loader.py:117-119,
my_model_trainer.py:185-199). TPU-first, per-BATCH host fetches would stall
the device, so the streaming granularity is a ROUND: only the sampled
clients' train shards are read from the (HDF5 or mmap) source, stacked into
the same padded ``[S, Nmax, ...]`` layout the device-resident path uses, and
``device_put`` from the reader thread while the previous round still
computes (both the host read AND the host->device transfer ride behind
compute; per-stage wall times are accumulated in ``transfer_stats``).
Evaluation streams the cohort through in client chunks.

Metric parity: rows are placed in exactly the order the device-resident
``_stack_pad`` uses, so a streamed round program sees bitwise-identical
inputs and produces bitwise-identical metrics (tested in
tests/test_stream.py).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, NamedTuple

import jax
import numpy as np

from neuroimagedisttraining_tpu.data.hdf5 import fetch_rows
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as obs_names
from neuroimagedisttraining_tpu.utils import native


class EvalChunk(NamedTuple):
    """One streamed client chunk: ``ids`` are the real client ids,
    ``padded_ids`` repeat the last id up to the static chunk size (the
    arrays below are always chunk-sized; pad clients carry n=0)."""

    ids: np.ndarray
    padded_ids: np.ndarray
    X: jax.Array
    y: jax.Array
    n: jax.Array


class StreamingFederation:
    """Round-granular host->device feed over a lazy voxel source.

    Parameters
    ----------
    X_source : h5py.Dataset | np.ndarray — lazy row-sliceable voxel store.
    y : np.ndarray — labels (host-resident, tiny).
    train_map / test_map : dict[int, np.ndarray] — per-client sample indices
        (same maps the device-resident ``build_federated_data`` consumes).
    val_map : optional per-client validation indices (FedFomo's 9-tuple val
        split); val shards are ``val_fraction``-small, so unlike train they
        may be fetched device-RESIDENT via ``get_val_resident``.
    """

    def __init__(self, X_source, y: np.ndarray,
                 train_map: dict[int, np.ndarray],
                 test_map: dict[int, np.ndarray], mesh=None,
                 val_map: dict[int, np.ndarray] | None = None):
        """``mesh``: optional client mesh — round/eval buffers are then
        device_put SHARDED over their leading (client) axis, so a streamed
        round feeds a multi-chip federation directly (one sampled client
        per core at the flagship layout); requires the sampled-set size to
        tile the mesh. A two-level (silos, clients) mesh shards the client
        axis over BOTH mesh axes silo-major, so the engine's silo-first
        aggregation routing (parallel/hierarchical.py) is preserved under
        streaming."""
        self.X = X_source
        self.mesh = mesh
        self.y = np.asarray(y)
        self.train_map = {c: np.asarray(v) for c, v in train_map.items()}
        self.test_map = {c: np.asarray(v) for c, v in test_map.items()}
        self.val_map = (None if val_map is None else
                        {c: np.asarray(v) for c, v in val_map.items()})
        self.num_clients = len(train_map)
        self.n_train = np.array([len(self.train_map[c])
                                 for c in range(self.num_clients)], np.int32)
        self.n_test = np.array([len(self.test_map[c])
                                for c in range(self.num_clients)], np.int32)
        # static pad sizes over the WHOLE federation so every round compiles
        # to one program
        self.nmax_train = max(1, int(self.n_train.max()))
        self.nmax_test = max(1, int(self.n_test.max()))
        if self.val_map is not None:
            self.n_val = np.array([len(self.val_map[c])
                                   for c in range(self.num_clients)],
                                  np.int32)
            self.nmax_val = max(1, int(self.n_val.max()))
        self.sample_shape = tuple(self.X.shape[1:])
        self.dtype = self.X.dtype
        self._pool = ThreadPoolExecutor(max_workers=1)
        self._pending: tuple[tuple, object] | None = None
        #: cumulative wall time of the streaming stages (ms) plus the
        #: bytes moved host->device; both stages run on the reader
        #: thread, i.e. behind the previous round's device compute when
        #: prefetch is active. Every update ALSO publishes into the obs
        #: metrics registry (``nidt_stream_transfer`` gauges, one series
        #: per key — value == this dict's entry by construction, the
        #: parity pin in tests/test_stream.py), so /metrics shows the
        #: feed's health live mid-run.
        self.transfer_stats = {"host_gather_ms": 0.0, "device_put_ms": 0.0,
                               "bytes": 0.0, "fetches": 0}
        self._stats_lock = threading.Lock()

    def _note_transfer(self, gather_s: float, put_s: float,
                       nbytes: int, fetches: int = 1) -> None:
        """Accumulate one work unit's stage timings into
        ``transfer_stats`` and mirror the totals into the obs registry
        (host/reader-thread only — the registry is thread-safe and this
        never runs inside a trace). The gauge carries THIS feed's
        totals; with several concurrent feeds in one process (tests) the
        last writer wins — a run owns one feed."""
        g = obs_metrics.gauge(
            obs_names.STREAM_TRANSFER,
            "cumulative streaming-feed totals (data/stream.py "
            "transfer_stats), one series per key",
            labelnames=("key",))
        with self._stats_lock:
            st = self.transfer_stats
            st["host_gather_ms"] += gather_s * 1e3
            st["device_put_ms"] += put_s * 1e3
            st["bytes"] += float(nbytes)
            st["fetches"] += fetches
            # publish INSIDE the lock: a main-thread fetch racing the
            # reader-thread prefetch must not interleave per-key sets
            # from two snapshots (the dict==gauge parity pin)
            for k, v in st.items():
                g.labels(key=k).set(float(v))

    def _put(self, x: np.ndarray, client_axis: int = 0):
        """Host -> device; sharded over the CLIENT axis when a mesh is
        attached (the jitted round program then runs SPMD over the
        client axis with no resharding). On a two-level mesh the client
        axis maps over (silos, clients) silo-major. ``client_axis``:
        where the client axis sits — 0 for round/eval buffers, 1 for
        window-stacked ``[K, S, ...]`` buffers (the K axis replicates,
        each scanned round stays client-sharded)."""
        if self.mesh is None:
            return jax.device_put(x)
        from jax.sharding import NamedSharding, PartitionSpec

        spec = PartitionSpec(*([None] * client_axis),
                             tuple(self.mesh.axis_names),
                             *([None] * (x.ndim - 1 - client_axis)))
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    # ---------- raw fetch (host thread) ----------

    def _split_maps(self, split: str):
        if split == "train":
            return self.train_map, self.nmax_train
        if split == "test":
            return self.test_map, self.nmax_test
        if split == "val":
            if self.val_map is None:
                raise ValueError("this StreamingFederation was built "
                                 "without a val_map (val_fraction=0)")
            return self.val_map, self.nmax_val
        raise ValueError(f"unknown split {split!r}")

    def _fill(self, Xs, ys, ns, client_ids: np.ndarray, split: str,
              n_real: int | None = None) -> None:
        """Fill one round's ``[S, nmax, ...]`` padded buffers (views into
        a larger window stack are fine) from the lazy source."""
        idx_map, _ = self._split_maps(split)
        for j, c in enumerate(client_ids):
            if n_real is not None and j >= n_real:
                break  # mesh-tiling pads: zero buffers, never gathered
            idx = idx_map[int(c)]
            if len(idx):
                if isinstance(self.X, np.ndarray):
                    # native multithreaded gather straight into the padded
                    # round buffer (no intermediate copy)
                    native.gather_rows(self.X, idx, out=Xs[j])
                else:
                    Xs[j, : len(idx)] = fetch_rows(self.X, idx)
                ys[j, : len(idx)] = self.y[idx]
            ns[j] = len(idx)

    def _fetch(self, client_ids: np.ndarray, split: str,
               n_real: int | None = None):
        _, nmax = self._split_maps(split)
        S = len(client_ids)
        Xs = np.zeros((S, nmax) + self.sample_shape, self.dtype)
        ys = np.zeros((S, nmax), np.int32)
        ns = np.zeros((S,), np.int32)
        self._fill(Xs, ys, ns, client_ids, split, n_real)
        return Xs, ys, ns

    def _fetch_put(self, client_ids: np.ndarray, split: str,
                   n_real: int | None = None):
        """Reader-thread work unit: host gather AND host->device transfer,
        so the transfer hides behind the previous round's compute instead
        of landing synchronously at the round boundary (VERDICT r3 weak #2).
        Blocks on the transfer so the timing is the true H2D cost."""
        t0 = time.perf_counter()
        Xs, ys, ns = self._fetch(client_ids, split, n_real)
        t1 = time.perf_counter()
        out = (self._put(Xs), self._put(ys), self._put(ns))
        jax.block_until_ready(out[0])
        t2 = time.perf_counter()
        self._note_transfer(t1 - t0, t2 - t1,
                            Xs.nbytes + ys.nbytes + ns.nbytes)
        return out

    def _fetch_put_window(self, ids_per_round: list[np.ndarray],
                          n_real: int | None = None):
        """Reader-thread work unit for a FUSED DISPATCH WINDOW (ISSUE
        10): every round's train shards of the window gathered into ONE
        ``[K, S, nmax, ...]`` stack and device_put once — window k+1's
        gather AND transfer ride behind window k's K-round scan exactly
        as the per-round feed hides behind one round. The client axis
        (axis 1) shards over the mesh when attached; the K axis
        replicates (the scan consumes one round per step)."""
        K, S = len(ids_per_round), len(ids_per_round[0])
        t0 = time.perf_counter()
        Xs = np.zeros((K, S, self.nmax_train) + self.sample_shape,
                      self.dtype)
        ys = np.zeros((K, S, self.nmax_train), np.int32)
        ns = np.zeros((K, S), np.int32)
        for k, ids in enumerate(ids_per_round):
            self._fill(Xs[k], ys[k], ns[k], np.asarray(ids), "train",
                       n_real)
        t1 = time.perf_counter()
        out = (self._put(Xs, client_axis=1), self._put(ys, client_axis=1),
               self._put(ns, client_axis=1))
        jax.block_until_ready(out[0])
        t2 = time.perf_counter()
        self._note_transfer(t1 - t0, t2 - t1,
                            Xs.nbytes + ys.nbytes + ns.nbytes, fetches=K)
        return out

    # ---------- double-buffered round feed ----------

    def prefetch_train(self, client_ids: np.ndarray,
                       n_real: int | None = None) -> None:
        """Kick off the next round's read + device transfer on the
        background thread. ``n_real``: entries past this index are
        mesh-tiling pads — their fetched sample counts are zeroed so they
        train as no-ops and weigh 0 in aggregation (the north-star
        frac-sampled sets need not tile the device grid)."""
        key = ("train", tuple(int(c) for c in client_ids), n_real)
        if self._pending is not None and self._pending[0] == key:
            return
        self._pending = (key, self._pool.submit(self._fetch_put,
                                                np.asarray(client_ids),
                                                "train", n_real))

    def get_train(self, client_ids: np.ndarray, n_real: int | None = None):
        """Device-resident padded arrays for the sampled clients; uses the
        prefetched (already transferred) buffer when it matches."""
        key = ("train", tuple(int(c) for c in client_ids), n_real)
        if self._pending is not None and self._pending[0] == key:
            out = self._pending[1].result()
            self._pending = None
            return out
        return self._fetch_put(np.asarray(client_ids), "train", n_real)

    # ---------- window-granular feed (fused dispatch, ISSUE 10) ----------

    @staticmethod
    def _window_key(ids_per_round, n_real):
        return ("train_window",
                tuple(tuple(int(c) for c in ids) for ids in ids_per_round),
                n_real)

    def prefetch_window(self, ids_per_round: list[np.ndarray],
                        n_real: int | None = None) -> None:
        """Kick off a whole dispatch window's read + transfer on the
        background thread — the window-granular analog of
        ``prefetch_train`` for the ``--rounds_per_dispatch K`` streamed
        driver: window k+1's ``[K, S, ...]`` stack lands on device while
        window k's fused scan computes. HBM note: a window holds K
        rounds' shards simultaneously — size K accordingly (the same
        trade the resident fused driver makes with compile time)."""
        key = self._window_key(ids_per_round, n_real)
        if self._pending is not None and self._pending[0] == key:
            return
        self._pending = (key, self._pool.submit(
            self._fetch_put_window,
            [np.asarray(ids) for ids in ids_per_round], n_real))

    def get_window(self, ids_per_round: list[np.ndarray],
                   n_real: int | None = None):
        """Device-resident ``[K, S, nmax, ...]`` stacks for a fused
        window; serves the prefetched buffers when the key matches
        (mismatches fetch fresh, never serve stale — the
        ``prefetch_train`` contract)."""
        key = self._window_key(ids_per_round, n_real)
        if self._pending is not None and self._pending[0] == key:
            out = self._pending[1].result()
            self._pending = None
            return out
        return self._fetch_put_window(
            [np.asarray(ids) for ids in ids_per_round], n_real)

    # ---------- resident val shards (FedFomo) ----------

    def get_val_resident(self):
        """All clients' VAL shards as device-resident padded arrays
        ``[C, nmax_val, ...]`` — the val split is val_fraction-small, so
        residency is safe even when the train cohort exceeds HBM.

        Deliberately REPLICATED (plain device_put, not the client-axis
        sharding): the consumer (FedFomo's pair scan) gathers arbitrary
        ``Xval[c]`` rows, and the unpadded ``num_clients`` axis need not
        tile the mesh."""
        Xs, ys, ns = self._fetch(np.arange(self.num_clients), "val")
        return (jax.device_put(Xs), jax.device_put(ys), jax.device_put(ns))

    # ---------- streamed evaluation ----------

    def eval_chunks(self, chunk_clients: int, split: str = "test"
                    ) -> Iterator[EvalChunk]:
        """Yield ``EvalChunk`` device chunks covering the cohort.

        The final chunk is padded with zero-sample clients so every chunk
        has the same static shape (one compiled eval program). Chunk k+1's
        host read AND device transfer are submitted to the background
        reader BEFORE chunk k is yielded, so both overlap the caller's
        device compute (same double-buffering as the round feed)."""
        metas = []
        for start in range(0, self.num_clients, chunk_clients):
            ids = np.arange(start, min(start + chunk_clients,
                                       self.num_clients))
            padded = np.concatenate(
                [ids, np.full(chunk_clients - len(ids), ids[-1])])
            metas.append((ids, padded))
        fut = self._pool.submit(self._fetch_put, metas[0][1], split,
                                len(metas[0][0]))
        for i, (ids, padded) in enumerate(metas):
            Xs, ys, ns = fut.result()
            if i + 1 < len(metas):
                fut = self._pool.submit(self._fetch_put, metas[i + 1][1],
                                        split, len(metas[i + 1][0]))
            yield EvalChunk(ids, padded, Xs, ys, ns)

    def sync(self) -> None:
        """Block until every submitted reader-thread work unit finished —
        the single-worker pool is FIFO, so a no-op barrier suffices. Used
        by benches to read ``transfer_stats`` without racing in-flight
        fetches."""
        self._pool.submit(lambda: None).result()

    def close(self):
        self._pool.shutdown(wait=False)
