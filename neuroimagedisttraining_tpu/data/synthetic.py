"""Synthetic ABCD-like cohort generator.

The real ABCD dataset (11,573 T1 gray-matter volumes, 121x145x121 voxels,
8-bit quantized HDF5 with keys ``X``/``y``/``site`` — reference
Preprocess_ABCD.ipynb cells 7/30/37, ABCD/data_loader.py:112-119) is private.
This generator produces a cohort with the same schema and statistical shape:
uint8 volumes, binary ``y`` (sex), integer ``site`` labels, with a
class-conditional signal so that models actually learn — used by tests,
benchmarks, and parity validation (SURVEY.md §7 "hard parts" #5).
"""

from __future__ import annotations

import numpy as np


def generate_synthetic_abcd(
    num_subjects: int = 256,
    shape: tuple[int, int, int] = (16, 16, 16),
    num_sites: int = 4,
    seed: int = 0,
    signal: float = 12.0,
) -> dict[str, np.ndarray]:
    """Returns ``{"X": uint8 [N,D,H,W], "y": int8 [N], "site": int16 [N]}``.

    The class signal is a smooth blob whose amplitude differs by class and
    whose position drifts slightly by site (site-level covariate shift, the
    phenomenon the federated setup exists to handle).
    """
    rng = np.random.default_rng(seed)
    d, h, w = shape
    y = rng.integers(0, 2, size=num_subjects).astype(np.int8)
    # Site sizes are imbalanced like real acquisition sites.
    site_probs = rng.dirichlet(np.full(num_sites, 2.0))
    site = rng.choice(num_sites, size=num_subjects, p=site_probs).astype(np.int16)

    zz, yy, xx = np.meshgrid(
        np.linspace(-1, 1, d), np.linspace(-1, 1, h), np.linspace(-1, 1, w),
        indexing="ij",
    )
    X = np.empty((num_subjects, d, h, w), dtype=np.uint8)
    site_shift = rng.normal(0, 0.15, size=(num_sites, 3))
    for i in range(num_subjects):
        cz, cy, cx = site_shift[site[i]]
        blob = np.exp(-(((zz - cz) ** 2 + (yy - cy) ** 2 + (xx - cx) ** 2)
                        / 0.18))
        base = 60.0 + 20.0 * blob
        base += signal * blob * (1.0 if y[i] == 1 else -1.0)
        base += rng.normal(0, 8.0, size=shape)
        X[i] = np.clip(base, 0, 255).astype(np.uint8)
    return {"X": X, "y": y, "site": site}


def write_synthetic_hdf5(path: str, **kwargs) -> dict[str, np.ndarray]:
    """Write the synthetic cohort in the reference HDF5 schema
    (keys ``X``, ``y``, ``site`` — ABCD/data_loader.py:112-119)."""
    import h5py

    data = generate_synthetic_abcd(**kwargs)
    with h5py.File(path, "w") as f:
        f.create_dataset("X", data=data["X"], chunks=(1,) + data["X"].shape[1:])
        f.create_dataset("y", data=data["y"])
        f.create_dataset("site", data=data["site"])
    return data
