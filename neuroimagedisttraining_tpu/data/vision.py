"""CIFAR-10/100 + TinyImageNet ingestion and the reference's vision
partition modes (fedml_api/data_preprocessing/cifar10/data_loader.py:75-249,
cifar100/ and tiny_imagenet/ mirrors).

Ingestion is dependency-light and egress-free: the canonical pickled batch
folders (``cifar-10-batches-py`` / ``cifar-100-python``) are read directly
(no torchvision), a ``.npz`` with {X_train,y_train,X_test,y_test} works for
any dataset (incl. TinyImageNet exported once from its ImageFolder layout),
and ``synthetic_vision_cohort`` generates class-separable images for tests.
Images are normalized at load with the standard per-channel mean/std the
reference's transforms use (_data_transforms_cifar10, data_loader.py:34-60)
— the device pipeline then treats them as opaque float32 [N,H,W,C].

Partition modes (partition_data, data_loader.py:75-190) share one
sequential-draw loop: equal client quotas (the reference's lognormal has
sigma=0 ⇒ deterministic sizes), per-client class priors, then repeated
{pick random unfilled client, draw class from its prior, pop an index from
that class pool}:

- ``n_cls``:   priors uniform over int(alpha) randomly chosen classes per
               client; exhausted class pools get a random-size refill
               (data_loader.py:104-109 — duplicates by design).
- ``dir``:     priors ~ Dirichlet(alpha); exhausted classes are redrawn
               (data_loader.py:135-147). Deviation (documented): when ALL of
               a client's prior mass is exhausted the reference spins
               forever; we renormalize over non-empty classes instead.
- ``my_part``: ``alpha`` shard groups, each with one Dirichlet(0.3) prior
               shared by its clients; exhausted pools reset to full
               (data_loader.py:149-190).

Test sets are label-proportional per client: each client draws ~|test|/C
samples from the global test pool matching its train class mix
(load_partition_data_cifar10, data_loader.py:216-234).
"""

from __future__ import annotations

import os
import pickle

import numpy as np

CIFAR10_MEAN = (0.4914, 0.4822, 0.4465)
CIFAR10_STD = (0.2470, 0.2435, 0.2616)
CIFAR100_MEAN = (0.5071, 0.4865, 0.4409)
CIFAR100_STD = (0.2673, 0.2564, 0.2762)
TINY_MEAN = (0.4802, 0.4481, 0.3975)
TINY_STD = (0.2770, 0.2691, 0.2821)

_STATS = {"cifar10": (CIFAR10_MEAN, CIFAR10_STD),
          "cifar100": (CIFAR100_MEAN, CIFAR100_STD),
          "tiny": (TINY_MEAN, TINY_STD)}


def _normalize(X_u8: np.ndarray, name: str) -> np.ndarray:
    mean, std = _STATS[name]
    X = X_u8.astype(np.float32) / 255.0
    return (X - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)


# ---------------- ingestion ----------------

def _load_pickle_batches(data_dir: str, name: str):
    """Read the canonical CIFAR pickled batch folders without torchvision."""
    if name == "cifar10":
        folder = os.path.join(data_dir, "cifar-10-batches-py")
        train_files = [f"data_batch_{i}" for i in range(1, 6)]
        test_files = ["test_batch"]
        label_key = b"labels"
    else:
        folder = os.path.join(data_dir, "cifar-100-python")
        train_files, test_files = ["train"], ["test"]
        label_key = b"fine_labels"
    if not os.path.isdir(folder):
        return None

    def read(files):
        xs, ys = [], []
        for f in files:
            with open(os.path.join(folder, f), "rb") as fh:
                d = pickle.load(fh, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8)
                      .reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            ys.append(np.asarray(d[label_key], np.int32))
        return np.concatenate(xs), np.concatenate(ys)

    Xtr, ytr = read(train_files)
    Xte, yte = read(test_files)
    return Xtr, ytr, Xte, yte


def _load_npz(data_dir: str):
    for cand in (data_dir, os.path.join(data_dir, "data.npz")):
        if os.path.isfile(cand) and cand.endswith(".npz"):
            z = np.load(cand)
            return (np.asarray(z["X_train"]), np.asarray(z["y_train"]),
                    np.asarray(z["X_test"]), np.asarray(z["y_test"]))
    return None


def _load_tiny_imagenet_folder(data_dir: str):
    """Canonical tiny-imagenet-200 ImageFolder layout (the reference's
    loader wraps it in torchvision ImageFolder,
    tiny_imagenet/data_loader.py:81-121): train/<wnid>/images/*.JPEG with
    classes in sorted-wnid order; val/ images labeled by
    val_annotations.txt. Requires PIL; returns None when layout absent."""
    root = data_dir
    if os.path.isdir(os.path.join(data_dir, "tiny-imagenet-200")):
        root = os.path.join(data_dir, "tiny-imagenet-200")
    train_dir = os.path.join(root, "train")
    val_dir = os.path.join(root, "val")
    if not (os.path.isdir(train_dir) and os.path.isdir(val_dir)):
        return None
    try:
        from PIL import Image
    except ImportError:
        return None

    wnids = sorted(d for d in os.listdir(train_dir)
                   if os.path.isdir(os.path.join(train_dir, d)))
    cls = {w: i for i, w in enumerate(wnids)}  # ImageFolder sorted order

    def read(path):
        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"), np.uint8)

    Xtr, ytr = [], []
    for w in wnids:
        img_dir = os.path.join(train_dir, w, "images")
        for f in sorted(os.listdir(img_dir)):
            Xtr.append(read(os.path.join(img_dir, f)))
            ytr.append(cls[w])
    Xte, yte = [], []
    ann = os.path.join(val_dir, "val_annotations.txt")
    with open(ann) as fh:
        for line in fh:
            parts = line.split("\t")
            if len(parts) < 2 or parts[1] not in cls:
                continue
            Xte.append(read(os.path.join(val_dir, "images", parts[0])))
            yte.append(cls[parts[1]])
    return (np.stack(Xtr), np.asarray(ytr, np.int32),
            np.stack(Xte), np.asarray(yte, np.int32))


def load_vision_dataset(name: str, data_dir: str):
    """-> (X_train f32 normalized [N,H,W,C], y_train i32, X_test, y_test)."""
    if name in ("cifar10", "cifar100"):
        raw = _load_pickle_batches(data_dir, name) or _load_npz(data_dir)
    elif name == "tiny":
        raw = _load_tiny_imagenet_folder(data_dir) or _load_npz(data_dir)
    else:
        raise ValueError(f"unknown vision dataset {name!r}")
    if raw is None:
        raise FileNotFoundError(
            f"no {name} data under {data_dir!r}: expected the pickled batch "
            "folder or an .npz with X_train/y_train/X_test/y_test")
    Xtr, ytr, Xte, yte = raw
    if Xtr.dtype == np.uint8:
        Xtr, Xte = _normalize(Xtr, name), _normalize(Xte, name)
    return (Xtr.astype(np.float32), ytr.astype(np.int32),
            Xte.astype(np.float32), yte.astype(np.int32))


def synthetic_vision_cohort(num_train: int = 256, num_test: int = 96,
                            num_classes: int = 10, hw: int = 32,
                            seed: int = 0):
    """Tiny class-separable images for tests: class-k images carry a mean
    shift in a class-specific channel/quadrant pattern."""
    rng = np.random.default_rng(seed)

    def make(n):
        y = rng.integers(0, num_classes, size=n).astype(np.int32)
        X = rng.normal(size=(n, hw, hw, 3)).astype(np.float32)
        for k in range(num_classes):
            sel = y == k
            X[sel, k % hw, :, k % 3] += 2.5
        return X, y

    Xtr, ytr = make(num_train)
    Xte, yte = make(num_test)
    return Xtr, ytr, Xte, yte


# ---------------- partition modes ----------------

def _draw_partition(y: np.ndarray, quotas: np.ndarray, priors: np.ndarray,
                    mode: str, rs: np.random.RandomState
                    ) -> dict[int, np.ndarray]:
    """The reference's shared sequential-draw loop
    (data_loader.py:97-147, identical skeleton in all three modes)."""
    n_client, n_cls = priors.shape
    prior_cumsum = np.cumsum(priors, axis=1)
    idx_list = [np.where(y == k)[0] for k in range(n_cls)]
    cls_amount = np.asarray([len(ix) for ix in idx_list], np.int64)
    out: list[list[int]] = [[] for _ in range(n_client)]
    quotas = quotas.copy()
    while quotas.sum() > 0:
        c = rs.randint(n_client)
        if quotas[c] <= 0:
            continue
        quotas[c] -= 1
        redraws = 0
        while True:
            k = int(np.argmax(rs.uniform() <= prior_cumsum[c]))
            if cls_amount[k] <= 0:
                # classes with NO samples at all (sparse label sets in a
                # user .npz, or num_classes > observed classes) can never
                # refill — redraw like dir mode instead of crashing
                if len(idx_list[k]) == 0:
                    mode_here = "dir"
                else:
                    mode_here = mode
                if mode_here == "n_cls":
                    # random-size refill (data_loader.py:107-108)
                    cls_amount[k] = rs.randint(0, len(idx_list[k]))
                    continue
                if mode_here == "my_part":
                    cls_amount[k] = len(idx_list[k])  # full reset (:184)
                    continue
                # dir: redraw; guard against the reference's infinite spin
                redraws += 1
                if redraws > 100:
                    alive = np.flatnonzero(cls_amount > 0)
                    k = int(rs.choice(alive))
                else:
                    continue
            cls_amount[k] -= 1
            out[c].append(int(idx_list[k][cls_amount[k]]))
            break
    return {c: np.asarray(sorted(ix), np.int64) for c, ix in enumerate(out)}


def vision_partition(y_train: np.ndarray, client_number: int, alpha: float,
                     method: str, seed: int = 0,
                     num_classes: int | None = None
                     ) -> dict[int, np.ndarray]:
    rs = np.random.RandomState(seed)
    n_cls = int(num_classes if num_classes is not None
                else y_train.max() + 1)
    n = len(y_train)
    # lognormal(sigma=0) == deterministic equal quotas (data_loader.py:83-85)
    quotas = np.full(client_number, n / client_number)
    quotas = (quotas / quotas.sum() * n).astype(np.int64)

    if method == "n_cls":
        a = max(1, int(alpha))
        priors = np.zeros((client_number, n_cls))
        for c in range(client_number):
            chosen = rs.choice(n_cls, a, replace=False)
            priors[c, chosen] = 1.0 / a
    elif method == "dir":
        priors = rs.dirichlet([alpha] * n_cls, size=client_number)
    elif method == "my_part":
        n_shards = max(1, int(alpha))
        group_priors = rs.dirichlet([0.3] * n_cls, size=n_shards)
        per_group = max(1, client_number // n_shards)
        priors = np.stack([group_priors[min(c // per_group, n_shards - 1)]
                           for c in range(client_number)])
    else:
        raise ValueError(f"unknown vision partition {method!r}")
    return _draw_partition(y_train, quotas, priors, method, rs)


def proportional_test_split(y_test: np.ndarray, train_stats: dict,
                            client_number: int, seed: int = 0,
                            num_classes: int | None = None
                            ) -> dict[int, np.ndarray]:
    """Per-client test sets drawn from the global pool matching each
    client's train class mix (data_loader.py:216-234)."""
    rs = np.random.RandomState(seed)
    n_cls = int(num_classes if num_classes is not None else y_test.max() + 1)
    idx_by_cls = [np.where(y_test == k)[0] for k in range(n_cls)]
    per_client = int(np.ceil(len(y_test) / client_number))
    out = {}
    for c in range(client_number):
        counts = train_stats.get(c, {})
        total = max(1, sum(counts.values()))
        picks = []
        for k in range(n_cls):
            want = int(np.ceil(counts.get(k, 0) / total * per_client))
            if want <= 0:
                continue
            perm = rs.permutation(len(idx_by_cls[k]))
            picks.append(idx_by_cls[k][perm[:want]])
        out[c] = (np.sort(np.concatenate(picks)) if picks
                  else np.asarray([], np.int64))
    return out


# ---------------- federation assembly ----------------

def federate_vision(name: str, data_dir: str, partition_method: str,
                    alpha: float, client_number: int, mesh=None,
                    val_fraction: float = 0.0, seed: int = 0,
                    synthetic: bool = False, num_classes: int | None = None,
                    synthetic_num: tuple[int, int] | None = None):
    """-> (FederatedData, info): the vision counterpart of federate_cohort,
    with separate train/test pools and the reference's partition modes."""
    from neuroimagedisttraining_tpu.data import partition as P
    from neuroimagedisttraining_tpu.data.federate import build_federated_data

    if synthetic:
        # sizes default inside synthetic_vision_cohort (single source)
        Xtr, ytr, Xte, yte = synthetic_vision_cohort(
            *(synthetic_num or ()), seed=seed,
            num_classes=num_classes or 10)
    else:
        Xtr, ytr, Xte, yte = load_vision_dataset(name, data_dir)
    n_cls = int(num_classes if num_classes is not None else ytr.max() + 1)

    if partition_method in ("n_cls", "dir", "my_part"):
        train_map = vision_partition(ytr, client_number, alpha,
                                     partition_method, seed=seed,
                                     num_classes=n_cls)
    elif partition_method in ("homo", "hetero"):
        if partition_method == "homo":
            train_map = P.homo_partition(len(ytr), client_number, seed=seed)
        else:
            train_map = P.dirichlet_partition(ytr, client_number, alpha,
                                              seed=seed)
    else:
        raise ValueError(
            f"unknown vision partition_method {partition_method!r}")

    stats = P.record_data_stats(ytr, train_map)
    test_map = proportional_test_split(yte, stats, client_number, seed=seed,
                                       num_classes=n_cls)

    val_map = None
    if val_fraction > 0:  # FedFomo 9-tuple (cifar10/data_val_loader.py)
        val_map, new_train = {}, {}
        rs = np.random.RandomState(seed + 1)  # one stream: clients get
        # independent permutations, not copies of the same one
        for c, idx in train_map.items():
            idx = np.array(idx, copy=True)
            rs.shuffle(idx)
            nv = max(1, int(len(idx) * val_fraction))
            val_map[c], new_train[c] = idx[:nv], idx[nv:]
        train_map = new_train

    info = {"partition_method": partition_method, "stats": stats,
            "client_num": client_number,
            "train_counts": [int(len(train_map[c]))
                             for c in sorted(train_map)]}
    fed = build_federated_data(Xtr, ytr, train_map, test_map, mesh=mesh,
                               val_map=val_map, X_eval=Xte, y_eval=yte)
    return fed, info
