"""HDF5 cohort reader — the reference ABCD schema.

The reference opens one HDF5 file with keys ``X`` (uint8 voxel volumes),
``y`` (labels), ``site`` (acquisition-site labels), reads ``y``/``site``
eagerly and replaces ``X`` with an index tensor for lazy per-batch fetching
(ABCD/data_loader.py:105-119; the actual voxel I/O happens inside the
trainers, my_model_trainer.py:185-199).

Here the same split: ``load_abcd_hdf5(lazy=True)`` keeps ``X`` as the open
h5py dataset (a lazy, sliceable handle the streaming layer fancy-reads per
round), ``lazy=False`` materializes it (small cohorts / tests).
"""

from __future__ import annotations

import numpy as np


def load_abcd_hdf5(path: str, lazy: bool = True) -> dict:
    """Open a reference-schema HDF5 cohort.

    Returns ``{"X": h5py.Dataset | ndarray, "y": ndarray, "site": ndarray,
    "file": h5py.File | None}``. With ``lazy=True`` the caller owns the open
    file handle (close via ``cohort["file"].close()``); voxels are fetched
    on demand. Schema parity: ABCD/data_loader.py:112-119.
    """
    import h5py

    f = h5py.File(path, "r")
    for key in ("X", "y", "site"):
        if key not in f:
            f.close()
            raise KeyError(
                f"HDF5 cohort {path!r} missing dataset {key!r} "
                "(reference schema: X, y, site — ABCD/data_loader.py:112)")
    y = np.asarray(f["y"])
    site = np.asarray(f["site"])
    if lazy:
        return {"X": f["X"], "y": y, "site": site, "file": f}
    X = np.asarray(f["X"])
    f.close()
    return {"X": X, "y": y, "site": site, "file": None}


def fetch_rows(X_source, idx: np.ndarray) -> np.ndarray:
    """Fancy-read rows by (possibly unsorted) indices, preserving order.

    h5py requires increasing unique indices for fancy reads; the reference
    sorts the batch index tensor before reading
    (sailentgrads/my_model_trainer.py:185-193). We sort, read, and undo the
    permutation so callers get rows in the order they asked for.
    """
    from neuroimagedisttraining_tpu.utils import native

    idx = np.asarray(idx)
    if isinstance(X_source, np.ndarray):
        # multithreaded native row gather (numpy fallback inside)
        return native.gather_rows(X_source, idx)
    order = np.argsort(idx, kind="stable")
    sorted_idx, inv = idx[order], np.empty_like(order)
    inv[order] = np.arange(len(order))
    # h5py also rejects duplicate indices; collapse then re-expand
    uniq, uniq_inverse = np.unique(sorted_idx, return_inverse=True)
    data = np.ascontiguousarray(X_source[uniq])
    return native.gather_rows(data, uniq_inverse[inv])
