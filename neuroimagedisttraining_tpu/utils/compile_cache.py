"""Persistent XLA compilation-cache plumbing (ISSUE 4 satellite).

The flagship 3D-CNN round program costs ~30 s to compile; with the
persistent cache the compile is paid once per machine, not once per
process — repeat experiments, every silo process of a cross-silo run,
and bench reruns all hit the disk cache. One resolution order everywhere
(both CLIs and bench.py): explicit flag value > ``NIDT_COMPILE_CACHE``
env var > the caller's default. An empty resolved path disables caching.
"""

from __future__ import annotations

import os

#: shared default for the CLIs ("" = caller opts out by default)
DEFAULT_CACHE_DIR = "/tmp/nidt_jax_cache"


def enable_compile_cache(path: str | None = None,
                         default: str = DEFAULT_CACHE_DIR) -> str | None:
    """Point JAX's persistent compilation cache at a directory.

    ``path=None`` means "not specified on the command line": the
    ``NIDT_COMPILE_CACHE`` env var is consulted, then ``default``.
    An explicit empty string (or empty resolution) disables the cache.
    Returns the directory in effect, or None when disabled. Call BEFORE
    the first compilation — entries written earlier in the process are
    not retroactively cached."""
    if path is None:
        path = os.environ.get("NIDT_COMPILE_CACHE") or default
    if not path:
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # cache everything that took meaningfully long to build; the 0.2 s
    # floor skips trivial op-by-op executables whose disk round-trip
    # costs more than recompiling
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    return path
