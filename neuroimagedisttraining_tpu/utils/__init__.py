from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger, get_logger  # noqa: F401
from neuroimagedisttraining_tpu.utils import pytree  # noqa: F401
