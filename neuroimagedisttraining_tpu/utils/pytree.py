"""Pytree helpers used across engines.

The reference moves model state around as ``OrderedDict`` state dicts with
``copy.deepcopy`` (sailentgrads_api.py:131-136). Here all federated state is
JAX pytrees; these helpers provide the small algebra the engines share.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

PyTree = Any


def tree_zeros_like(tree: PyTree) -> PyTree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_ones_like(tree: PyTree) -> PyTree:
    return jax.tree.map(jnp.ones_like, tree)


def tree_add(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: PyTree, s) -> PyTree:
    return jax.tree.map(lambda x: x * s, tree)


def tree_mul(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree.map(jnp.multiply, a, b)


def tree_dot(a: PyTree, b: PyTree) -> jax.Array:
    parts = jax.tree.leaves(jax.tree.map(
        lambda x, y: jnp.vdot(x.astype(jnp.float32), y.astype(jnp.float32)), a, b))
    return jnp.sum(jnp.stack(parts))


def tree_norm(tree: PyTree) -> jax.Array:
    """Global L2 norm over all leaves (torch clip_grad_norm_ semantics)."""
    return jnp.sqrt(jnp.maximum(tree_dot(tree, tree), 0.0))


def tree_size(tree: PyTree) -> int:
    return sum(x.size for x in jax.tree.leaves(tree))


def tree_nnz(tree: PyTree) -> jax.Array:
    """Count of nonzero entries — the reference's communication-volume metric
    (fedml_core/trainer/model_trainer.py:49-53)."""
    return sum(jnp.sum(x != 0) for x in jax.tree.leaves(tree))


def tree_vector(tree: PyTree) -> jax.Array:
    """Flatten-concat all leaves to one vector (robust_aggregation.py:4-12)."""
    return jnp.concatenate([jnp.ravel(x) for x in jax.tree.leaves(tree)])


def tree_stack_index(tree: PyTree, idx) -> PyTree:
    """Gather rows of a leading-axis-stacked pytree: tree[idx] per leaf."""
    return jax.tree.map(lambda x: x[idx], tree)


def tree_weighted_mean(stacked: PyTree, weights: jax.Array) -> PyTree:
    """Weighted mean over the leading (client) axis of a stacked pytree.

    This IS FedAvg: with the client axis sharded over the mesh, XLA lowers the
    sum to an ICI all-reduce (replaces fedavg_api.py:102-117's Python loop).
    """
    w = weights / jnp.maximum(jnp.sum(weights), 1e-12)

    def leaf(x):
        wb = w.reshape((-1,) + (1,) * (x.ndim - 1)).astype(jnp.float32)
        return jnp.sum(x.astype(jnp.float32) * wb, axis=0).astype(x.dtype)

    return jax.tree.map(leaf, stacked)


def tree_by_name(tree: PyTree, name: str):
    """Look up a leaf by its '/'-joined key path (the naming used by
    tree_map_with_path_names)."""
    node = tree
    for part in name.split("/"):
        node = node[part] if isinstance(node, dict) else node[int(part)]
    return node


def tree_map_with_path_names(fn: Callable[[str, jax.Array], jax.Array],
                             tree: PyTree) -> PyTree:
    """Map with a '/'-joined key-path string, for name-conditioned transforms
    (e.g. mask only conv/linear kernels)."""
    def wrap(path, leaf):
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        return fn(name, leaf)
    return jax.tree_util.tree_map_with_path(wrap, tree)
