"""ctypes loader for the native host-data-path library (native/gather.cpp).

Build-on-first-use: compiles the shared library with g++ into the package's
``native/`` directory the first time it's needed (pybind11 is not in this
image; ctypes + extern "C" needs no Python headers at all). Every entry
point has a numpy fallback, so the framework runs — just slower on the
host-streaming path — on boxes without a toolchain.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger("neuroimagedisttraining_tpu.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                           "native")
_SRC = os.path.join(_NATIVE_DIR, "gather.cpp")
_SO = os.path.join(_NATIVE_DIR, "libnidt_gather.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | bool | None = None  # None = not tried, False = failed

DEFAULT_THREADS = min(8, os.cpu_count() or 1)


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
           _SRC, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        # surface WHY the numpy slow path is in use; logged once per
        # process because load() latches _lib = False after this fails
        stderr = getattr(e, "stderr", None)
        detail = (stderr.decode("utf-8", errors="replace").strip()
                  if stderr else str(e))
        log.warning("native gather build failed (%s); falling back to the "
                    "numpy slow path: %s", " ".join(cmd), detail)
        return False


def load() -> ctypes.CDLL | None:
    """The library handle, building it if necessary; None when unavailable."""
    global _lib
    with _lock:
        if _lib is False:
            return None
        if _lib is not None:
            return _lib
        try:
            fresh = (os.path.isfile(_SO)
                     and os.path.getmtime(_SO) >= os.path.getmtime(_SRC))
        except OSError:
            # source missing (e.g. binary-only install): use the .so as-is
            fresh = os.path.isfile(_SO)
        if not fresh and not _build():
            _lib = False
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _lib = False
            return None
        lib.nidt_gather_rows_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int]
        _lib = lib
        return lib


def gather_rows(src: np.ndarray, idx: np.ndarray,
                out: np.ndarray | None = None,
                n_threads: int = DEFAULT_THREADS) -> np.ndarray:
    """dst[i] = src[idx[i]] — multithreaded row gather for uint8 sources,
    numpy fallback otherwise. ``out`` may supply a preallocated target
    (e.g. a slice of the padded round buffer)."""
    idx = np.ascontiguousarray(idx, np.int64)
    lib = load()
    if (lib is None or src.dtype != np.uint8
            or not src.flags["C_CONTIGUOUS"]):
        gathered = src[idx]
        if out is None:
            return gathered
        out[: len(idx)] = gathered
        return out
    row_bytes = int(np.prod(src.shape[1:], dtype=np.int64)) * src.itemsize
    if out is None:
        out = np.empty((len(idx),) + src.shape[1:], src.dtype)
    dst = out[: len(idx)]
    assert dst.flags["C_CONTIGUOUS"]
    lib.nidt_gather_rows_u8(
        src.ctypes.data, idx.ctypes.data, len(idx), row_bytes,
        dst.ctypes.data, n_threads)
    return out
