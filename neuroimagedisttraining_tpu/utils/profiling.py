"""Profiling hooks + failure context.

SURVEY §5.1: the reference has no timeline profiler, only hook-based FLOPs
counting; the TPU equivalent it prescribes is ``jax.profiler`` traces (+ the
analytic FLOPs model in ops/flops.py). ``profile_trace`` wraps any span in a
TensorBoard-loadable trace capture (XLA ops, HBM, ICI); the CLI exposes it
as ``--profile_dir``.

SURVEY §5.3 / §2.7: the reference's failure handling is the
``raise_MPI_error`` context manager — log traceback, then
``MPI.COMM_WORLD.Abort()`` (fedml_api/utils/context.py:9-18).
``failure_context`` is the equivalent for our runtime: log, run the
registered teardown (e.g. a comm manager's stop, or
``jax.distributed.shutdown`` in multi-host mode), re-raise.
"""

from __future__ import annotations

import contextlib
import logging
import traceback
from typing import Callable


@contextlib.contextmanager
def profile_trace(log_dir: str | None, enabled: bool = True):
    """Capture a jax.profiler trace of the enclosed span into ``log_dir``
    (viewable in TensorBoard / XProf). No-op when disabled or dir empty."""
    if not (enabled and log_dir):
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named sub-span inside a trace (shows up on the timeline)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def failure_context(logger: logging.Logger | None = None,
                    teardown: Callable[[], None] | None = None,
                    name: str = "run"):
    """Log-then-teardown-then-reraise (raise_MPI_error parity,
    context.py:9-18 — minus the unsound process Abort: teardown is
    caller-supplied and the exception propagates)."""
    log = logger or logging.getLogger("neuroimagedisttraining_tpu")
    try:
        yield
    except Exception as exc:
        log.error("FATAL in %s:\n%s", name, traceback.format_exc())
        # flight-recorder post-mortem (obs/flight.py, ISSUE 9): the last
        # N control-plane decisions, dumped BEFORE teardown can destroy
        # more state; dumping must never mask the original exception
        try:
            from neuroimagedisttraining_tpu.obs import flight

            flight.record("failure", name=name,
                          error=f"{type(exc).__name__}: {exc}")
            out = flight.dump(reason=f"failure_context: {name}")
            if out:
                log.error("flight recorder dumped to %s", out)
            else:
                # no dump path configured (e.g. a silo rank): the
                # recorded decisions must not vanish — log the tail
                evs = flight.events()
                if evs:
                    log.error("no flight dump path configured; last "
                              "%d of %d flight events: %s",
                              min(20, len(evs)), len(evs), evs[-20:])
        except Exception:  # noqa: BLE001 — best-effort post-mortem
            pass
        if teardown is not None:
            try:
                teardown()
            except Exception:
                log.error("teardown after failure also failed:\n%s",
                          traceback.format_exc())
        raise
