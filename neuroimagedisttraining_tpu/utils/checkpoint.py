"""Round-granular checkpoint/resume.

The reference has NO checkpointing — 3-day SLURM runs killed at the time
limit lost everything (SURVEY.md §5.4, DisPFL/error3469448.err). This module
is the rebuild requirement SURVEY names: save {params, per-client stacked
states, masks, opt state, round idx, PRNG keys, history, stat accumulators}
every ``checkpoint_every`` rounds; resume replays the remaining rounds
bitwise-identically (all per-round randomness is derived from the round
index, so state + round is a complete resume point).

Format: flax msgpack over a dict pytree of numpy arrays, written atomically
(tmp + rename). Typed JAX PRNG keys are encoded via ``jax.random.key_data``
and rebuilt with ``wrap_key_data`` on load.
"""

from __future__ import annotations

import os
import re
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

_CKPT_RE = re.compile(r"^ckpt_(\d{8})\.msgpack$")
_KEY_MARK = "__prng_key_data__"


def _is_prng_key(x) -> bool:
    return isinstance(x, jax.Array) and jnp.issubdtype(x.dtype,
                                                       jax.dtypes.prng_key)


def _encode(tree: Any) -> Any:
    def enc(x):
        if _is_prng_key(x):
            return {_KEY_MARK: np.asarray(jax.random.key_data(x))}
        if isinstance(x, (jax.Array, np.ndarray)):
            return np.asarray(x)
        return x

    return jax.tree.map(enc, tree)


def _decode(tree: Any) -> Any:
    def is_marked(x):
        return isinstance(x, dict) and _KEY_MARK in x

    def dec(x):
        if is_marked(x):
            return jax.random.wrap_key_data(jnp.asarray(x[_KEY_MARK]))
        return x

    return jax.tree.map(dec, tree, is_leaf=is_marked)


def _path(ckpt_dir: str, round_idx: int) -> str:
    return os.path.join(ckpt_dir, f"ckpt_{round_idx:08d}.msgpack")


def save_checkpoint(ckpt_dir: str, round_idx: int, state: dict,
                    keep: int = 3) -> str:
    """Atomically write the state pytree for ``round_idx`` (the round just
    completed); prune to the newest ``keep`` checkpoints."""
    os.makedirs(ckpt_dir, exist_ok=True)
    payload = {"round": int(round_idx), "state": _encode(state)}
    raw = serialization.msgpack_serialize(payload)
    final = _path(ckpt_dir, round_idx)
    tmp = final + ".tmp"
    with open(tmp, "wb") as f:
        f.write(raw)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    for old in list_checkpoints(ckpt_dir)[:-keep]:
        os.unlink(_path(ckpt_dir, old))
    return final


def list_checkpoints(ckpt_dir: str) -> list[int]:
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def load_checkpoint(ckpt_dir: str, round_idx: int | None = None
                    ) -> tuple[int, dict] | None:
    """Load the given (or latest) checkpoint. Returns (round_idx, state) —
    ``round_idx`` is the last COMPLETED round; resume at round_idx + 1."""
    rounds = list_checkpoints(ckpt_dir)
    if not rounds:
        return None
    if round_idx is None:
        round_idx = rounds[-1]
    elif round_idx not in rounds:
        raise FileNotFoundError(
            f"no checkpoint for round {round_idx} in {ckpt_dir} "
            f"(have {rounds})")
    with open(_path(ckpt_dir, round_idx), "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    return int(payload["round"]), _decode(payload["state"])
