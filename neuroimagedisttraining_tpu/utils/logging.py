"""Experiment logging.

Replaces the reference's per-experiment ``logging.FileHandler`` under
``LOG/<dataset>/<identity>.log`` (main_sailentgrads.py:184-192) with the same
file layout plus a structured round-indexed JSONL metrics stream, which the
reference lacked (its metrics lived only in free-text log lines).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any, Mapping


def get_logger(name: str = "nidt", process_id: int = 0) -> logging.Logger:
    """Console logger with process id in the format, mirroring
    fedml_api/utils/logger.py:7-33."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(logging.Formatter(
            f"[p{process_id}] %(asctime)s %(levelname)s %(name)s: %(message)s"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


class ExperimentLogger:
    """File log + JSONL metrics for one experiment identity."""

    def __init__(self, log_dir: str, dataset: str, identity: str,
                 console: bool = True):
        self.dir = os.path.join(log_dir, dataset)
        os.makedirs(self.dir, exist_ok=True)
        self.identity = identity
        self.log_path = os.path.join(self.dir, identity + ".log")
        self.jsonl_path = os.path.join(self.dir, identity + ".metrics.jsonl")
        self._log = logging.getLogger(f"nidt.exp.{identity}")
        self._log.setLevel(logging.INFO)
        self._log.propagate = False
        # logging.getLogger CACHES by name: constructing a second
        # ExperimentLogger with the same identity (benches, re-built
        # engines, tests) used to STACK another FileHandler/StreamHandler
        # on the cached logger, duplicating every subsequent line once
        # per construction — drop any handlers a previous instance left
        # before adding ours (regression-pinned in tests/test_obs.py)
        for h in list(self._log.handlers):
            h.close()
            self._log.removeHandler(h)
        fh = logging.FileHandler(self.log_path)
        fh.setFormatter(logging.Formatter("%(message)s"))  # message-only parity
        self._log.addHandler(fh)
        if console:
            ch = logging.StreamHandler(sys.stdout)
            ch.setFormatter(logging.Formatter("%(message)s"))
            self._log.addHandler(ch)
        self._t0 = time.monotonic()

    def info(self, msg: str, *args: Any) -> None:
        self._log.info(msg, *args)

    def warning(self, msg: str, *args: Any) -> None:
        self._log.warning(msg, *args)

    def metrics(self, round_idx: int, **values: Any) -> None:
        """Append one structured metrics record for a round — and
        publish every numeric scalar into the obs metrics registry
        (obs/metrics.py, ISSUE 9), so a live ``/metrics`` scrape sees
        the same train_loss/acc/auc series the JSONL file records."""
        rec: dict[str, Any] = {"round": int(round_idx),
                               "t": round(time.monotonic() - self._t0, 3)}
        for k, v in values.items():
            rec[k] = _jsonable(v)
        with open(self.jsonl_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        self._publish_registry(round_idx, rec)
        self._log.info("round %d metrics: %s", round_idx,
                       {k: rec[k] for k in values})

    def _publish_registry(self, round_idx: int, rec: Mapping[str, Any]
                          ) -> None:
        """Gauge semantics (last value wins) keyed by metric name — one
        flat namespace, nested dicts flattened with ``_`` (the same
        flattening the JSONL reader would do)."""
        from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
        from neuroimagedisttraining_tpu.obs import names as obs_names

        g = obs_metrics.gauge(
            obs_names.EXP_METRIC,
            "per-round experiment metrics (ExperimentLogger.metrics)",
            labelnames=("key",))
        obs_metrics.gauge(
            obs_names.EXP_ROUND,
            "last round index ExperimentLogger.metrics recorded",
        ).set(int(round_idx))

        def put(prefix: str, v: Any) -> None:
            if isinstance(v, Mapping):
                for k2, v2 in v.items():
                    put(f"{prefix}_{k2}", v2)
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                g.labels(key=prefix).set(float(v))

        for k, v in rec.items():
            if k not in ("round", "t"):
                put(k, v)

    def close(self) -> None:
        for h in list(self._log.handlers):
            h.close()
            self._log.removeHandler(h)


def _jsonable(v: Any) -> Any:
    if isinstance(v, Mapping):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if hasattr(v, "item") and getattr(v, "ndim", None) == 0:
        return v.item()
    if hasattr(v, "tolist"):
        return v.tolist()
    return v
