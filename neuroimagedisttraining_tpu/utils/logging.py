"""Experiment logging.

Replaces the reference's per-experiment ``logging.FileHandler`` under
``LOG/<dataset>/<identity>.log`` (main_sailentgrads.py:184-192) with the same
file layout plus a structured round-indexed JSONL metrics stream, which the
reference lacked (its metrics lived only in free-text log lines).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any, Mapping


def get_logger(name: str = "nidt", process_id: int = 0) -> logging.Logger:
    """Console logger with process id in the format, mirroring
    fedml_api/utils/logger.py:7-33."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(logging.Formatter(
            f"[p{process_id}] %(asctime)s %(levelname)s %(name)s: %(message)s"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


class ExperimentLogger:
    """File log + JSONL metrics for one experiment identity."""

    def __init__(self, log_dir: str, dataset: str, identity: str,
                 console: bool = True):
        self.dir = os.path.join(log_dir, dataset)
        os.makedirs(self.dir, exist_ok=True)
        self.identity = identity
        self.log_path = os.path.join(self.dir, identity + ".log")
        self.jsonl_path = os.path.join(self.dir, identity + ".metrics.jsonl")
        self._log = logging.getLogger(f"nidt.exp.{identity}")
        self._log.setLevel(logging.INFO)
        self._log.propagate = False
        fh = logging.FileHandler(self.log_path)
        fh.setFormatter(logging.Formatter("%(message)s"))  # message-only parity
        self._log.addHandler(fh)
        if console:
            ch = logging.StreamHandler(sys.stdout)
            ch.setFormatter(logging.Formatter("%(message)s"))
            self._log.addHandler(ch)
        self._t0 = time.monotonic()

    def info(self, msg: str, *args: Any) -> None:
        self._log.info(msg, *args)

    def warning(self, msg: str, *args: Any) -> None:
        self._log.warning(msg, *args)

    def metrics(self, round_idx: int, **values: Any) -> None:
        """Append one structured metrics record for a round."""
        rec: dict[str, Any] = {"round": int(round_idx),
                               "t": round(time.monotonic() - self._t0, 3)}
        for k, v in values.items():
            rec[k] = _jsonable(v)
        with open(self.jsonl_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        self._log.info("round %d metrics: %s", round_idx,
                       {k: rec[k] for k in values})

    def close(self) -> None:
        for h in list(self._log.handlers):
            h.close()
            self._log.removeHandler(h)


def _jsonable(v: Any) -> Any:
    if isinstance(v, Mapping):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if hasattr(v, "item") and getattr(v, "ndim", None) == 0:
        return v.item()
    if hasattr(v, "tolist"):
        return v.tolist()
    return v
