"""Privacy plane: secure quantized aggregation + a real RDP accountant.

Two pillars (ROADMAP item 5, ARCHITECTURE.md "Privacy plane"):

- ``secure_quant`` — secure aggregation over uniform-QUANTIZED updates
  in a small GF(p): field-element frames (one wire-dtype residue per
  parameter + seed-expanded mask slots) replace the dense protocol's
  int64 share stacks, so privacy finally composes with the bandwidth
  story instead of costing 6x the plain wire. Bitwise-exact vs the
  plain quantized weighted mean on the same survivor set, Bonawitz
  dropout semantics preserved.
- ``accountant`` — an RDP/moments accountant (subsampled Gaussian,
  integer order grid, Mironov epsilon conversion) wired into the
  ``weak_dp`` defense and the dpsgd clip+noise path, reporting per-silo
  (epsilon, delta) in ``stat_info`` and the run-end audit.

Key discipline (nidtlint ``dp-key-discipline``): nothing in this
package constructs a PRNG root — mask/noise randomness arrives as
caller-threaded generators or jax keys derived from the config seed.
"""

from neuroimagedisttraining_tpu.privacy.accountant import (  # noqa: F401
    DEFAULT_ORDERS,
    RDPAccountant,
    rdp_gaussian,
    rdp_to_epsilon,
    weak_dp_noise_multiplier,
)
from neuroimagedisttraining_tpu.privacy.secure_quant import (  # noqa: F401
    QuantSpec,
    SlotAccumulator,
    check_headroom,
    encode_secure_quant,
    integer_weights,
    is_secure_quant_frame,
    leaf_scales,
    quantized_weighted_mean,
    weighted_fold_capacity,
)
