"""Secure QUANTIZED aggregation: field-element frames over small GF(p).

The dense secure protocol (cross_silo.SecureFedAvgServer) ships every
client upload as ``n_shares`` int64 slot arrays — ~``8 * n_shares``
bytes per parameter, 6x MORE than the plain dense wire it is protecting.
Bonawitz et al. 2017's observation is that secure aggregation is just a
sum inside a finite ring, and the ring only needs to hold the AGGREGATE:
uniform-quantize each update into a small field, mask it there, and the
wire carries one small residue per parameter instead of a stack of
int64 slots.

Two composed ideas:

- **Small field.** With the two-phase weight exchange (each client
  shares ``quantize(w_c * update)``, ``sum w_c <= 1``) the aggregate is
  the weighted MEAN, so ``|sum_c v_c| < B * 2^frac_bits`` for a value
  bound B independent of cohort size — a 16-bit prime
  (``mpc.FIELD_PRIMES[16] = 65521``) holds it with room to spare.
  Individual residues may wrap (quantization is mod p); only the
  aggregate needs headroom, and ``check_headroom`` verifies it at
  STARTUP against the configured field/frac_bits/cohort.
- **Seed-expanded masks.** Additive sharing splits ``q`` into
  ``n_shares`` slots of which ``n_shares - 1`` are pure randomness.
  Those slots are shipped as 64-bit PRG SEEDS; only the data slot
  ``q - sum(masks) mod p`` rides the wire as field elements. The server
  re-expands the seeds and folds every slot SLOT-MAJOR into int64
  accumulators — the same privacy invariant as the dense protocol (no
  server-side intermediate equals a client's quantized update; the
  ``trace`` hook lets tests assert it) under the same trust model as
  the single-aggregator degenerate mode (the server holds everything
  needed to unmask ONE client and is trusted not to — exactly as it is
  trusted not to combine one client's slots in the dense protocol).

Wire cost: ``wire_dtype_for(p)`` bytes per parameter + 8 bytes per
extra share — ~2 B/param at the default 16-bit field vs ~24 B/param
for the dense secure protocol at ``n_shares = 3`` (measured for real in
scripts/run_secure_bench.sh -> bench_matrix/secure_bench.json).

Exactness contract (the parity pin, tests/test_privacy.py): the folded,
dequantized aggregate equals ``quantized_weighted_mean`` — the plain
quantized ``tree_weighted_mean`` over the same survivor set — BITWISE,
and equals the jitted device program (ops/mpc_device.py
``secure_sum_device`` at this p/frac_bits) bitwise too, because host
(``mpc.quantize32``) and device (``quantize_device``) use the identical
float32 embedding and the mask material cancels exactly in the field.

Dropout (Bonawitz semantics, inherited from PR 2): a client's frame
folds whole or not at all — there is no partial fold — and a phase-B
dropout leaves the survivors' weight mass W < 1, which the server
repairs by rescaling 1/W after dequantize (survivor re-weighting).

Host numpy only (the OS-process federation runs deviceless); the jitted
counterpart for simulated engines is the existing
``ops/mpc_device.secure_aggregate_tree`` parameterized with this spec's
``(p, frac_bits)``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import numpy as np

from neuroimagedisttraining_tpu.codec.wire import SECURE_QUANT_KEY
from neuroimagedisttraining_tpu.ops import mpc

PyTree = Any

SQ_VERSION = 1

#: aggregate-magnitude bound the startup headroom check assumes: the
#: weighted mean of model updates (weights summing to <= 1) stays below
#: this per coordinate. 3D-CNN params here live in [-1, 1]; 16 leaves a
#: 16x margin (and fits the default 16-bit field at frac_bits 10 with
#: 2x to spare), and a violation is a defined sign-preserving
#: saturation (quantize32's field-edge clamp), never silent wraparound
#: garbage.
VALUE_BOUND = 16.0

#: fixed-point bits for integer-scaled aggregation weights (the async
#: one-phase path, where weights are staleness-discounted floats): a
#: weight is folded as round(w * 2^WEIGHT_FRAC_BITS) inside the field
WEIGHT_FRAC_BITS = 6


@dataclasses.dataclass(frozen=True)
class QuantSpec:
    """Field + fixed-point geometry of one secure-quant deployment.
    Hashable (jit-static); both endpoints must agree — frames carry the
    triple and the server validates it on every fold."""

    p: int = mpc.FIELD_PRIMES[16]
    frac_bits: int = 10
    n_shares: int = 3

    @staticmethod
    def from_bits(field_bits: int, frac_bits: int = 10,
                  n_shares: int = 3) -> "QuantSpec":
        if field_bits not in mpc.FIELD_PRIMES:
            raise ValueError(
                f"secure_quant_field_bits must be one of "
                f"{sorted(mpc.FIELD_PRIMES)} (got {field_bits})")
        return QuantSpec(p=mpc.FIELD_PRIMES[field_bits],
                         frac_bits=int(frac_bits),
                         n_shares=int(n_shares))

    @property
    def wire_dtype(self) -> np.dtype:
        return mpc.wire_dtype_for(self.p)


def check_headroom(spec: QuantSpec, cohort: int,
                   value_bound: float = VALUE_BOUND) -> None:
    """STARTUP validation of the field geometry (never mid-round):

    - the dequantized AGGREGATE must fit the centered field range
      (``value_bound * 2^frac_bits < p/2``) — individual residues may
      wrap, the sum may not;
    - the int64 slot accumulators must never overflow over the cohort
      (weighted folds scale by up to ``2^WEIGHT_FRAC_BITS * n_max``);
    - the device program's uint32 add-mod lattice needs ``p < 2^31``.
    """
    if spec.n_shares < 2:
        raise ValueError(
            f"secure_quant needs n_shares >= 2 (got {spec.n_shares}): one "
            "share is the plaintext")
    if not 1 < spec.p < 1 << 31:
        raise ValueError(f"field modulus {spec.p} outside (1, 2^31)")
    if spec.frac_bits < 1:
        raise ValueError(f"frac_bits must be >= 1, got {spec.frac_bits}")
    agg_range = value_bound * (1 << spec.frac_bits)
    if agg_range >= spec.p // 2:
        raise ValueError(
            f"secure_quant headroom exceeded: aggregate range "
            f"value_bound * 2^frac_bits = {agg_range:.0f} must stay below "
            f"p/2 = {spec.p // 2} — lower secure_quant_frac_bits or raise "
            f"secure_quant_field_bits (p={spec.p}, "
            f"frac_bits={spec.frac_bits})")
    if cohort > 0 and cohort * (spec.p - 1) >= 1 << 62:
        raise ValueError(
            f"slot accumulator headroom exceeded: cohort {cohort} x "
            f"(p-1) overflows int64")


# ---------------------------------------------------------------------------
# frames
# ---------------------------------------------------------------------------

def _named_leaves(tree: PyTree) -> list[tuple[str, np.ndarray]]:
    from neuroimagedisttraining_tpu.codec.wire import (
        _named_leaves as named,
    )

    return named(tree)


def _mask_slot(seed: int, sizes: list[tuple[str, int]],
               p: int) -> dict[str, np.ndarray]:
    """Expand one share seed into per-leaf uniform GF(p) material —
    identical on client and server (one sequential seeded stream per
    slot, walked in the frame's leaf order, which both ends derive from
    the same tree structure). The seed itself is the client's secret
    entropy; this expansion is a deterministic function of it."""
    rng = np.random.default_rng(np.uint64(seed))
    return {name: rng.integers(0, p, size=n, dtype=np.int64)
            for name, n in sizes}


def is_secure_quant_frame(obj: Any) -> bool:
    return isinstance(obj, dict) and SECURE_QUANT_KEY in obj


def leaf_scales(reference: PyTree,
                value_bound: float = VALUE_BOUND) -> dict[str, float]:
    """Per-leaf power-of-two scale factors derived from the round's
    broadcast ``reference`` — both endpoints hold the identical tree
    (the round-tag gate guarantees it), so both derive the identical
    scales with NOTHING extra on the wire. Values are quantized as
    ``x / scale`` and the aggregate multiplied back at finalize; powers
    of two make the float32 divide/multiply exact, so the bitwise
    parity pin survives scaling.

    Why: model PARAMS live well inside ``value_bound``, but BatchNorm
    running statistics track raw activation moments and can reach the
    hundreds — without scaling they'd saturate the 16-bit field's range
    (defined, sign-preserving, but a wrong aggregate). The scale gives
    each leaf ``2 * max(|ref|, 1)`` of headroom: updates are residuals
    of the reference, so a leaf would have to quadruple in one round to
    reach the saturation edge."""
    out = {}
    for name, leaf in _named_leaves(reference):
        m = float(np.max(np.abs(np.asarray(leaf, np.float32))))  \
            if np.asarray(leaf).size else 0.0
        need = 2.0 * max(m, 1.0)
        out[name] = float(2.0 ** math.ceil(math.log2(need / value_bound))) \
            if need > value_bound else 1.0
    return out


def encode_secure_quant(update: PyTree, weight: float, spec: QuantSpec,
                        rng: np.random.Generator,
                        scales: dict[str, float] | None = None) -> dict:
    """One client's field-element frame: quantize ``weight * update``
    into GF(p) (float32 embedding — ``mpc.quantize32``), draw
    ``n_shares - 1`` mask seeds from the client's OWN rng, and ship
    ``q - sum(masks) mod p`` as the data slot in the field's wire dtype
    plus the seeds. ``weight`` is the phase-A normalized FedAvg weight
    (two-phase sync protocol) or 1.0 (one-phase async protocol — the
    server folds integer-scaled weights instead). ``scales`` are the
    per-leaf ``leaf_scales`` both endpoints derive from the round's
    reference (None = unscaled)."""
    named = _named_leaves(update)
    sizes = [(name, int(np.asarray(x).size)) for name, x in named]
    seeds = rng.integers(0, np.iinfo(np.uint64).max, size=spec.n_shares - 1,
                         dtype=np.uint64)
    masked = {name: mpc.quantize32(
        np.float32(weight) * np.asarray(x, np.float32).reshape(-1)
        / np.float32(scales[name] if scales else 1.0),
        p=spec.p, frac_bits=spec.frac_bits) for name, x in named}
    for seed in seeds:
        mat = _mask_slot(int(seed), sizes, spec.p)
        masked = {name: np.mod(masked[name] - mat[name], spec.p)
                  for name, _ in sizes}
    leaves = {}
    for name, x in named:
        arr = np.asarray(x)
        leaves[name] = {"sh": list(arr.shape), "dt": str(arr.dtype),
                        "v": masked[name].astype(spec.wire_dtype)}
    return {SECURE_QUANT_KEY: SQ_VERSION, "p": int(spec.p),
            "fb": int(spec.frac_bits), "k": int(spec.n_shares),
            "seeds": seeds, "leaves": leaves}


def _validate_frame(frame: dict, spec: QuantSpec) -> None:
    if not is_secure_quant_frame(frame):
        raise ValueError(
            "expected a secure-quant field-element frame; got a "
            f"{type(frame).__name__} without the frame magic — the sender "
            "is not running --secure_quant (config skew)")
    ver = int(frame[SECURE_QUANT_KEY])
    if ver != SQ_VERSION:
        raise ValueError(f"secure-quant frame version {ver} != supported "
                         f"{SQ_VERSION}")
    got = (int(frame["p"]), int(frame["fb"]), int(frame["k"]))
    want = (spec.p, spec.frac_bits, spec.n_shares)
    if got != want:
        raise ValueError(
            f"secure-quant spec mismatch: frame carries (p, frac_bits, "
            f"n_shares) = {got}, server configured {want} — every rank "
            "must share one --secure_quant_field_bits / "
            "--secure_quant_frac_bits / --mpc_n_shares configuration")
    n_seeds = int(np.asarray(frame["seeds"]).size)
    if n_seeds != spec.n_shares - 1:
        raise ValueError(
            f"secure-quant frame carries {n_seeds} mask seeds, expected "
            f"n_shares - 1 = {spec.n_shares - 1}")


# ---------------------------------------------------------------------------
# server-side fold
# ---------------------------------------------------------------------------

class SlotAccumulator:
    """Slot-major GF(p) accumulation over arriving frames — the secure
    server's only model-sized state. Slot j of every client folds into
    accumulator j; accumulators combine only in ``finalize`` (the
    privacy invariant the dense protocol pins: no stored intermediate
    equals any client's quantized update). ``trace`` (tests-only)
    records every post-fold accumulator state."""

    def __init__(self, spec: QuantSpec, trace: list | None = None,
                 like: PyTree | None = None):
        self.spec = spec
        self.trace = trace
        self._slots: list[dict[str, np.ndarray]] | None = None
        #: expected (leaf name, flat size) structure: from ``like`` when
        #: the caller owns a template (the server's params), else locked
        #: to the first folded frame — every later frame must match
        #: BEFORE any accumulator mutation (fold atomicity)
        self._sizes: list[tuple[str, int]] | None = None
        if like is not None:
            self._sizes = [(name, int(np.asarray(x).size))
                           for name, x in _named_leaves(like)]
        self.folded = 0

    @staticmethod
    def _frame_sizes(frame: dict) -> list[tuple[str, int]]:
        return [(name, int(np.prod(rec["sh"])) if rec["sh"] else 1)
                for name, rec in frame["leaves"].items()]

    def _expand(self, frame: dict) -> list[dict[str, np.ndarray]]:
        sizes = self._frame_sizes(frame)
        slots = [_mask_slot(int(s), sizes, self.spec.p)
                 for s in np.asarray(frame["seeds"]).tolist()]
        slots.append({name: np.asarray(rec["v"], np.int64)
                      for name, rec in frame["leaves"].items()})
        return slots

    def fold(self, frame: dict, weight_int: int = 1) -> None:
        """Fold one client's frame WHOLE or not at all (the Bonawitz
        discard contract): the frame's leaf structure is validated
        against the template/first frame BEFORE any accumulator
        mutation, so a structurally skewed frame raises with the
        accumulators untouched. ``weight_int`` scales every slot inside
        the field — 1 for the two-phase protocol (weights were applied
        client-side), the integer-scaled staleness weight for the async
        one-phase path."""
        _validate_frame(frame, self.spec)
        w = int(weight_int)
        if w < 1:
            raise ValueError(f"weight_int must be >= 1, got {w}")
        sizes = self._frame_sizes(frame)
        if self._sizes is None:
            self._sizes = sizes
        elif sizes != self._sizes:
            raise ValueError(
                "secure-quant frame leaf structure mismatch: frame "
                f"carries {sizes[:3]}... vs expected {self._sizes[:3]}"
                "... — sender and receiver model trees differ (version "
                "skew); frame discarded whole")
        slots = self._expand(frame)
        if self._slots is None:
            self._slots = [
                {name: (w * v) % self.spec.p for name, v in s.items()}
                for s in slots]
        else:
            for acc, s in zip(self._slots, slots):
                for name, v in s.items():
                    # w * v < 2^? : w <= 2^WEIGHT_FRAC_BITS * n_max and
                    # v < p < 2^31; check_headroom bounds the product
                    acc[name] = (acc[name] + w * v) % self.spec.p
        self.folded += 1
        if self.trace is not None:
            self.trace.extend(np.concatenate(
                [a.reshape(-1) for a in s.values()]).copy()
                for s in self._slots)

    def merge(self, other: "SlotAccumulator") -> None:
        """Fold another accumulator INTO this one slot-wise:
        ``slot_j := (slot_j + other.slot_j) mod p``. The GF(p) residue
        algebra is commutative and associative, so merging per-worker
        accumulators in ANY order equals folding every frame into one
        accumulator — the cross-process invariant the sharded ingest
        plane (asyncfl/ingest.py) is built on. Both accumulators must
        share the spec and leaf structure; ``other`` is left untouched."""
        if other.spec != self.spec:
            raise ValueError(
                f"cannot merge SlotAccumulators with different specs: "
                f"{other.spec} vs {self.spec}")
        if other._slots is None:
            return
        if self._slots is None:
            if self._sizes is not None and other._sizes != self._sizes:
                raise ValueError(
                    "secure-quant accumulator merge: leaf structure "
                    f"mismatch ({other._sizes[:3]}... vs "
                    f"{self._sizes[:3]}...)")
            self._sizes = other._sizes
            self._slots = [{name: v.copy() for name, v in s.items()}
                           for s in other._slots]
        else:
            if other._sizes != self._sizes:
                raise ValueError(
                    "secure-quant accumulator merge: leaf structure "
                    f"mismatch ({other._sizes[:3]}... vs "
                    f"{self._sizes[:3]}...)")
            for acc, s in zip(self._slots, other._slots):
                for name, v in s.items():
                    acc[name] = (acc[name] + v) % self.spec.p
        self.folded += other.folded
        if self.trace is not None:
            self.trace.extend(np.concatenate(
                [a.reshape(-1) for a in s.values()]).copy()
                for s in self._slots)

    def export_centered(self) -> dict[str, np.ndarray] | None:
        """Combine the slots and CENTER-LIFT the total into plain int64:
        ``t - p`` for residues above ``p//2``. When the accumulated
        weighted aggregate is inside the field's centered range (the
        caller's headroom contract — asyncfl/ingest.py flushes partials
        before the folded weight mass can leave it), the lifted value IS
        the true integer ``sum_c w_c * q~_c`` over this accumulator's
        frames, so lifted partials from different processes combine
        EXACTLY in ordinary int64 addition — no shared modulus needed
        across partials, which is what makes the cross-worker merge
        bitwise partition-independent. Returns None when nothing folded;
        does not reset the accumulator."""
        if self._slots is None:
            return None
        total = self._slots[0]
        for s in self._slots[1:]:
            total = {name: (total[name] + s[name]) % self.spec.p
                     for name in total}
        half = self.spec.p // 2
        return {name: np.where(t > half, t - self.spec.p, t)
                for name, t in total.items()}

    def finalize(self, like: PyTree, rescale: float = 1.0,
                 scales: dict[str, float] | None = None) -> PyTree:
        """Combine slots, dequantize (float32 centered lift — bitwise
        the device program's), undo the per-leaf ``leaf_scales``,
        rescale (1/W survivor re-weighting or 1/sum(w_int) for weighted
        folds), reshape like ``like``."""
        if self._slots is None:
            raise ValueError("finalize() before any frame folded")
        total = self._slots[0]
        for s in self._slots[1:]:
            total = {name: (total[name] + s[name]) % self.spec.p
                     for name in total}
        out = {}
        for name, t in total.items():
            deq = mpc.dequantize32(t, p=self.spec.p,
                                   frac_bits=self.spec.frac_bits)
            if scales:
                deq = deq * np.float32(scales[name])
            out[name] = np.asarray(rescale * deq, np.float64)
        self._slots = None
        self.folded = 0
        from neuroimagedisttraining_tpu.codec.wire import _rebuild_like

        named = _named_leaves(like)
        rebuilt = {}
        for name, x in named:
            arr = np.asarray(x)
            rebuilt[name] = out[name].reshape(arr.shape).astype(arr.dtype)
        return _rebuild_like(like, rebuilt)


# ---------------------------------------------------------------------------
# references + helpers
# ---------------------------------------------------------------------------

def quantized_weighted_mean(trees: list, weights, spec: QuantSpec,
                            rescale: float = 1.0,
                            scales: dict[str, float] | None = None
                            ) -> PyTree:
    """THE parity reference: the plain (mask-free) quantized weighted
    mean ``dequantize(sum_c quantize(w_c * u_c))`` over normalized
    weights, computed with the identical float32 embedding and the same
    per-leaf scales — what the secure fold must equal BITWISE on the
    same survivor set."""
    w = np.asarray(weights, np.float64)
    wn = w / max(float(np.sum(w)), 1e-12)
    acc: dict[str, np.ndarray] | None = None
    for tree, wc in zip(trees, wn):
        named = _named_leaves(tree)
        q = {name: mpc.quantize32(
            np.float32(wc) * np.asarray(x, np.float32).reshape(-1)
            / np.float32(scales[name] if scales else 1.0),
            p=spec.p, frac_bits=spec.frac_bits) for name, x in named}
        acc = q if acc is None else {
            name: (acc[name] + q[name]) % spec.p for name in acc}
    from neuroimagedisttraining_tpu.codec.wire import _rebuild_like

    named = _named_leaves(trees[0])
    out = {}
    for name, x in named:
        arr = np.asarray(x)
        deq = mpc.dequantize32(acc[name] % spec.p, p=spec.p,
                               frac_bits=spec.frac_bits)
        if scales:
            deq = deq * np.float32(scales[name])
        out[name] = np.asarray(rescale * deq, np.float64).reshape(
            arr.shape).astype(arr.dtype)
    return _rebuild_like(trees[0], out)


def weighted_fold_capacity(spec: QuantSpec,
                           value_bound: float = VALUE_BOUND) -> float:
    """Total integer weight mass one aggregation can fold before the
    weighted aggregate leaves the field's centered range — the
    feasibility bound the async server checks at STARTUP against its
    buffer size (a 16-bit field folds ~2 weight units; the one-phase
    buffered path effectively needs field_bits 32)."""
    return (spec.p // 2) / (value_bound * (1 << spec.frac_bits))


def integer_weights(weights, spec: QuantSpec,
                    value_bound: float = VALUE_BOUND
                    ) -> tuple[np.ndarray, float]:
    """Integer-scaled fold weights for the one-phase (async) path.
    Only weight RATIOS matter (the dequantized total is divided by the
    integer mass), so weights are normalized by their max and scaled by
    the largest ``2^s, s <= WEIGHT_FRAC_BITS`` whose total stays inside
    ``weighted_fold_capacity`` — the staleness ratios are preserved to
    ~2^-s relative precision. Deterministic in the weights, so a replay
    reproduces the aggregation bitwise. Returns ``(w_int[C], denom)``
    with the weighted mean = dequantized total / denom."""
    from neuroimagedisttraining_tpu.privacy.accountant import (
        validate_weights,
    )

    w = validate_weights(weights)
    wn = w / float(np.max(w))
    limit = weighted_fold_capacity(spec, value_bound)
    for s in range(WEIGHT_FRAC_BITS, -1, -1):
        wi = np.maximum(np.rint(wn * (1 << s)).astype(np.int64), 1)
        # an accepted upload never folds at 0 ^ (it was admitted)
        if float(np.sum(wi)) < limit:
            return wi, float(np.sum(wi))
    raise ValueError(
        f"secure_quant weighted-fold headroom exhausted: {w.size} "
        f"buffered uploads cannot fold inside p={spec.p} at "
        f"frac_bits={spec.frac_bits} (capacity {limit:.1f} weight "
        "units) — use --secure_quant_field_bits 32 for the buffered "
        "one-phase path, or shrink --buffer_k")


def frame_nbytes(frame: dict) -> int:
    from neuroimagedisttraining_tpu.codec import wire as codec_wire

    return codec_wire.frame_nbytes(frame)
