"""RDP (moments) accountant for the DP noise paths.

Abadi et al. 2016 introduced the moments accountant: track the privacy
loss of a composed mechanism through its Renyi-divergence moments
instead of naive (epsilon, delta) composition; Mironov 2017 recast the
same bookkeeping as Renyi Differential Privacy — an additive accountant
over a grid of orders alpha, converted to (epsilon, delta) once at
report time. This module is that accountant for the two noise paths the
repo actually ships:

- the ``weak_dp`` server/engine defense (clip to ``norm_bound``, add
  per-client Gaussian noise ``stddev`` — core/robust.py), and
- the ``dpsgd`` engine's round-level clip+noise on each client's local
  update (``--dp_clip`` / ``--dp_sigma``).

Math (all pure numpy/stdlib — no jax, no scipy; the accountant runs on
the host control plane and must work in the deviceless OS-process
federation):

- Subsampled Gaussian mechanism, integer orders alpha >= 2
  (Mironov et al. 2019, the standard integer-order expansion the
  moments accountant evaluates):
    RDP(alpha) = 1/(alpha-1) * log( sum_{k=0..alpha} C(alpha,k)
                 (1-q)^(alpha-k) q^k exp((k^2-k)/(2 sigma^2)) )
  evaluated in log space (logsumexp + lgamma) so sigma < 1 and
  alpha ~ 512 stay finite.
- q = 1 (full participation) collapses to the Gaussian mechanism's
  closed form RDP(alpha) = alpha / (2 sigma^2) — the single-round
  reference the tests pin the expansion against.
- Composition is ADDITIVE in RDP: T rounds cost T * RDP(alpha).
- Conversion (Mironov 2017, Prop. 3):
    epsilon(delta) = min over alpha of RDP(alpha) + log(1/delta)/(alpha-1).

Noise-multiplier normalization: RDP formulas are stated for noise
sigma * sensitivity. ``weak_dp`` adds ABSOLUTE noise ``stddev`` to each
client's update clipped to ``norm_bound`` and then takes a weighted
mean, so the effective multiplier depends on the weights —
``weak_dp_noise_multiplier`` computes it exactly:
noise on the weighted mean has std ``stddev * sqrt(sum w^2) / W`` while
one client's clipped contribution moves it by at most
``norm_bound * max(w) / W``, giving
z = stddev * sqrt(sum w^2) / (norm_bound * max(w)).
(Uniform weights: z = stddev * sqrt(C) / norm_bound.)
"""

from __future__ import annotations

import math

import numpy as np

#: default Renyi order grid: dense integers where the minimum usually
#: lands, sparse large orders for very small epsilon regimes
DEFAULT_ORDERS: tuple[int, ...] = tuple(range(2, 65)) + (
    80, 96, 128, 192, 256, 384, 512)


def _log_binom(n: int, k: int) -> float:
    return (math.lgamma(n + 1) - math.lgamma(k + 1)
            - math.lgamma(n - k + 1))


def _logsumexp(vals: np.ndarray) -> float:
    m = float(np.max(vals))
    if not math.isfinite(m):
        return m
    return m + math.log(float(np.sum(np.exp(vals - m))))


def rdp_gaussian(q: float, noise_multiplier: float,
                 orders=DEFAULT_ORDERS) -> np.ndarray:
    """Per-step RDP of the subsampled Gaussian mechanism at every order.

    ``q``: sampling rate in [0, 1]; ``noise_multiplier``: noise sigma in
    units of the mechanism's sensitivity. Orders must be integers >= 2
    (the grid is validated — a float order would silently evaluate the
    integer expansion at the wrong alpha).
    """
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"sampling rate q must be in [0, 1], got {q}")
    if not (math.isfinite(noise_multiplier) and noise_multiplier > 0):
        # NaN fails the > comparison too: a poisoned multiplier must
        # raise here, never surface as "epsilon": NaN in a privacy audit
        raise ValueError(
            f"noise_multiplier must be finite and > 0, got "
            f"{noise_multiplier} (sigma == 0 is not a DP mechanism — "
            "epsilon is infinite)")
    orders = np.asarray(orders)
    if not np.all(orders == orders.astype(int)) or np.any(orders < 2):
        raise ValueError(f"orders must be integers >= 2, got {orders}")
    s2 = float(noise_multiplier) ** 2
    if q == 0.0:
        return np.zeros(len(orders), np.float64)
    if q == 1.0:
        # Gaussian mechanism closed form — also the tests' single-round pin
        return orders.astype(np.float64) / (2.0 * s2)
    out = np.empty(len(orders), np.float64)
    logq, log1q = math.log(q), math.log1p(-q)
    for i, a in enumerate(int(a) for a in orders):
        terms = np.asarray([
            _log_binom(a, k) + k * logq + (a - k) * log1q
            + (k * k - k) / (2.0 * s2)
            for k in range(a + 1)])
        out[i] = _logsumexp(terms) / (a - 1)
    return out


def rdp_to_epsilon(rdp: np.ndarray, orders=DEFAULT_ORDERS,
                   delta: float = 1e-5) -> tuple[float, int]:
    """(epsilon, best_order): the tightest (epsilon, delta) the RDP curve
    certifies (Mironov 2017 Prop. 3)."""
    if not 0.0 < delta < 1.0:
        raise ValueError(f"delta must be in (0, 1), got {delta}")
    orders = np.asarray(orders, np.float64)
    eps = np.asarray(rdp, np.float64) + math.log(1.0 / delta) / (orders - 1)
    i = int(np.argmin(eps))
    return float(eps[i]), int(orders[i])


def validate_weights(weights) -> np.ndarray:
    """THE aggregation-weight validator the privacy plane shares (the
    epsilon report and the field-fold weights both ride on it): finite,
    non-negative, max > 0 — a NaN weight fails every comparison
    silently, so it must raise here, not skew arithmetic downstream."""
    w = np.asarray(weights, np.float64)
    if w.size == 0 or not np.all(np.isfinite(w)) or np.any(w < 0) \
            or float(np.max(w)) <= 0:
        raise ValueError(
            f"weights must be finite, non-negative, with max > 0: {w}")
    return w


def weak_dp_noise_multiplier(stddev: float, norm_bound: float,
                             weights) -> float:
    """Effective noise multiplier of one weak_dp round (see module
    docstring): per-client absolute noise ``stddev`` on updates clipped
    to ``norm_bound``, combined by the weighted mean with ``weights``."""
    if norm_bound <= 0 or stddev <= 0:
        raise ValueError(
            f"weak_dp accounting needs norm_bound > 0 and stddev > 0 "
            f"(got norm_bound={norm_bound}, stddev={stddev})")
    w = validate_weights(weights)
    return float(stddev * math.sqrt(float(np.sum(w * w)))
                 / (norm_bound * float(np.max(w))))


class RDPAccountant:
    """Additive RDP ledger over a fixed order grid.

    ``step(q, noise_multiplier, steps)`` adds the RDP of ``steps``
    subsampled-Gaussian rounds (heterogeneous rounds compose by calling
    it again with different parameters); ``epsilon()`` converts the
    running total to the tightest (epsilon, delta). Pure host numpy —
    safe to call from control-plane threads, never inside a trace.
    """

    def __init__(self, delta: float = 1e-5, orders=DEFAULT_ORDERS):
        if not 0.0 < delta < 1.0:
            raise ValueError(f"delta must be in (0, 1), got {delta}")
        self.delta = float(delta)
        self.orders = tuple(int(a) for a in orders)
        self._rdp = np.zeros(len(self.orders), np.float64)
        self.steps = 0

    def step(self, q: float, noise_multiplier: float,
             steps: int = 1) -> None:
        if steps < 0:
            raise ValueError(f"steps must be >= 0, got {steps}")
        if steps:
            self._rdp = self._rdp + steps * rdp_gaussian(
                q, noise_multiplier, self.orders)
            self.steps += int(steps)

    def epsilon(self) -> float:
        if self.steps == 0:
            return 0.0
        return rdp_to_epsilon(self._rdp, self.orders, self.delta)[0]

    def spent(self) -> dict:
        """JSON-able report for stat_info / the run-end audit."""
        return {"epsilon": self.epsilon(), "delta": self.delta,
                "steps": self.steps}
