"""On-device (XLA) secure aggregation: the TurboAggregate MPC stage as a
jittable program.

The host numpy path (ops/mpc.py::secure_sum — parity with
fedml_api/standalone/turboaggregate/mpc_function.py:214-224) costs a full
FedAvg round of wall time per round on one CPU core, fully serialized
between the train stages (VERDICT r4 weak #3). The quantize / share /
slot-accumulate pipeline is elementwise adds and reductions over GF(p), so
it lowers cleanly onto the TPU's VPU — no host round-trip, fused into the
round program.

Field arithmetic without int64 (TPU jax runs x64-disabled): with
p = 2^31 - 1 every residue is < 2^31, so the SUM of two residues is
< 2^32 - 2 and uint32 addition never wraps before the ``% p`` that follows
each add. Products never occur (additive shares need only addition), so no
wider type is required.

Masking material comes from ``jax.random`` uniform draws in [0, p); the
share randomness cancels exactly in the slot sum (additive shares by
construction), so the aggregate is independent of the key — the key only
decorrelates the masking material across rounds, mirroring the host path's
``call_idx`` seeding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from neuroimagedisttraining_tpu.ops.mpc import P_DEFAULT


def quantize_device(x: jax.Array, p: int = P_DEFAULT,
                    frac_bits: int = 16) -> jax.Array:
    """round(x * 2^frac_bits) mod p as uint32 residues (the host
    quantize's embedding, ops/mpc.py:220-224). Exact for
    |x| * 2^frac_bits < p/2 — scaled magnitudes also stay well inside
    float32's 2^24 exact-integer range for every update this framework
    ships (unit-ish weighted deltas).

    Range guard (ADVICE r5): scaled magnitudes are CLAMPED to the
    largest float32 BELOW p/2 before the int32 cast. Without it, a float
    beyond int32 range saturates to 2^31-1 == p in XLA's cast — an
    out-of-field "residue" the host path (int64 mod p) never produces,
    so device and host would silently diverge; and a naive float32(p//2)
    limit ROUNDS UP past p/2, flipping the sign of positive overflows at
    dequantize. Saturating at the fixed-point range edge is a defined,
    sign-preserving overflow; values that large are already outside the
    |x|*2^frac_bits < p/2 exactness contract either way."""
    import numpy as _np  # static limit math only, never on traced values

    lim = _np.float32((p - 1) // 2)
    if int(lim) > (p - 1) // 2:  # float32 rounded UP past the field edge
        lim = _np.nextafter(lim, _np.float32(0.0))
    scaled = jnp.rint(x.astype(jnp.float32) * (1 << frac_bits))
    # NaN would survive the clip and hit the int cast as an undefined
    # conversion — map it to the zero residue (neutral contribution),
    # bitwise-matching the host quantize32's NaN rule
    scaled = jnp.where(jnp.isnan(scaled), jnp.float32(0.0), scaled)
    v = jnp.clip(scaled, -lim, lim).astype(jnp.int32)
    return jnp.where(v < 0, v + p, v).astype(jnp.uint32)


def dequantize_device(q: jax.Array, p: int = P_DEFAULT,
                      frac_bits: int = 16) -> jax.Array:
    """Centered lift then /2^frac_bits (host dequantize,
    ops/mpc.py:227-230)."""
    qi = q.astype(jnp.int32)  # residues < p = 2^31 - 1 fit int32 exactly
    centered = jnp.where(q > p // 2, qi - p, qi)
    return centered.astype(jnp.float32) / (1 << frac_bits)


def _addmod(a: jax.Array, b: jax.Array, p: int) -> jax.Array:
    s = a + b  # both < p < 2^31 -> s < 2^32 - 2, no uint32 wrap
    return jnp.where(s >= p, s - p, s)


def secure_sum_device(stack: jax.Array, key: jax.Array, n_shares: int,
                      frac_bits: int = 16, p: int = P_DEFAULT,
                      return_slots: bool = False):
    """Secure aggregation of a client-stacked float array ``stack[S, ...]``
    on device: quantize each client's update into GF(p), split into
    ``n_shares`` additive shares, accumulate SLOT-MAJOR (share slot j sums
    across ALL clients before any two slots combine — ops/mpc.py
    secure_sum's privacy invariant), then combine slots and dequantize.

    With ``return_slots`` the per-slot totals (the only server-visible
    intermediates) are also returned so tests can assert they are
    uniformly-random masked material, not any client's plaintext.

    All three reductions (masking-row sum, per-slot client sum, cross-
    slot total) run as ``lax.fori_loop`` so the trace is O(1) in clients
    and shares instead of the O(S x n_shares x leaves) unrolled program
    ADVICE r5 flagged — same ascending accumulation order, so the output
    is bitwise-equal to the unrolled path (pinned in tests/test_mpc.py).
    """
    if n_shares < 2:
        raise ValueError(
            f"secure_sum_device needs n_shares >= 2 ({n_shares} given): "
            "with a single share there is no masking material and the "
            "'secure' aggregation would be the plaintext sum")
    if not 1 < p < 1 << 31:
        # the whole pipeline rides uint32 residues whose pairwise sums
        # must not wrap before the % p that follows each add; this also
        # admits the SMALL fields of the secure-quantized path
        # (privacy/secure_quant.py ships uint16 frames over
        # p = FIELD_PRIMES[16] and aggregates through this same program)
        raise ValueError(f"field modulus p must be in (1, 2^31), got {p}")
    S = stack.shape[0]
    pp = jnp.uint32(p)
    q = quantize_device(stack, p=p, frac_bits=frac_bits)       # [S, ...]
    # masking material: n_shares-1 uniform draws per client element; the
    # final share is determined (q - sum of the others)
    r = jax.random.randint(key, (n_shares - 1,) + q.shape, 0, p,
                           dtype=jnp.int32).astype(jnp.uint32)
    rsum = jax.lax.fori_loop(1, n_shares - 1,
                             lambda j, acc: _addmod(acc, r[j], pp), r[0])
    last = _addmod(q, pp - rsum, pp)                           # q - rsum
    shares = jnp.concatenate([r, last[None]])      # [n_shares, S, ...]
    # slot-major accumulation over the client axis, ascending client
    # order per slot — every slot advances one client per iteration, so
    # no two slots combine before each has folded all S clients
    slots = jax.lax.fori_loop(
        1, S, lambda c, acc: _addmod(acc, shares[:, c], pp), shares[:, 0])
    total = jax.lax.fori_loop(
        1, n_shares, lambda j, acc: _addmod(acc, slots[j], pp), slots[0])
    out = dequantize_device(total, p=p, frac_bits=frac_bits)
    if return_slots:
        return out, slots
    return out


def secure_aggregate_tree(weighted_stacked, key: jax.Array, n_shares: int,
                          frac_bits: int = 16, p: int = P_DEFAULT):
    """``secure_sum_device`` over every leaf of a client-stacked pytree,
    one fresh key per leaf — the jittable counterpart of
    TurboAggregateEngine's host MPC boundary."""
    leaves, treedef = jax.tree.flatten(weighted_stacked)
    keys = jax.random.split(key, len(leaves))
    out = [secure_sum_device(leaf, k, n_shares, frac_bits=frac_bits, p=p)
           for leaf, k in zip(leaves, keys)]
    return jax.tree.unflatten(treedef, out)
