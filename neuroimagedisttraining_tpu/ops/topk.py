"""Global top-k threshold selection over flattened saliency vectors.

Replaces the reference's ``torch.topk(all_scores, k)[..., -1]`` global
threshold (snip.py:91-98) — which materializes a full sorted copy of the
~61M-element AlexNet3D score vector — with a multi-round histogram-select:
each round counts ``x >= t`` for a ladder of thresholds and narrows the
bracket containing the k-th largest value. With 4 rounds x 512 bins the
bracket shrinks by 512^4 ≈ 7e10 > 2^32, i.e. to float32 resolution: the
returned threshold is the exact k-th largest float.

The counting pass is the hot part and runs as a Pallas TPU kernel
(`_count_ge_pallas`): the score vector streams HBM->VMEM in [rows, 128]
blocks; each block compares against the threshold ladder in 128-wide chunks
on the VPU and accumulates partial counts into a VMEM accumulator mapped to
the same output block across the whole grid. Non-TPU backends (tests) use an
XLA fallback with identical semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_BLOCK_ROWS = 256          # x block = [256, 128] floats = 128 KiB VMEM
_LANES = 128
_BIN_CHUNK = 128


def _count_ge_kernel(x_ref, thr_ref, out_ref):
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:]                      # [R, 128]
    nbins = out_ref.shape[1]

    def body(j, _):
        sl = pl.dslice(j * _BIN_CHUNK, _BIN_CHUNK)
        thr_chunk = thr_ref[0, sl]                               # [C]
        cmp = x[:, :, None] >= thr_chunk[None, None, :]          # [R,128,C]
        partial = jnp.sum(cmp.astype(jnp.float32), axis=(0, 1))  # [C]  # nidt: allow[precision-upcast] -- histogram COUNTS accumulate in f32 on the VPU (exactness of the bracket, not an activation)
        out_ref[0, sl] = out_ref[0, sl] + partial
        return 0

    jax.lax.fori_loop(0, nbins // _BIN_CHUNK, body, 0)


def _count_ge_pallas(x2d: jax.Array, thresholds: jax.Array) -> jax.Array:
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = x2d.shape[0]
    nbins = thresholds.shape[0]
    grid = rows // _BLOCK_ROWS
    out = pl.pallas_call(
        _count_ge_kernel,
        out_shape=jax.ShapeDtypeStruct((1, nbins), jnp.float32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((1, nbins), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nbins), lambda i: (0, 0)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(x2d, thresholds[None, :])
    return out[0]


def _count_ge_xla(x2d: jax.Array, thresholds: jax.Array) -> jax.Array:
    def chunk_counts(thr_chunk):
        return jnp.sum((x2d[None, :, :] >= thr_chunk[:, None, None])
                       .astype(jnp.float32), axis=(1, 2))  # nidt: allow[precision-upcast] -- histogram counts in f32, XLA fallback mirrors the kernel bitwise

    chunks = thresholds.reshape(-1, _BIN_CHUNK // 2)
    return jax.lax.map(chunk_counts, chunks).reshape(-1)


def _pad_to_blocks(x: jax.Array) -> jax.Array:
    n = x.shape[0]
    per_block = _BLOCK_ROWS * _LANES
    padded = ((n + per_block - 1) // per_block) * per_block
    fill = jnp.finfo(jnp.float32).min
    return jnp.concatenate(
        [x.astype(jnp.float32),  # nidt: allow[precision-upcast] -- saliency scores compare in exact f32: the k-th-largest bracket is defined on the f32 value lattice
         jnp.full((padded - n,), fill, jnp.float32)]).reshape(-1, _LANES)


@functools.partial(jax.jit, static_argnames=("k", "rounds", "nbins",
                                             "use_pallas"))
def kth_largest(x: jax.Array, k: int, rounds: int = 4, nbins: int = 512,
                use_pallas: bool | None = None) -> jax.Array:
    """Exact (to float32 resolution) k-th largest value of a 1-D vector.

    A mask ``x >= kth_largest(x, k)`` keeps >= k entries (ties included) —
    the same semantics as the reference's ``>= acceptable_score``
    (snip.py:96-98).

    Non-finite contract: the histogram bracket assumes every comparison
    ``x >= t`` is meaningful; a single NaN (or a +/-inf min/max bracket)
    would otherwise silently converge to a garbage threshold — worse than
    the reference, whose ``torch.topk`` would at least surface the NaN in
    the returned value. So non-finite input yields a NaN threshold
    (which poisons any ``>=`` mask to all-False *visibly*, and which
    eager callers — ops/snip.py:mask_from_scores — turn into a raised
    error before any mask is built).
    """
    assert x.ndim == 1
    assert nbins % _BIN_CHUNK == 0, (
        f"nbins ({nbins}) must be a multiple of {_BIN_CHUNK}: the Pallas "
        "kernel floor-divides the bin ladder into chunks and would silently "
        "drop remainder bins")
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    count_ge = _count_ge_pallas if use_pallas else _count_ge_xla
    x2d = _pad_to_blocks(x)
    lo = jnp.min(x).astype(jnp.float32)  # nidt: allow[precision-upcast] -- f32 bracket endpoints: the threshold IS an f32 value by contract
    hi = jnp.max(x).astype(jnp.float32)  # nidt: allow[precision-upcast] -- f32 bracket endpoints: the threshold IS an f32 value by contract

    def round_fn(carry, _):
        lo, hi = carry
        thr = jnp.linspace(lo, hi, nbins)
        counts = count_ge(x2d, thr)
        # counts is non-increasing in the threshold, except for sub-float32
        # linspace wiggle in the final rounds — so take the longest TRUE
        # prefix of (count >= k), not the total count of TRUEs.
        prefix = jnp.cumprod((counts >= k).astype(jnp.int32))
        j = jnp.maximum(jnp.sum(prefix) - 1, 0)
        new_lo = thr[j]
        new_hi = jnp.where(j + 1 < nbins, thr[jnp.minimum(j + 1, nbins - 1)],
                           hi)
        return (new_lo, new_hi), None

    (lo, hi), _ = jax.lax.scan(round_fn, (lo, hi), None, length=rounds)
    ok = jnp.all(jnp.isfinite(x))
    return jnp.where(ok, lo, jnp.float32(jnp.nan))  # nidt: allow[precision-upcast] -- the NaN-poison sentinel is an f32 threshold by contract


def topk_threshold_mask(x: jax.Array, k: int, **kw) -> tuple[jax.Array, jax.Array]:
    thr = kth_largest(x, k, **kw)
    return (x >= thr), thr
