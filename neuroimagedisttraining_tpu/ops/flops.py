"""Analytic FLOPs / communication-volume accounting.

Replaces the reference's module-hook FLOPs census
(fedml_api/utils/main_flops_counter.py:30-80) with a shape-based analytic
pass over the model's captured intermediates: for fixed shapes this is exact
and free (one ``jax.eval_shape``). Supports the reference's two modes —
dense, and sparsity-aware where each conv/dense layer's MACs are scaled by
its mask density (main_flops_counter counts nonzero weights). Training FLOPs
= 3x inference (forward + ~2x backward), the reference's convention
(model_trainer.py:39-47 via count_training_flops_per_sample).

Communication volume = nonzero parameter count of the update pytree
(model_trainer.py:49-53).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.ops.masks import is_weight_kernel
from neuroimagedisttraining_tpu.utils.pytree import tree_map_with_path_names

PyTree = Any


def _collect_kernels(params: PyTree) -> dict[str, tuple[int, ...]]:
    shapes: dict[str, tuple[int, ...]] = {}

    def collect(name, leaf):
        if is_weight_kernel(name, leaf):
            shapes[name] = tuple(leaf.shape)
        return leaf

    tree_map_with_path_names(collect, params)
    return shapes


def count_inference_flops(model, params: PyTree, sample_x: jax.Array,
                          mask_density: dict[str, float] | None = None,
                          batch_stats: PyTree | None = None) -> float:
    """FLOPs (MAC*2) of one forward pass at ``sample_x``'s shape.

    Conv: 2 * prod(out_spatial) * prod(kernel_shape); Dense: 2 * in * out —
    computed from captured intermediate output shapes. ``mask_density`` maps
    kernel path -> kept fraction for sparsity-aware counting."""
    out_shapes: dict[str, tuple[int, ...]] = {}
    variables = {"params": params}
    if batch_stats is not None and jax.tree.leaves(batch_stats):
        variables["batch_stats"] = batch_stats
    # train=True so BatchNorm needs no pre-existing running stats when
    # ``batch_stats`` is not supplied; shapes are identical either way.
    train = "batch_stats" not in variables

    def run(v, x):
        _, inter = model.apply(
            v, x, train=train, capture_intermediates=True,
            mutable=["intermediates", "batch_stats"],
            rngs={"dropout": jax.random.key(0)} if train else None)
        return inter

    # variables/sample_x ride as eval_shape ARGUMENTS (not closure
    # constants) so the whole pass is abstract: callers may hand
    # ``jax.eval_shape``-derived ShapeDtypeStruct params — the
    # flagship-shape cost-model parity check (obs/compute.py) counts
    # FLOPs without materializing a single activation
    inter = jax.eval_shape(run, variables, sample_x)

    def walk(node, prefix):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, prefix + (k,))
        elif isinstance(node, (tuple, list)):
            for v in node:
                if hasattr(v, "shape"):
                    out_shapes["/".join(prefix[:-1])] = tuple(v.shape)
        elif hasattr(node, "shape"):
            out_shapes["/".join(prefix[:-1])] = tuple(node.shape)

    walk(inter.get("intermediates", inter), ())

    total = 0.0
    for name, kshape in _collect_kernels(params).items():
        density = 1.0 if mask_density is None else float(
            mask_density.get(name, 1.0))
        macs_per_pos = float(np.prod(kshape))
        mod_path = name.rsplit("/", 1)[0]  # e.g. "f0/conv"
        if len(kshape) > 2:  # conv kernel [*k, Cin, Cout]
            out = out_shapes.get(mod_path + "/__call__") or \
                out_shapes.get(mod_path)
            if out is None:
                # A conv kernel whose module output we can't see would be
                # undercounted by the full spatial extent (~1e6x for ABCD
                # volumes) — refuse to count silently.
                raise ValueError(
                    f"FLOPs counter: no captured intermediate output for "
                    f"conv module {mod_path!r} (kernel {name!r}); available "
                    f"paths: {sorted(out_shapes)[:8]}...")
            spatial = float(np.prod(out[1:-1]))  # NDHWC spatial dims
            total += 2.0 * macs_per_pos * spatial * density
        else:  # dense [in, out]
            total += 2.0 * macs_per_pos * density
    return total


def count_training_flops_per_sample(model, params: PyTree,
                                    sample_x: jax.Array,
                                    mask_density: dict[str, float] | None = None,
                                    batch_stats: PyTree | None = None
                                    ) -> float:
    """3x inference, reference convention (model_trainer.py:39-47)."""
    return 3.0 * count_inference_flops(model, params, sample_x, mask_density,
                                       batch_stats=batch_stats)


def count_communication_params(update: PyTree) -> float:
    """Nonzero entries of an update pytree (model_trainer.py:49-53)."""
    return float(sum(int(jnp.sum(x != 0)) for x in jax.tree.leaves(update)))


def densities_from_masks(masks: PyTree) -> dict[str, float]:
    out: dict[str, float] = {}

    def collect(name, m):
        if is_weight_kernel(name, m):
            out[name] = float(jnp.mean(m))
        return m

    tree_map_with_path_names(collect, masks)
    return out
