"""Sub-FedAvg iterative-magnitude-pruning primitives
(fedml_api/standalone/subavg/prune_func.py:9-87), jit-safe.

``fake_prune``: per maskable layer, the ``each_prune_ratio`` percentile of
|w| over currently-ALIVE weights (mask>0) becomes a threshold; weights with
|w| below it are dropped from the mask (prune_func.py:9-30 — note the
comparison is against the FULL tensor, so already-dead weights stay dead).
The percentile uses numpy's linear interpolation between order statistics.

``real_prune`` is just ``params * mask`` (prune_func.py:33-49) — engines use
``tree_mul`` directly.

``mask_distance_mean``: mean over maskable layers of the per-layer Hamming
*fraction* (scipy.spatial.distance.hamming semantics, prune_func.py:52-66).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from neuroimagedisttraining_tpu.ops.masks import is_weight_kernel
from neuroimagedisttraining_tpu.utils.pytree import (
    tree_by_name as _by_name,
    tree_map_with_path_names,
)

PyTree = Any


def _percentile_alive(absw: jax.Array, mask: jax.Array,
                      ratio: float) -> tuple[jax.Array, jax.Array]:
    """(threshold, n_alive): the ``ratio`` quantile (linear interpolation,
    np.percentile parity) of ``absw`` restricted to mask>0."""
    alive = jnp.where(mask > 0, absw, jnp.inf)
    n_alive = jnp.sum(mask > 0).astype(jnp.int32)
    srt = jnp.sort(alive)
    q = ratio * (n_alive.astype(jnp.float32) - 1.0)
    lo = jnp.clip(jnp.floor(q).astype(jnp.int32), 0, absw.shape[0] - 1)
    hi = jnp.clip(lo + 1, 0, absw.shape[0] - 1)
    frac = q - lo.astype(jnp.float32)
    v_lo = jnp.take(srt, lo)
    v_hi = jnp.where(hi < n_alive, jnp.take(srt, hi), v_lo)
    return v_lo + frac * (v_hi - v_lo), n_alive


def fake_prune(each_prune_ratio: float, params: PyTree,
               masks: PyTree) -> PyTree:
    """Candidate next mask: drop the bottom ``each_prune_ratio`` fraction of
    alive |w| per maskable layer; non-maskable leaves keep their mask."""

    def prune(name, m):
        if not is_weight_kernel(name, m):
            return m
        w = _by_name(params, name)
        absw = jnp.abs(w.reshape(-1))
        thr, n_alive = _percentile_alive(absw, m.reshape(-1),
                                         each_prune_ratio)
        new_m = jnp.where(absw < thr, 0.0, m.reshape(-1))
        # empty alive set: reference would crash; we keep the (all-zero) mask
        new_m = jnp.where(n_alive > 0, new_m, m.reshape(-1))
        return new_m.reshape(m.shape)

    return tree_map_with_path_names(prune, masks)


def mask_distance_mean(m1: PyTree, m2: PyTree) -> jax.Array:
    """Mean over maskable layers of per-layer differing-entry FRACTION
    (prune_func.py:52-66 dist_masks)."""
    fracs = []

    def collect(name, a):
        if is_weight_kernel(name, a):
            b = _by_name(m2, name)
            fracs.append(jnp.mean(jnp.abs(a - b)))
        return a

    tree_map_with_path_names(collect, m1)
    return jnp.mean(jnp.stack(fracs))


def density_all_leaves(params: PyTree) -> jax.Array:
    """nonzero/total over EVERY leaf (print_pruning, prune_func.py:69-87) —
    the ``dense`` floor check counts biases/norm params too."""
    nz = sum(jnp.sum(x != 0) for x in jax.tree.leaves(params))
    total = sum(x.size for x in jax.tree.leaves(params))
    return nz.astype(jnp.float32) / total

