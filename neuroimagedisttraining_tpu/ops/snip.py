"""SNIP saliency scoring + global mask construction (SalientGrads core).

The reference computes per-weight saliency by monkey-patching every
Conv3d/Linear with a multiplicative ``weight_mask`` parameter and taking
``|dL/d mask|`` at mask=1 (snip.py:21-74). Since the patched forward is
``conv(x, w * mask)``, the chain rule gives ``dL/d mask = w ⊙ dL/d(w*mask)``,
so at mask=1 the score is exactly ``|w ⊙ grad_w L|`` — one ``jax.grad``
call, no model surgery.

Mask construction (snip.py:80-116): concat+normalize all scores by their
global sum, threshold at the k-th largest normalized score
(k = keep_ratio * total), binary masks for conv/linear kernels, ones for
everything else. The k-th value comes from the Pallas histogram-select
kernel (ops/topk.py).

Cross-client averaging (snip.py:120-140 ``get_mean_snip_scores``) is a plain
mean over the stacked client axis — under the mesh this is one ICI
all-reduce.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.core.trainer import ClientState, LocalTrainer
from neuroimagedisttraining_tpu.ops.masks import is_weight_kernel
from neuroimagedisttraining_tpu.ops.topk import kth_largest
from neuroimagedisttraining_tpu.utils.pytree import (
    tree_by_name as _get,
    tree_map_with_path_names,
)

PyTree = Any


def snip_scores(trainer: LocalTrainer, cs: ClientState, x: jax.Array,
                y: jax.Array) -> PyTree:
    """|w ⊙ grad_w L| on one minibatch, zeros for non-maskable leaves."""
    _, grads, _, _ = trainer.loss_and_grad(cs, x, y)
    return tree_map_with_path_names(
        lambda name, g: jnp.abs(_get(cs.params, name) * g)
        if is_weight_kernel(name, g) else jnp.zeros_like(g),
        grads)


def _stratified_indices(rng: jax.Array, y: jax.Array, n_valid,
                        batch_size: int) -> jax.Array:
    """Label-balanced batch draw: each class contributes with equal expected
    frequency — the intent of the reference's StratifiedKFold batch sampler
    for IterSNIP (client.py:36-46), expressed as weighted sampling so it jits
    with static shapes."""
    valid = jnp.arange(y.shape[0]) < n_valid
    # per-sample weight = 1 / (count of its own label among valid samples),
    # computed via an equality matrix so it works for any label set without
    # a static class count (clients hold <= a few thousand samples, so the
    # O(n^2) compare is negligible)
    eq = (y[None, :] == y[:, None]) & valid[None, :]
    cnt = jnp.sum(eq, axis=1)
    w = jnp.where(valid, 1.0 / jnp.maximum(cnt, 1), 0.0)
    p = w / jnp.maximum(jnp.sum(w), 1e-12)
    return jax.random.choice(rng, y.shape[0], (batch_size,), replace=True,
                             p=p)


def iter_snip_batch_indices(rng: jax.Array, iterations: int,
                            batch_size: int, n_valid) -> jax.Array:
    """[iterations, batch_size] of the batch indices ``iter_snip_scores``
    would draw from ``rng`` (its ``cs.rng``) — the hoisted form the
    cohort-sharded phase-1 computes OUTSIDE its ``shard_map`` and passes
    via ``idx_stack=``: in-partition RNG draws consumed by a scan are
    the measured jax-0.4.x SPMD miscompile class the round's perms hoist
    exists for (parallel/cohort.py). Must mirror ``one_iter``'s splits
    exactly."""
    rngs = jax.random.split(rng, iterations)

    def one(r):
        brng, _ = jax.random.split(r)
        return jax.random.randint(brng, (batch_size,), 0,
                                  jnp.maximum(n_valid, 1))

    return jax.vmap(one)(rngs)


def iter_snip_scores(trainer: LocalTrainer, cs: ClientState, X: jax.Array,
                     y: jax.Array, n_valid, iterations: int,
                     batch_size: int, stratified: bool = False,
                     idx_stack: jax.Array | None = None) -> PyTree:
    """IterSNIP: mean saliency over ``iterations`` minibatches
    (client.py:30-53 + snip.py:143-164). Batches are drawn uniformly from
    the client's valid range, or label-balanced when ``stratified``
    (reference ``stratified_sampling`` flag). ``idx_stack``: precomputed
    batch indices (:func:`iter_snip_batch_indices`, cohort-sharded
    phase-1) — the dropout rng stream is identical either way (the split
    that would feed the draw is still consumed)."""
    def one_iter(carry, xs):
        if idx_stack is None:
            brng, srng = jax.random.split(xs)
            if stratified:
                idx = _stratified_indices(brng, y, n_valid, batch_size)
            else:
                idx = jax.random.randint(brng, (batch_size,), 0,
                                         jnp.maximum(n_valid, 1))
        else:
            rng, idx = xs
            _, srng = jax.random.split(rng)
        # fresh dropout rng per iteration so IterSNIP iterations don't share
        # one dropout mask
        s = snip_scores(trainer, cs.replace(rng=srng),
                        jnp.take(X, idx, axis=0), jnp.take(y, idx, axis=0))
        return jax.tree.map(jnp.add, carry, s), None

    zero = jax.tree.map(jnp.zeros_like, cs.params)
    rngs = jax.random.split(cs.rng, iterations)
    xs = rngs if idx_stack is None else (rngs, idx_stack)
    total, _ = jax.lax.scan(one_iter, zero, xs)
    return jax.tree.map(lambda t: t / iterations, total)


def mean_scores(stacked_scores: PyTree) -> PyTree:
    """Server-side mean of per-client score pytrees (snip.py:120-140); with a
    client-sharded leading axis this lowers to an all-reduce."""
    return jax.tree.map(lambda s: jnp.mean(s, axis=0), stacked_scores)


def mask_from_scores(scores: PyTree, keep_ratio: float) -> tuple[PyTree, jax.Array]:
    """Normalize scores by global sum, keep the top ``keep_ratio`` fraction
    globally (cross-layer), ones for non-maskable leaves (snip.py:80-116)."""
    flat_parts, total_elems = [], 0

    def collect(name, s):
        nonlocal total_elems
        if is_weight_kernel(name, s):
            flat_parts.append(s.reshape(-1))
            total_elems += s.size
        return s

    tree_map_with_path_names(collect, scores)
    all_scores = jnp.concatenate(flat_parts)
    norm = jnp.sum(all_scores)
    # count non-finite entries on the RAW scores: after the /norm below a
    # single NaN poisons every element and the count would read as "all"
    bad = jnp.sum(~jnp.isfinite(all_scores))
    all_scores = all_scores / norm
    k = max(1, int(total_elems * keep_ratio))
    threshold = kth_largest(all_scores, k)
    # Fail LOUDLY on non-finite saliency (e.g. one client's phase-1 loss
    # diverged): the histogram top-k would otherwise return a garbage
    # threshold and the run would continue with a silently-wrong global
    # mask. (The reference would crash inside torch.topk; silence is
    # worse.) This runs eagerly — generate_global_mask calls it outside
    # jit — and the three diagnostics sync in ONE batched device fetch
    # (ISSUE 4 / VERDICT r5 #5): the old per-check bool()/int() pulls
    # cost 3-5 round trips through the device tunnel back to back, each
    # blocking on the full score pipeline; all quantities are computed
    # first (garbage-tolerant — a non-finite norm just yields a
    # non-finite threshold we are about to refuse) and fetched together.
    norm_h, bad_h, thr_h = jax.device_get((norm, bad, threshold))
    if not np.isfinite(norm_h):
        raise FloatingPointError(
            f"SNIP saliency scores contain {int(bad_h)} non-finite "
            "entries (or their sum overflows): refusing to build the "
            "global mask. Check the phase-1 loss of each client for "
            "divergence.")
    if norm_h == 0:
        # all-zero saliency (e.g. dead activations or a zero-initialized
        # head): normalizing would give 0/0 = NaN everywhere — distinct
        # failure, distinct diagnostic
        raise FloatingPointError(
            "SNIP saliency scores are identically zero: no signal to rank "
            "— the phase-1 gradient probe produced zero gradients for "
            "every maskable weight (dead activations? zero init?).")
    if not np.isfinite(thr_h):
        raise FloatingPointError(
            f"global top-k threshold is non-finite ({int(bad_h)} "
            "non-finite raw saliency scores): refusing to build "
            "the global mask. Check the phase-1 loss of each client for "
            "divergence.")

    def build(name, s):
        if is_weight_kernel(name, s):
            return ((s / norm) >= threshold).astype(jnp.float32)
        return jnp.ones_like(s)

    return tree_map_with_path_names(build, scores), threshold

