"""Fused masked-SGD tail: clip + weight-decay + momentum + update + mask
in ONE pass over the parameters (Pallas on TPU, XLA fallback elsewhere).

The unfused optax chain the engines run per training step
(core/optim.py: ``clip_by_global_norm -> add_decayed_weights -> trace``,
then ``params += -lr * updates`` and the masked engines' ``params *=
mask``) materializes a full params-sized intermediate in HBM per stage —
five reads + four writes of the 2.6M-param flagship tree per step, and
the masked-grad intermediate exists only to be multiplied and thrown
away. This module computes the identical arithmetic as one elementwise
kernel per leaf: read {param, grad, momentum, mask}, write {param,
momentum}. The global-norm reduction stays a separate (unavoidable)
pass, shared with the unfused path via ``optax.global_norm``.

Parity contract (tests/test_precision.py):

- the XLA fallback reproduces the optax chain BITWISE — same ops in the
  same order (``lax.select(trigger, g, (g / gnorm) * clip)``,
  ``g + wd*p``, ``u + momentum*t``, ``p + (-lr)*u``, ``p * mask``), so
  masked engines produce identical masks/metrics with the fused path on
  or off;
- the Pallas kernel is pinned bit-equal to the fallback on TPU (the
  same elementwise f32 ops on the VPU); on CPU the kernel runs in
  interpreter mode under a tolerance pin (the interpreter's math is the
  fallback's — the pin guards the padding/blocking plumbing).

Template: ops/stemconv.py / ops/topk.py (block conventions, the
CompilerParams fallback for the pinned jax-0.4.x toolchain). Scalars
ride a (1, 128) f32 operand mapped to every grid step — lr is a traced
per-round scalar, the clip trigger and global norm are per-step values;
clip/wd/momentum are config constants baked as static flags so a
disabled stage costs nothing (and a wd=0 model avoids the ``g + 0*p``
rewrite of signed zeros the unfused identity stage never performs).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax

_LANES = 128
_MAX_BLOCK_ROWS = 512   # [512, 128] f32 block = 256 KiB VMEM per operand


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# ---------- kernel ----------

def _make_kernel(has_clip: bool, has_wd: bool, has_trace: bool,
                 has_mask: bool):
    """Kernel factory: the stage set is static per config, so a disabled
    stage is absent from the compiled kernel entirely."""

    def kernel(*refs):
        refs = list(refs)
        p_ref = refs.pop(0)
        g_ref = refs.pop(0)
        t_ref = refs.pop(0) if has_trace else None
        m_ref = refs.pop(0) if has_mask else None
        s_ref = refs.pop(0)
        p_out = refs.pop(0)
        t_out = refs.pop(0) if has_trace else None

        p = p_ref[...]
        g = g_ref[...]
        if has_clip:
            ok = s_ref[0, 0]       # 1.0 when gnorm < clip (no rescale)
            gnorm = s_ref[0, 1]
            clip = s_ref[0, 2]
            g = jnp.where(ok > 0.5, g, (g / gnorm) * clip)
        if has_wd:
            g = g + s_ref[0, 3] * p
        if has_trace:
            g = g + s_ref[0, 4] * t_ref[...]
            t_out[...] = g
        p_new = p + (-s_ref[0, 5]) * g
        if has_mask:
            p_new = p_new * m_ref[...]
        p_out[...] = p_new

    return kernel


def _leaf_pallas(p, g, t, m, scalars, has_clip: bool, has_wd: bool,
                 interpret: bool = False):
    """One leaf through the fused kernel: flatten -> pad to [R, 128]
    blocks -> grid over row blocks -> unpad. Returns (p_new, t_new|None).
    Zero padding is inert through every stage (0/gnorm*clip = 0,
    0 + wd*0 = 0, ...) and sliced off regardless."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    has_trace, has_mask = t is not None, m is not None
    n = p.size
    rows = _round_up(max(1, -(-n // _LANES)), 8)
    block_rows = min(_MAX_BLOCK_ROWS, rows)
    rows = _round_up(rows, block_rows)
    grid = rows // block_rows

    def pad2d(x):
        flat = x.astype(jnp.float32).reshape(-1)
        flat = jnp.concatenate(
            [flat, jnp.zeros((rows * _LANES - n,), jnp.float32)])
        return flat.reshape(rows, _LANES)

    operands = [pad2d(p), pad2d(g)]
    if has_trace:
        operands.append(pad2d(t))
    if has_mask:
        operands.append(pad2d(m))
    operands.append(scalars)

    blk = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
    in_specs = [blk] * (2 + has_trace + has_mask) + [
        pl.BlockSpec((1, _LANES), lambda i: (0, 0))]
    out_shape = [jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)]
    out_specs = [blk]
    if has_trace:
        out_shape.append(jax.ShapeDtypeStruct((rows, _LANES), jnp.float32))
        out_specs.append(blk)

    # jax >= 0.5 renamed TPUCompilerParams -> CompilerParams; support
    # both so the kernel imports under the pinned 0.4.x toolchain
    params_cls = getattr(pltpu, "CompilerParams",
                         getattr(pltpu, "TPUCompilerParams", None))
    out = pl.pallas_call(
        _make_kernel(has_clip, has_wd, has_trace, has_mask),
        out_shape=tuple(out_shape),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        compiler_params=params_cls(dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*operands)

    unpad = lambda x: x.reshape(-1)[:n].reshape(p.shape)
    p_new = unpad(out[0])
    t_new = unpad(out[1]) if has_trace else None
    return p_new, t_new


# ---------- XLA fallback (the bitwise reference) ----------

def _leaf_xla(p, g, t, m, ok, gnorm, clip: float, wd: float,
              momentum: float, lr):
    """The optax chain's exact per-leaf arithmetic, fused lexically (XLA
    fuses it into one loop on CPU/GPU): this IS the reference the Pallas
    kernel is pinned against, and it is bitwise-equal to the unfused
    ``make_local_optimizer`` path by construction (same ops, same
    order — clipping.clip_by_global_norm / transform.trace /
    add_decayed_weights, optax 0.2.x)."""
    if clip > 0:
        g = jax.lax.select(ok, g, (g / gnorm.astype(g.dtype)) * clip)
    if wd > 0:
        g = g + wd * p
    if momentum > 0:
        g = g + momentum * t
    t_new = g if momentum > 0 else None
    p_new = jnp.add(p, -lr * g)
    if m is not None:
        p_new = jnp.multiply(p_new, m)
    return p_new, t_new


# ---------- public API ----------

def fused_sgd_step(params, grads, trace, mask, *, clip: float, wd: float,
                   momentum: float, lr, use_pallas: bool | None = None,
                   interpret: bool = False):
    """One fused SGD step over a whole pytree.

    ``trace`` is the momentum tree (None when momentum == 0); ``mask``
    the sparse-training mask tree (None for dense engines). ``lr`` may
    be a traced scalar (the per-round decayed lr). Returns
    ``(new_params, new_trace|None)`` — float32 master weights in, f32
    out, exactly like the unfused chain.
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    ok = gnorm = None
    if clip > 0:
        gnorm = optax.global_norm(grads)          # the shared reduction
        ok = jnp.squeeze(gnorm < clip)

    leaves_p, treedef = jax.tree.flatten(params)
    leaves_g = treedef.flatten_up_to(grads)
    leaves_t = (treedef.flatten_up_to(trace) if trace is not None
                else [None] * len(leaves_p))
    leaves_m = (treedef.flatten_up_to(mask) if mask is not None
                else [None] * len(leaves_p))

    if use_pallas or interpret:
        # [ok, gnorm, clip, wd, momentum, lr] + lane padding; a single
        # (1, 128) f32 operand broadcast to every grid step
        svals = jnp.stack([
            jnp.where(ok, 1.0, 0.0) if ok is not None else jnp.float32(1),
            (gnorm if gnorm is not None else jnp.float32(1))
            .astype(jnp.float32),
            jnp.float32(clip), jnp.float32(wd), jnp.float32(momentum),
            jnp.asarray(lr, jnp.float32)])
        scalars = jnp.zeros((1, _LANES), jnp.float32).at[0, :6].set(svals)
        step = functools.partial(_leaf_pallas, scalars=scalars,
                                 has_clip=clip > 0, has_wd=wd > 0,
                                 interpret=interpret)
    else:
        step = functools.partial(_leaf_xla, ok=ok, gnorm=gnorm, clip=clip,
                                 wd=wd, momentum=momentum, lr=lr)

    out = [step(p, g, t, m) for p, g, t, m in
           zip(leaves_p, leaves_g, leaves_t, leaves_m)]
    new_params = jax.tree.unflatten(treedef, [o[0] for o in out])
    new_trace = (jax.tree.unflatten(treedef, [o[1] for o in out])
                 if trace is not None else None)
    return new_params, new_trace
