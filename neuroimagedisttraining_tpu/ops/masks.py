"""Sparse-mask machinery shared by SalientGrads / DisPFL / Sub-FedAvg.

Masks are pytrees congruent with ``params``: float 0/1 arrays for maskable
leaves (conv/linear kernels — the reference masks ``Conv3d``/``Linear``
``.weight`` only, snip.py:42-55) and ones elsewhere (snip.py:108-113).

Ported semantics:
- ``calculate_sparsities``: ERK (Erdos-Renyi-Kernel) layer sparsity with the
  dense-layer escape loop, and uniform mode
  (DisPFL/my_model_trainer.py:56-130, identical copy in sailentgrads).
- ``init_masks``: per-layer random masks with exactly
  ``(1-sparsity)*numel`` ones (my_model_trainer.py:32-43).
- ``fire_mask``: cosine-annealed drop of the smallest-magnitude surviving
  weights (DisPFL/client.py:71-82) — exact drop counts via rank-vs-dynamic-k
  comparison instead of torch's dynamic index slicing.
- ``regrow_mask``: regrow by largest gradient magnitude on currently-zero
  positions, or random regrow under ``dis_gradient_check``
  (DisPFL/client.py:85-99).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.utils.pytree import (
    tree_by_name as _by_name,
    tree_map_with_path_names,
)

PyTree = Any


def is_weight_kernel(name: str, leaf) -> bool:
    """Maskable leaf: a conv/dense kernel (reference: Conv3d/Linear .weight)."""
    return name.endswith("kernel") and getattr(leaf, "ndim", 0) >= 2


def ones_mask(params: PyTree) -> PyTree:
    return jax.tree.map(jnp.ones_like, params)


def mask_density(masks: PyTree, params: PyTree | None = None) -> jax.Array:
    """Fraction of kept weights over maskable leaves."""
    num, den = 0.0, 0.0
    flat = jax.tree_util.tree_leaves_with_path(masks)
    for path, leaf in flat:
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        if is_weight_kernel(name, leaf):
            num = num + jnp.sum(leaf)
            den = den + leaf.size
    return num / max(den, 1.0)


def calculate_sparsities(params: PyTree, distribution: str = "ERK",
                         dense_ratio: float = 0.5,
                         erk_power_scale: float = 1.0,
                         tabu: tuple[str, ...] = ()) -> dict[str, float]:
    """Per-maskable-leaf target sparsity, keyed by '/'-joined param path.

    ERK: sparsity_l = 1 - eps * ((sum shape_l / prod shape_l) ** power);
    layers whose probability would exceed 1 are made dense and epsilon is
    re-solved (my_model_trainer.py:56-130).
    """
    shapes: dict[str, tuple[int, ...]] = {}

    def collect(name, leaf):
        if is_weight_kernel(name, leaf):
            shapes[name] = tuple(leaf.shape)
        return leaf

    tree_map_with_path_names(collect, params)

    sparsities: dict[str, float] = {}
    if distribution == "uniform":
        for name in shapes:
            sparsities[name] = 0.0 if name in tabu else 1.0 - dense_ratio
        return sparsities

    if distribution != "ERK":
        raise ValueError(f"unknown distribution {distribution!r}")

    density = dense_ratio
    dense_layers = set(t for t in tabu if t in shapes)
    while True:
        divisor, rhs = 0.0, 0.0
        raw_probabilities: dict[str, float] = {}
        for name, shape in shapes.items():
            n_param = float(np.prod(shape))
            if name in dense_layers:
                rhs -= n_param * (1.0 - density)
            else:
                rhs += n_param * density
                raw_probabilities[name] = (
                    float(np.sum(shape)) / float(np.prod(shape))
                ) ** erk_power_scale
                divisor += raw_probabilities[name] * n_param
        epsilon = rhs / divisor
        max_prob = max(raw_probabilities.values())
        if max_prob * epsilon > 1:
            for name, p in raw_probabilities.items():
                if p == max_prob:
                    dense_layers.add(name)
        else:
            break
    for name in shapes:
        if name in dense_layers:
            sparsities[name] = 0.0
        else:
            sparsities[name] = 1.0 - epsilon * raw_probabilities[name]
    return sparsities


def init_masks(rng: jax.Array, params: PyTree,
               sparsities: dict[str, float]) -> PyTree:
    """Random binary masks with exactly floor((1-s)*numel) ones per maskable
    leaf; ones elsewhere (my_model_trainer.py:32-43)."""
    leaves_rng = {name: r for name, r in zip(
        sorted(sparsities), jax.random.split(rng, max(len(sparsities), 1)))}

    def build(name, leaf):
        if name not in sparsities:
            return jnp.ones_like(leaf)
        dense_numel = int((1.0 - sparsities[name]) * leaf.size)
        flat = jnp.zeros((leaf.size,), leaf.dtype)
        perm = jax.random.permutation(leaves_rng[name], leaf.size)
        flat = flat.at[perm[:dense_numel]].set(1)
        return flat.reshape(leaf.shape)

    return tree_map_with_path_names(build, params)


def _rank_of(values: jax.Array, descending: bool = False) -> jax.Array:
    """rank[i] = position of element i in the sorted order (stable)."""
    order = jnp.argsort(-values if descending else values)
    return jnp.zeros_like(order).at[order].set(jnp.arange(order.shape[0]))


def fire_mask(masks: PyTree, weights: PyTree, round_idx, comm_round: int,
              anneal_factor: float = 0.5) -> tuple[PyTree, dict]:
    """Drop ceil(drop_ratio * nnz) smallest-|w| surviving weights per layer;
    drop_ratio = anneal/2 * (1 + cos(round*pi/comm_round))
    (DisPFL/client.py:71-82). Exact counts under jit via rank < k."""
    drop_ratio = anneal_factor / 2.0 * (
        1.0 + jnp.cos(round_idx * jnp.pi / comm_round))
    num_remove = {}

    def fire(name, m):
        w = _by_name(weights, name)
        if not is_weight_kernel(name, m):
            return m
        nnz = jnp.sum(m)
        k = jnp.ceil(drop_ratio * nnz).astype(jnp.int32)
        num_remove[name] = k
        temp = jnp.where(m.reshape(-1) > 0, jnp.abs(w.reshape(-1)),
                         jnp.float32(1e5))
        rank = _rank_of(temp)
        keep = (rank >= k).astype(m.dtype) * m.reshape(-1)
        return keep.reshape(m.shape)

    new_masks = tree_map_with_path_names(fire, masks)
    return new_masks, num_remove


def regrow_mask(masks: PyTree, num_remove: dict, gradient: PyTree | None,
                rng: jax.Array | None = None,
                dis_gradient_check: bool = False) -> PyTree:
    """Regrow ``num_remove[name]`` positions per layer on zeros: by largest
    |grad| (default) or uniformly at random (DisPFL/client.py:85-99)."""
    names = sorted(num_remove)
    rngs = {}
    if dis_gradient_check:
        assert rng is not None
        rngs = {n: r for n, r in zip(names, jax.random.split(rng, max(len(names), 1)))}

    def regrow(name, m):
        if name not in num_remove:
            return m
        k = num_remove[name]
        flat = m.reshape(-1)
        if dis_gradient_check:
            score = jnp.where(flat == 0,
                              jax.random.uniform(rngs[name], flat.shape),
                              -jnp.float32(1e5))
        else:
            g = _by_name(gradient, name).reshape(-1)
            score = jnp.where(flat == 0, jnp.abs(g), -jnp.float32(1e5))
        rank = _rank_of(score, descending=True)
        return jnp.where(rank < k, jnp.ones_like(flat), flat).reshape(m.shape)

    return tree_map_with_path_names(regrow, masks)


def mask_hamming_distance(a: PyTree, b: PyTree) -> jax.Array:
    """Total count of differing mask entries (slim_util.py:14-19 dist_masks)."""
    parts = jax.tree.leaves(jax.tree.map(
        lambda x, y: jnp.sum(jnp.abs(x - y)), a, b))
    return jnp.sum(jnp.stack(parts))

