"""Alternative weight-gradient for the C_in=1 stride-2 stem conv
(Pallas split-K; opt-in via ``NIDT_FAST_STEM=1``).

The flagship 3D CNNs open with ``Conv3d(1, 64, kernel_size=5, stride=2)``
(salient_models.py:147), and its kernel-gradient — a contraction of ~4M
patch rows onto a tiny 125x64 output — dominates the whole training
step: per-stage bisection puts stage f0's fwd+bwd at ~44 ms of a ~40 ms
full-model step, i.e. everything after the stem is free (PROFILE.md
round 2). Every XLA formulation measured lands 13-40 ms (conv emitter,
im2col+dot, k-split batched dot, parity-decomposed convs), far from the
shape's compute cost.

This module is the Pallas alternative. It is OFF by default: on the
harness's shared tunnel chip the measured effective HBM bandwidth
(~75-200 GB/s, time-varying — nominal v5e is 819) makes the step
bandwidth-bound, and this path's extra patch materialization made it
NET SLOWER there (80-96 ms) despite the clean MXU contraction. On
full-bandwidth hardware the split puts ~2.2 GB of traffic behind a
canonical [128, K]x[K, 64] MXU stream and is expected to win; measure
before enabling.

Design (see ``_dw_pallas``): XLA builds one contiguous patch row per
tap from stride-2 parity sub-volumes, stacked to [128, R]; Pallas runs
the [128, R] x [R, C] contraction as a split-K grid of canonical MXU
dots with per-block f32 partials (no program_id, no cross-step
accumulation — composes with the engines' client-axis ``vmap``); a
ragged K tail falls to a tiny XLA dot.

``stem_conv3d`` wraps forward (plain XLA conv — fine on MXU) and this
backward in a ``custom_vjp``; dx falls back to the standard transposed
conv (dead-code-eliminated in training, where the input is data). On
non-TPU backends the whole op falls back to XLA autodiff. Gradient
products run in the training compute dtype (bf16 models -> bf16 dW,
matching XLA's own bf16 kernel-grad; f32 models keep f32).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_DN = ("NDHWC", "DHWIO", "NDHWC")
_K = 5       # kernel size per spatial dim
_S = 2       # stride
_KB = 3      # parity-block taps per dim (ceil(K/S))
_P = 8       # parities (S^3)


def _conv(x: jax.Array, w: jax.Array) -> jax.Array:
    return lax.conv_general_dilated(x, w, (_S,) * 3, "VALID",
                                    dimension_numbers=_DN)


_BLK = 8192   # split-K block columns per grid step
_MROWS = 128  # tap rows padded to one MXU/lane tile


def _dw_kernel(p_ref, g_ref, out_ref):
    """One split-K block: out = P_blk @ g_blk, canonical [M,K]x[K,N] MXU
    orientation, f32 accumulate."""
    out_ref[0] = lax.dot_general(
        p_ref[...], g_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _dw_pallas(x: jax.Array, g: jax.Array,
               interpret: bool = False) -> jax.Array:
    """dW [5,5,5,1,C] for y = conv3d(x, W, stride 2, VALID).

    Build: 8 parity sub-volumes of x (stride-2 slices), then one
    CONTIGUOUS row per tap — ``P[t] = flatten(x_par[p][block slice])`` —
    stacked to [128, R] (125 real taps + zero rows). Pure block copies;
    no conv emitter, no interleaving. Pallas then grids a split-K
    [128, blk] x [blk, C] MXU matmul over R with per-block f32 partials
    (summed by XLA); the ragged tail of R is a tiny XLA dot. Per-block
    partial outputs keep the kernel free of program_id/accumulation, so
    it composes with the engines' client-axis vmap."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    od, oh, ow = g.shape[1:4]
    c_out = g.shape[4]
    # products run in the training compute dtype: bf16 models get bf16
    # dW (matching XLA's own bf16 kernel-grad); f32 models keep f32
    cdtype = (x.dtype if x.dtype in (jnp.float32, jnp.bfloat16)
              else jnp.bfloat16)
    xb = x[..., 0].astype(cdtype)
    rows = []
    for kd in range(_K):
        for kh in range(_K):
            for kw in range(_K):
                par = xb[:, kd % _S::_S, kh % _S::_S, kw % _S::_S]
                sl = par[:, kd // _S:kd // _S + od,
                         kh // _S:kh // _S + oh,
                         kw // _S:kw // _S + ow]
                rows.append(sl.reshape(-1))
    r = rows[0].shape[0]
    taps = len(rows)                                     # 125
    p2 = jnp.stack(
        rows + [jnp.zeros((r,), cdtype)] * (_MROWS - taps))
    g2 = g.astype(cdtype).reshape(-1, c_out)             # [R, C]

    nblk = r // _BLK
    rmain = nblk * _BLK
    if nblk == 0:  # tiny inputs (tests): the ragged-tail dot covers all of R
        dw = lax.dot_general(p2[:taps], g2, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        return dw.reshape(_K, _K, _K, 1, c_out)
    part = pl.pallas_call(
        _dw_kernel,
        out_shape=jax.ShapeDtypeStruct((nblk, _MROWS, c_out), jnp.float32),
        grid=(nblk,),
        in_specs=[pl.BlockSpec((_MROWS, _BLK), lambda i: (0, i)),
                  pl.BlockSpec((_BLK, c_out), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, _MROWS, c_out), lambda i: (i, 0, 0)),
        # jax >= 0.5 renamed TPUCompilerParams -> CompilerParams; support
        # both so the kernel imports under the pinned 0.4.x toolchain
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(p2[:, :rmain], g2[:rmain])

    dw = jnp.sum(part, axis=0)[:taps]                    # [125, C]
    if rmain < r:                                        # ragged K tail
        dw = dw + lax.dot_general(
            p2[:taps, rmain:], g2[rmain:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    return dw.reshape(_K, _K, _K, 1, c_out)


@jax.custom_vjp
def stem_conv3d(x: jax.Array, w: jax.Array) -> jax.Array:
    """``conv3d(x, w, stride 2, VALID)`` for single-channel NDHWC input
    with a Pallas weight-gradient on TPU (XLA autodiff elsewhere)."""
    return _conv(x, w)


def _fwd(x, w):
    return _conv(x, w), (x, w)


def _bwd(res, g):
    x, w = res
    # dx via the standard transposed conv — XLA DCEs it when the input is
    # training data (nothing consumes the cotangent)
    _, vjp = jax.vjp(lambda x_: _conv(x_, w), x)
    (dx,) = vjp(g)
    if jax.default_backend() == "tpu":
        dw = _dw_pallas(x, g).astype(w.dtype)
    else:
        _, vjp_w = jax.vjp(lambda w_: _conv(x, w_), w)
        (dw,) = vjp_w(g)
    return dx, dw


stem_conv3d.defvjp(_fwd, _bwd)


@functools.partial(jax.jit, static_argnames=())
def _dw_reference(x, g):
    """XLA kernel-grad (for tests): dW of sum(conv * g)."""
    _, vjp_w = jax.vjp(lambda w_: _conv(x, w_),
                       jnp.zeros((_K, _K, _K, 1, g.shape[-1]), x.dtype))
    (dw,) = vjp_w(g)
    return dw
