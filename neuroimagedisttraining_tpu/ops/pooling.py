"""Scatter-free non-overlapping 3D max pool (opt-in).

The reference's 3D CNNs pool with kernel 3 / stride 3 — NON-overlapping
windows (salient_models.py:150-168 ``nn.MaxPool3d(kernel_size=3,
stride=3)``). XLA's generic max-pool gradient is ``SelectAndScatter``
(a serial scatter on TPU). For disjoint windows the gradient has a
closed form with no scatter:

    dx = (x == upsample(max)) * upsample(g)

Measured on the harness TPU (PROFILE.md round 2): ~4% faster full train
step (41.7 -> 39.9 ms at b16) — but it carries the pooled outputs as
VJP residuals plus an upsample temporary, and the flagship 4-client b16
no-remat federation packs HBM to within ~50 MB of capacity, where that
overhead tips it OOM. The model zoo therefore keeps XLA's max-pool by
DEFAULT; enable this op per-process via ``NIDT_FAST_POOL=1`` for
layouts with headroom (1-client-per-core mesh layout, smaller batch, or
remat="stem").

Tie semantics: the window's gradient is split EQUALLY across all
elements tied at the max (torch routes it all to the first argmax; XLA's
SelectAndScatter to one winner). Ties are common here — these pools
consume post-ReLU bf16 activations where whole windows of 0.0 tie — so
the equal split conserves the window's gradient mass exactly instead of
inflating it up to k^3-fold; on tie-free inputs all three rules agree
(pinned by tests/test_ops.py against the XLA reference).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def max_pool_3d_nonoverlap(x: jax.Array, k: int) -> jax.Array:
    """kernel=k, stride=k, VALID — torch ``MaxPool3d(k, stride=k)``."""
    return jax.lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min,
        jax.lax.max, (1, k, k, k, 1), (1, k, k, k, 1), "VALID")


def _fwd(x, k):
    y = max_pool_3d_nonoverlap(x, k)
    return y, (x, y)


def _upsample_nn(y: jax.Array, k: int, out_spatial: tuple[int, int, int]
                 ) -> jax.Array:
    """Nearest-neighbor upsample of NDHWC ``y`` by factor ``k``,
    zero-padded to ``out_spatial`` (tail voxels beyond the last full
    window belong to no window)."""
    n, d, h, w, c = y.shape
    y = jnp.broadcast_to(y[:, :, None, :, None, :, None, :],
                         (n, d, k, h, k, w, k, c))
    y = y.reshape(n, d * k, h * k, w * k, c)
    pd, ph, pw = (out_spatial[0] - d * k, out_spatial[1] - h * k,
                  out_spatial[2] - w * k)
    if pd or ph or pw:
        y = jnp.pad(y, [(0, 0), (0, pd), (0, ph), (0, pw), (0, 0)])
    return y


def _bwd(k, res, g):
    x, y = res
    spatial = x.shape[1:4]
    yb = _upsample_nn(y, k, spatial)
    mask = (x == yb).astype(g.dtype)
    # equal-split across ties: post-ReLU bf16 activations tie at the max
    # routinely (whole windows of 0.0), where routing the FULL gradient
    # to every tie would inflate dx up to k^3-fold vs the reference's
    # single-argmax routing — dividing by the tie count conserves the
    # window's gradient mass exactly
    cnt = jax.lax.reduce_window(mask, 0.0, jax.lax.add,
                                (1, k, k, k, 1), (1, k, k, k, 1), "VALID")
    gb = _upsample_nn(g / jnp.maximum(cnt, 1.0), k, spatial)
    return (mask * gb,)


max_pool_3d_nonoverlap.defvjp(_fwd, _bwd)
