from neuroimagedisttraining_tpu.ops import masks, snip, topk, flops  # noqa: F401
