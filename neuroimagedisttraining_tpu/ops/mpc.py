"""Finite-field MPC toolkit for secure aggregation (TurboAggregate).

Capability parity with fedml_api/standalone/turboaggregate/mpc_function.py:4-275:
Shamir/BGW share encode/decode, LCC (Lagrange Coded Computing) encode/decode
with K data chunks + T random masking chunks, Lagrange coefficient
generation, additive secret shares, Diffie-Hellman-style key agreement, plus
the fixed-point float<->field quantization the reference's TA_trainer needs
but never shipped.

Re-designed, not translated: the reference computes share polynomials with
O(N·T) Python loops over scalar ``np.mod`` calls; here every operation is a
vectorized numpy expression over int64 with a modulus after each product so
all intermediates stay below 2^63 (valid for any prime p < 2^31.5; default
p = 2^31 - 1, the 8th Mersenne prime). Modular inverses use Fermat
exponentiation (a^(p-2) mod p) via vectorized square-and-multiply instead of
the reference's iterative extended-Euclid (modular_inv,
mpc_function.py:4-18).
"""

from __future__ import annotations

import numpy as np

P_DEFAULT = 2**31 - 1  # Mersenne prime; p^2 < 2^63 keeps int64 products exact

#: wire-size-tiered fields for secure QUANTIZED aggregation (privacy/):
#: the largest prime below each wire width. The share algebra is the
#: same mod any prime; a smaller field means fewer bytes per masked
#: element on the wire (uint16 shares are 4x smaller than the dense
#: protocol's int64 slots). Keyed by field_bits.
FIELD_PRIMES = {8: 251, 16: 65521, 32: P_DEFAULT}


def wire_dtype_for(p: int) -> np.dtype:
    """Smallest unsigned numpy dtype that holds every residue of GF(p) —
    what a field-element frame ships per masked value."""
    if p <= 1 << 8:
        return np.dtype(np.uint8)
    if p <= 1 << 16:
        return np.dtype(np.uint16)
    if p < 1 << 32:
        return np.dtype(np.uint32)
    raise ValueError(f"field modulus {p} exceeds the uint32 wire width")


def _asfield(x, p: int) -> np.ndarray:
    return np.mod(np.asarray(x, np.int64), p)


def mod_pow(base, exp: int, p: int) -> np.ndarray:
    """Vectorized square-and-multiply: base**exp mod p over int64 arrays."""
    base = _asfield(base, p)
    out = np.ones_like(base)
    e = int(exp)
    while e > 0:
        if e & 1:
            out = (out * base) % p
        base = (base * base) % p
        e >>= 1
    return out


def mod_inv(a, p: int) -> np.ndarray:
    """Fermat inverse a^(p-2) mod p (p prime). Parity with modular_inv
    (mpc_function.py:4-18) on every unit of the field."""
    return mod_pow(a, p - 2, p)


def lagrange_coeffs(alphas, betas, p: int) -> np.ndarray:
    """U[i, j] = prod_{k!=j} (alpha_i - beta_k) / (beta_j - beta_k) mod p
    (gen_Lagrange_coeffs, mpc_function.py:39-59) — evaluation of the
    Lagrange basis over points ``betas`` at targets ``alphas``."""
    alphas = _asfield(alphas, p)
    betas = _asfield(betas, p)
    A, B = len(alphas), len(betas)
    # denominators: prod over k != j of (beta_j - beta_k)
    den = np.ones(B, np.int64)
    num = np.ones((A, B), np.int64)
    for k in range(B):
        db = np.mod(betas - betas[k], p)          # [B]
        db[k] = 1                                 # skip self term
        den = (den * db) % p
        da = np.mod(alphas[:, None] - betas[k], p)  # [A, 1]
        keep = np.ones(B, np.int64)
        keep[k] = 0                               # term excluded for j == k
        num = (num * np.where(keep, da, 1)) % p
    return (num * mod_inv(den, p)[None, :]) % p


# ---------------- BGW (Shamir) secret sharing ----------------

def bgw_encode(X, N: int, T: int, p: int = P_DEFAULT, rng=None) -> np.ndarray:
    """Degree-T Shamir shares of X (field elements, any shape) evaluated at
    alpha = 1..N (BGW_encoding, mpc_function.py:62-75). Returns [N, *X.shape].
    Secrecy: any T shares reveal nothing; T+1 reconstruct."""
    rng = rng or np.random.default_rng()  # nidt: allow[determinism-unseeded-rng] -- secret-sharing masks MUST be unpredictable: fresh OS entropy unless a test injects rng
    X = _asfield(X, p)
    coeffs = np.concatenate(
        [X[None], rng.integers(0, p, size=(T,) + X.shape, dtype=np.int64)])
    alphas = np.arange(1, N + 1, dtype=np.int64) % p
    shares = np.zeros((N,) + X.shape, np.int64)
    a_pow = np.ones(N, np.int64)
    for t in range(T + 1):
        term = (a_pow.reshape((N,) + (1,) * X.ndim) * coeffs[t]) % p
        shares = (shares + term) % p
        a_pow = (a_pow * alphas) % p
    return shares


def bgw_decode(shares, worker_idx, p: int = P_DEFAULT) -> np.ndarray:
    """Reconstruct the secret from >= T+1 shares: Lagrange-interpolate the
    share polynomial at 0 (BGW_decoding + gen_BGW_lambda_s,
    mpc_function.py:78-108). ``shares``: [R, ...], ``worker_idx``: 0-based."""
    alphas_eval = (np.asarray(worker_idx, np.int64) + 1) % p
    lam = lagrange_coeffs(np.zeros(1, np.int64), alphas_eval, p)[0]  # [R]
    acc = np.zeros(shares.shape[1:], np.int64)
    for r in range(shares.shape[0]):
        acc = (acc + lam[r] * _asfield(shares[r], p)) % p
    return acc


# ---------------- LCC (Lagrange Coded Computing) ----------------

def _lcc_points(N: int, K: int, T: int, p: int):
    """Evaluation (alphas) / interpolation (betas) point grids.

    DELIBERATE DEVIATION from the reference: mpc_function.py:122-125
    centers BOTH grids around 0, so they overlap — a worker whose alpha
    equals a data-chunk beta receives that chunk IN THE CLEAR (f(beta_j) is
    the plaintext chunk j), voiding the T-privacy guarantee. Here the
    alphas start strictly after the betas, keeping the grids disjoint; the
    encode/decode pair stays self-consistent, only the (broken) share
    values differ from the reference's."""
    n_beta = K + T
    stt_b = -(n_beta // 2)
    betas = np.arange(stt_b, stt_b + n_beta, dtype=np.int64)
    alphas = np.arange(betas[-1] + 1, betas[-1] + 1 + N, dtype=np.int64)
    return np.mod(alphas, p), np.mod(betas, p)


def lcc_encode(X, N: int, K: int, T: int, p: int = P_DEFAULT,
               rng=None) -> np.ndarray:
    """Split X (first axis divisible by K) into K chunks + T random chunks,
    interpolate through them at ``betas`` and evaluate at ``alphas``
    (LCC_encoding / LCC_encoding_w_Random, mpc_function.py:111-164).
    Returns [N, m//K, ...]."""
    rng = rng or np.random.default_rng()  # nidt: allow[determinism-unseeded-rng] -- secret-sharing masks MUST be unpredictable: fresh OS entropy unless a test injects rng
    X = _asfield(X, p)
    m = X.shape[0]
    assert m % K == 0, f"first axis {m} not divisible by K={K}"
    chunks = X.reshape((K, m // K) + X.shape[1:])
    if T:
        rand = rng.integers(0, p, size=(T,) + chunks.shape[1:],
                            dtype=np.int64)
        chunks = np.concatenate([chunks, rand])
    alphas, betas = _lcc_points(N, K, T, p)
    U = lagrange_coeffs(alphas, betas, p)          # [N, K+T]
    out = np.zeros((N,) + chunks.shape[1:], np.int64)
    for j in range(K + T):
        term = (U[:, j].reshape((N,) + (1,) * (chunks.ndim - 1))
                * chunks[j]) % p
        out = (out + term) % p
    return out


def lcc_decode(f_eval, N: int, K: int, T: int, worker_idx,
               p: int = P_DEFAULT) -> np.ndarray:
    """Recover the K data chunks from workers' evaluations
    (LCC_decoding, mpc_function.py:195-211). ``f_eval``: [R, m//K, ...]."""
    alphas, betas = _lcc_points(N, K, T, p)
    alphas_eval = alphas[np.asarray(worker_idx, np.int64)]
    U = lagrange_coeffs(betas[:K], alphas_eval, p)  # [K, R]
    out = np.zeros((K,) + f_eval.shape[1:], np.int64)
    for r in range(f_eval.shape[0]):
        term = (U[:, r].reshape((K,) + (1,) * (f_eval.ndim - 1))
                * _asfield(f_eval[r], p)) % p
        out = (out + term) % p
    return out.reshape((K * f_eval.shape[1],) + f_eval.shape[2:])


# ---------------- additive secret sharing ----------------

def additive_shares(x, n_out: int, p: int = P_DEFAULT, rng=None) -> np.ndarray:
    """n_out shares summing to x mod p (Gen_Additive_SS,
    mpc_function.py:214-224)."""
    rng = rng or np.random.default_rng()  # nidt: allow[determinism-unseeded-rng] -- secret-sharing masks MUST be unpredictable: fresh OS entropy unless a test injects rng
    x = _asfield(x, p)
    shares = rng.integers(0, p, size=(n_out - 1,) + x.shape, dtype=np.int64)
    last = np.mod(x - np.mod(shares.sum(axis=0), p), p)
    return np.concatenate([shares, last[None]])


def secure_sum(stack, n_shares: int, frac_bits: int = 16,
               p: int = P_DEFAULT, rng=None, trace=None) -> np.ndarray:
    """Server-side secure aggregation of a client-stacked float array
    ``stack[S, ...]`` via additive secret shares (Gen_Additive_SS,
    mpc_function.py:214-224): quantize each client's update into GF(p),
    split into ``n_shares`` additive shares, and accumulate SLOT-MAJOR —
    share slot j sums across ALL clients before any two slots are
    combined. Each slot total is uniformly-random masked material, so no
    server-side intermediate ever equals an individual client's quantized
    update (the privacy invariant VERDICT r2 weak #2 found violated by the
    earlier per-client ``shares.sum(axis=0)`` order); only the final
    cross-slot sum — the aggregate itself — is in the clear.

    ``trace``: optional list; every server-side intermediate (each slot
    accumulator state after each client) is appended, so tests can assert
    the invariant directly.
    """
    rng = rng or np.random.default_rng()  # nidt: allow[determinism-unseeded-rng] -- secret-sharing masks MUST be unpredictable: fresh OS entropy unless a test injects rng
    stack = np.asarray(stack)
    slots = np.zeros((n_shares,) + stack.shape[1:], np.int64)
    for c in range(stack.shape[0]):
        q = quantize(stack[c], p=p, frac_bits=frac_bits)
        shares = additive_shares(q, n_shares, p=p, rng=rng)
        slots = (slots + shares) % p
        if trace is not None:
            trace.extend(slots.copy())
    total = np.mod(slots.sum(axis=0), p)
    return dequantize(total, p=p, frac_bits=frac_bits)


# ---------------- DH key agreement ----------------

def pk_gen(sk: int, p: int = P_DEFAULT, g: int = 0) -> int:
    """g=0 is the reference's degenerate test mode returning sk
    (my_pk_gen, mpc_function.py:263-268)."""
    return int(sk) if g == 0 else int(mod_pow(np.int64(g), int(sk), p))


def key_agreement(my_sk: int, u_pk: int, p: int = P_DEFAULT,
                  g: int = 0) -> int:
    return (int(np.mod(np.int64(my_sk) * np.int64(u_pk), p)) if g == 0
            else int(mod_pow(np.int64(u_pk), int(my_sk), p)))


# ---------------- fixed-point float <-> field ----------------

def quantize(x, p: int = P_DEFAULT, frac_bits: int = 16) -> np.ndarray:
    """Two's-complement-style embedding: round(x * 2^frac_bits) mod p.
    Values must satisfy |x| * 2^frac_bits < p/2 for exact recovery."""
    scaled = np.rint(np.asarray(x, np.float64) * (1 << frac_bits))
    return np.mod(scaled.astype(np.int64), p)


def dequantize(q, p: int = P_DEFAULT, frac_bits: int = 16) -> np.ndarray:
    q = _asfield(q, p)
    centered = np.where(q > p // 2, q - p, q)
    return centered.astype(np.float64) / (1 << frac_bits)


def quantize32(x, p: int = P_DEFAULT, frac_bits: int = 16) -> np.ndarray:
    """Host embedding BITWISE-identical to the device one
    (ops/mpc_device.py::quantize_device): float32 scale/round with the
    same sign-preserving saturation at the field edge. The float64
    ``quantize`` above can differ from the device by one LSB per element
    (test_mpc notes it); the secure-QUANTIZED aggregation parity pin
    (privacy/secure_quant.py — host protocol == device program ==
    plain quantized weighted mean, bitwise) needs the embeddings to
    agree exactly, so the host path uses this float32 twin."""
    lim = np.float32((p - 1) // 2)
    if int(lim) > (p - 1) // 2:  # float32 rounded UP past the field edge
        lim = np.nextafter(lim, np.float32(0.0))
    scaled = np.rint(np.asarray(x, np.float32) * np.float32(1 << frac_bits))
    # NaN passes through clip and the int cast would yield INT_MIN — an
    # arbitrary out-of-field "residue" that corrupts the aggregate. Map
    # it to the zero residue (a neutral contribution) instead; +/-inf
    # saturates sign-preservingly via the clip. Mirrored on device.
    scaled = np.where(np.isnan(scaled), np.float32(0.0), scaled)
    v = np.clip(scaled, -lim, lim).astype(np.int32).astype(np.int64)
    return np.where(v < 0, v + p, v)


def dequantize32(q, p: int = P_DEFAULT, frac_bits: int = 16) -> np.ndarray:
    """float32 centered lift matching ``dequantize_device`` bitwise."""
    q = _asfield(q, p)
    centered = np.where(q > p // 2, q - p, q).astype(np.int32)
    return centered.astype(np.float32) / np.float32(1 << frac_bits)
