"""L0 offline preprocessing: raw NIfTI cohort -> X/y/site HDF5.

The reference ships this stage as a notebook (Preprocess_ABCD.ipynb); this
module is the same pipeline as a runnable CLI::

    python -m neuroimagedisttraining_tpu.preprocess \
        --raw_dir /data/ABCD/Raw_Data --subject_info ABCDSexSiteInfo.txt \
        --out cohort.h5

Pipeline parity (cells cited from /root/reference/Preprocess_ABCD.ipynb):

1. Subject discovery (cell 3): ``<raw_dir>/<subject>/Baseline/<anat_201*>/
   Sm6mwc1pT1.nii`` — first matching anat dir per subject wins; subjects
   without one are skipped.
2. Brain mask (cells 7-16): voxelwise MEAN over all subjects' volumes,
   thresholded at ``mask_threshold`` (reference: mean > 0.2).
3. Mask apply (cell 20): each subject's volume is multiplied by the
   binary mask.
4. Labels (cells 25-28): CSV columns ``female`` -> category codes = y,
   ``abcd_site`` -> label-encoded (sorted-unique index) = site.
5. Per-subject min-max + 8-bit quantization (cell 37):
   ``uint8(round((x - min) / (max - min) * 255))`` per subject.
   STORAGE NOTE: the notebook divides back by 255 and stores float; this
   framework stores the uint8 codes directly (4x smaller on disk and over
   PCIe — the loader raw-casts uint8 -> float32 on device,
   core/trainer.py:77-80), so inputs span 0..255 instead of 0..1. That is
   a constant input scale absorbed by the first conv's weights; use
   ``--store_float`` for the notebook's exact 0..1 float32 storage.
6. HDF5 schema (cell 30): one file with datasets ``X``, ``y``, ``site``
   — exactly what ``data/hdf5.py::load_abcd_hdf5`` consumes. Rows are
   written subject-at-a-time (the full cohort never has to fit in RAM).

NIfTI ingestion uses nibabel when available and otherwise falls back to
the built-in minimal NIfTI-1 reader below (plain + .gz single-file,
scl_slope/scl_inter applied like ``nib.get_fdata``).
"""

from __future__ import annotations

import argparse
import csv
import gzip
import os
import struct
import sys

import numpy as np

# NIfTI-1 datatype codes -> numpy dtypes (the subset real T1 maps use)
_NIFTI_DTYPES = {2: "u1", 4: "i2", 8: "i4", 16: "f4", 64: "f8",
                 256: "i1", 512: "u2", 768: "u4"}


# ---------------------------------------------------------------- NIfTI IO

def read_nifti(path: str) -> np.ndarray:
    """Volume as float32, scl_slope/inter applied (nib.get_fdata parity)."""
    try:
        import nibabel as nib  # optional dependency

        return np.asarray(nib.load(path).get_fdata(), np.float32)
    except ImportError:
        pass
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        raw = f.read()
    if len(raw) < 348:
        raise ValueError(f"{path}: truncated NIfTI header")
    sizeof_hdr = struct.unpack("<i", raw[0:4])[0]
    bo = "<" if sizeof_hdr == 348 else ">"
    if struct.unpack(bo + "i", raw[0:4])[0] != 348:
        raise ValueError(f"{path}: not a NIfTI-1 file")
    dim = struct.unpack(bo + "8h", raw[40:56])
    shape = tuple(int(d) for d in dim[1: 1 + dim[0]])
    datatype = struct.unpack(bo + "h", raw[70:72])[0]
    vox_offset = int(struct.unpack(bo + "f", raw[108:112])[0])
    scl_slope = struct.unpack(bo + "f", raw[112:116])[0]
    scl_inter = struct.unpack(bo + "f", raw[116:120])[0]
    if datatype not in _NIFTI_DTYPES:
        raise ValueError(f"{path}: unsupported NIfTI datatype {datatype}")
    dt = np.dtype(bo + _NIFTI_DTYPES[datatype])
    n = int(np.prod(shape))
    data = np.frombuffer(raw, dt, count=n, offset=vox_offset)
    data = data.reshape(shape, order="F").astype(np.float32)
    if np.isfinite(scl_slope) and scl_slope not in (0.0, 1.0):
        data = data * scl_slope
    if np.isfinite(scl_inter) and scl_inter != 0.0:
        data = data + scl_inter
    return data


def write_nifti(path: str, data: np.ndarray) -> None:
    """Minimal NIfTI-1 writer (float32, identity affine) — enough for the
    synthetic round-trip test and for exporting masks."""
    data = np.asarray(data, np.float32)
    hdr = bytearray(352)  # 348 header + 4-byte extension flag
    struct.pack_into("<i", hdr, 0, 348)
    dims = (data.ndim,) + data.shape + (1,) * (7 - data.ndim)
    struct.pack_into("<8h", hdr, 40, *dims)
    struct.pack_into("<h", hdr, 70, 16)        # datatype = float32
    struct.pack_into("<h", hdr, 72, 32)        # bitpix
    struct.pack_into("<8f", hdr, 76, 1, 1, 1, 1, 1, 1, 1, 1)  # pixdim
    struct.pack_into("<f", hdr, 108, 352.0)    # vox_offset
    struct.pack_into("<f", hdr, 112, 1.0)      # scl_slope
    hdr[344:348] = b"n+1\x00"                  # magic: single-file
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(bytes(hdr))
        f.write(np.asarray(data, "<f4").tobytes(order="F"))


# ---------------------------------------------------------------- pipeline

def discover_subjects(raw_dir: str, anat_prefix: str = "anat_201",
                      volume_name: str = "Sm6mwc1pT1.nii"):
    """(subject_id, volume_path) pairs — cell 3's directory walk."""
    out = []
    for sid in sorted(os.listdir(raw_dir)):
        base = os.path.join(raw_dir, sid, "Baseline")
        if not os.path.isdir(base):
            continue
        for inside in sorted(os.listdir(base)):
            if inside.startswith(anat_prefix):
                for cand in (volume_name, volume_name + ".gz"):
                    p = os.path.join(base, inside, cand)
                    if os.path.exists(p):
                        out.append((sid, p))
                        break
                else:
                    continue
                break  # first matching anat dir wins (cell 3 fileFlag)
    return out


#: candidate subject-id columns, checked in order (ABCD uses subjectkey /
#: src_subject_id; the notebook's sheet carries none, hence the fallback)
_ID_COLUMNS = ("subjectkey", "src_subject_id", "subject_id", "subject", "id")


def _codes(vals):
    """pandas category codes == sorted-unique index (cells 25-28)."""
    uniq = sorted(set(vals))
    table = {v: i for i, v in enumerate(uniq)}
    return np.asarray([table[v] for v in vals])


def load_subject_info(path: str):
    """``female``/``abcd_site`` columns -> (female values, site values,
    ids, id column name) in file order. Values are RAW strings — category
    codes must be computed AFTER any join/subset, or a dropped row
    carrying a novel value would shift every kept subject's code. ``ids``
    is the subject-id column when one exists (so callers can join rows to
    discovered volumes by id instead of by position), else ``None``."""
    with open(path, newline="") as f:
        rows = list(csv.DictReader(f))
    if not rows:
        raise ValueError(f"{path}: empty subject info")
    for col in ("female", "abcd_site"):
        if col not in rows[0]:
            raise ValueError(f"{path}: missing column {col!r}")
    female = [r["female"] for r in rows]
    site = [r["abcd_site"] for r in rows]
    ids, id_col = None, None
    for col in _ID_COLUMNS:
        if col in rows[0]:
            ids, id_col = [r[col] for r in rows], col
            break
    return female, site, ids, id_col


def quantize_subject(vol: np.ndarray) -> np.ndarray:
    """Per-subject min-max -> uint8 codes (cell 37)."""
    lo, hi = float(vol.min()), float(vol.max())
    norm = (vol - lo) / max(hi - lo, 1e-12)
    return (norm * 255).astype(np.uint8)


def preprocess_cohort(raw_dir: str, subject_info: str, out_path: str,
                      mask_threshold: float = 0.2,
                      anat_prefix: str = "anat_201",
                      volume_name: str = "Sm6mwc1pT1.nii",
                      store_float: bool = False,
                      log=print) -> dict:
    """Run the full pipeline; returns a summary dict."""
    import h5py

    subjects = discover_subjects(raw_dir, anat_prefix, volume_name)
    if not subjects:
        raise ValueError(f"no subjects with {volume_name} under {raw_dir}")
    female, site_raw, ids, id_col = load_subject_info(subject_info)
    if ids is not None:
        # join by subject id: a CSV row whose volume was skipped by
        # discovery must not shift every later subject's y/site
        table = {sid: i for i, sid in enumerate(ids)}
        if len(table) != len(ids):
            dupes = sorted({s for s in ids if ids.count(s) > 1})
            raise ValueError(
                f"subject info column {id_col!r} has duplicate ids "
                f"{dupes[:5]} — ambiguous join")
        missing = [sid for sid, _ in subjects if sid not in table]
        if missing:
            raise ValueError(
                f"subject info is missing {id_col!r} rows for discovered "
                f"volumes: {missing[:5]}"
                f"{'...' if len(missing) > 5 else ''} (if this column is "
                "not the directory subject id, rename it to re-enable "
                "positional pairing)")
        order = [table[sid] for sid, _ in subjects]
        female = [female[i] for i in order]
        site_raw = [site_raw[i] for i in order]
    elif len(female) != len(subjects):
        # positional pairing is only sound when the counts agree exactly
        raise ValueError(
            f"subject info has {len(female)} rows but {len(subjects)} "
            "volumes were discovered and no subject-id column "
            f"({'/'.join(_ID_COLUMNS)}) is present to join on — row-order "
            "pairing would silently misalign labels")
    # codes AFTER the join: dropped rows must not contribute categories
    y = _codes(female).astype(np.int8)
    site = _codes(site_raw).astype(np.int16)
    log(f"{len(subjects)} subjects discovered")

    # pass 1: voxelwise mean -> brain mask (cells 7-16)
    total = None
    for _, p in subjects:
        vol = read_nifti(p)
        total = vol if total is None else total + vol
    mask = (total / len(subjects)) > mask_threshold
    log(f"brain mask: {int(mask.sum())}/{mask.size} voxels "
        f"(threshold {mask_threshold})")

    # pass 2: mask -> per-subject min-max -> quantize -> stream rows out
    shape = mask.shape
    with h5py.File(out_path, "w") as f:
        X = f.create_dataset(
            "X", (len(subjects),) + shape,
            dtype=np.float32 if store_float else np.uint8,
            chunks=(1,) + shape)
        for i, (_, p) in enumerate(subjects):
            vol = read_nifti(p)
            if vol.shape != shape:
                raise ValueError(
                    f"{p}: shape {vol.shape} != mask shape {shape}")
            q = quantize_subject(vol * mask)
            X[i] = (q.astype(np.float32) / 255.0) if store_float else q
        f.create_dataset("y", data=y)
        f.create_dataset("site", data=site)
    log(f"wrote {out_path}: X{(len(subjects),) + shape} "
        f"{'float32' if store_float else 'uint8'}, y, site")
    return {"subjects": len(subjects), "shape": shape,
            "mask_voxels": int(mask.sum()),
            "sites": int(site.max()) + 1}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="neuroimagedisttraining_tpu.preprocess",
        description=__doc__.split("\n\n")[0])
    ap.add_argument("--raw_dir", required=True,
                    help="BIDS-ish root: <raw_dir>/<subject>/Baseline/"
                         "anat_201*/Sm6mwc1pT1.nii")
    ap.add_argument("--subject_info", required=True,
                    help="CSV with 'female' and 'abcd_site' columns "
                         "(ABCDSexSiteInfo.txt layout), rows in subject "
                         "order")
    ap.add_argument("--out", required=True, help="output HDF5 path")
    ap.add_argument("--mask_threshold", type=float, default=0.2)
    ap.add_argument("--anat_prefix", type=str, default="anat_201")
    ap.add_argument("--volume_name", type=str, default="Sm6mwc1pT1.nii")
    ap.add_argument("--store_float", action="store_true",
                    help="store X as float32 in [0,1] (the notebook's "
                         "exact values) instead of uint8 codes")
    args = ap.parse_args(argv)
    preprocess_cohort(args.raw_dir, args.subject_info, args.out,
                      mask_threshold=args.mask_threshold,
                      anat_prefix=args.anat_prefix,
                      volume_name=args.volume_name,
                      store_float=args.store_float)
    return 0


if __name__ == "__main__":
    sys.exit(main())
