"""Seeded fault schedule: a pure function of ``(seed, round, rank)``.

Every event decision derives from ``np.random.default_rng(...)`` seeded
with the full event coordinates ``(seed, stream, round, rank[, seq])`` —
no global numpy stream, no OS entropy (nidtlint determinism rules), so
the entire fault trace replays bit-identically from the config seed in
any process, in any order of queries.

Ranks use the cross-silo numbering: rank 0 is the server, clients are
ranks ``1..num_clients``. The simulated engines map client index ``c``
to rank ``c + 1`` (``FederatedEngine.client_sampling`` survivor
filtering), so one ``--fault_spec`` drives both the in-process
simulation and the multiprocess federation.

``activity_mask`` is DisPFL's Bernoulli activity draw (dispfl_api.py:96,
ours at engines/dispfl.py), lifted here so the engine and the schedule
share one seeded stream — the unification ISSUE 2 requires.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import numpy as np

# sub-stream tags: distinct event kinds never share an RNG stream
_STREAM_CRASH = 1
_STREAM_STRAGGLE = 2
_STREAM_DROP = 3
_STREAM_DUP = 4
_STREAM_DISCONNECT = 5
_STREAM_BYZ = 6

#: value-fault kinds a Byzantine client can inject (faults/adversary.py
#: realizes them as jitted pytree transforms). ``scale`` and ``gauss``
#: carry a parameter: ``scale:K`` / ``gauss:STD``.
BYZ_KINDS = ("sign_flip", "scale", "gauss", "nonfinite")


def parse_byz_kind(text: str) -> str:
    """Validate a byz KIND token (``sign_flip | scale:K | gauss:STD |
    nonfinite``) and return it canonicalized. Raises ValueError on
    anything else — a typo'd attack kind must fail at config parse, not
    mid-round."""
    text = text.strip()
    name, _, param = text.partition(":")
    name = name.strip()
    if name not in BYZ_KINDS:
        raise ValueError(
            f"unknown byz kind {text!r}; one of sign_flip | scale:K | "
            "gauss:STD | nonfinite")
    if name in ("scale", "gauss"):
        if not param:
            raise ValueError(
                f"byz kind {name!r} needs a parameter ({name}:VALUE)")
        val = float(param)  # raises ValueError on garbage
        if name == "gauss" and val < 0:
            raise ValueError(f"byz gauss std must be >= 0, got {val}")
        return f"{name}:{val}"
    if param:
        raise ValueError(f"byz kind {name!r} takes no parameter "
                         f"(got {text!r})")
    return name


def activity_mask(seed: int, round_idx: int, n: int,
                  active_prob: float) -> np.ndarray:
    """DisPFL's per-round Bernoulli(active) draw, bit-identical to the
    engine's historical inline formula (engines/dispfl.py active_draw):
    one generator seeded ``seed * 100003 + round_idx``, one uniform per
    client."""
    rng = np.random.default_rng(seed * 100003 + round_idx)
    return rng.random(n) < active_prob


@dataclass(frozen=True)
class FaultSpec:
    """What can go wrong. All probabilities are per-event Bernoulli
    parameters; ``crashes`` adds deterministic (rank, round) kill points
    on top of the probabilistic draw, and ``rejoins`` ends a
    deterministic crash window (crash **and** come back — the churn the
    asyncfl load harness drives, ISSUE 7)."""

    crashes: tuple[tuple[int, int], ...] = ()  # (rank, round): dead from round on
    # (rank, round): alive again from round on — must follow a ``crashes``
    # directive for the same rank at an earlier round (parse-validated);
    # probabilistic crash_prob deaths stay permanent (no seeded stream
    # could decide WHICH probabilistic corpse a rejoin revives)
    rejoins: tuple[tuple[int, int], ...] = ()
    crash_prob: float = 0.0        # per-(round, rank); crashes are permanent
    straggle_prob: float = 0.0     # per-(round, rank)
    straggle_delay: float = 0.0    # max seconds; actual ~ U(0, max)
    drop_prob: float = 0.0         # per outbound protocol message
    dup_prob: float = 0.0          # per outbound protocol message
    disconnect_prob: float = 0.0   # mid-frame disconnect per outbound message
    # value faults (Byzantine clients, faults/adversary.py): (rank,
    # round, kind) — the client uploads adversarially transformed
    # updates from ``round`` on (a compromised silo stays compromised,
    # same permanence as ``crashes``); byz_prob draws a per-(round,
    # rank) transient corruption of ``byz_kind`` instead
    byz: tuple[tuple[int, int, str], ...] = ()
    byz_prob: float = 0.0
    byz_kind: str = "sign_flip"
    # device preemption (ISSUE 20, elastic compute plane): (round, ndev)
    # — at ROUND the training mesh loses devices down to NDEV survivors;
    # the engine re-plans client_mesh over them and resumes from the
    # last checkpoint (engines/base.py _maybe_preempt). A COMPUTE-plane
    # fault: it never corrupts upload values (any_value_faults excludes
    # it) and never touches the client-liveness streams.
    preempts: tuple[tuple[int, int], ...] = ()

    def __post_init__(self) -> None:
        # a rejoin without an earlier deterministic crash for the same
        # rank is a spec typo (the rank was never scheduled dead) — fail
        # at parse/construction, never mid-run
        for rank, at in self.rejoins:
            if not any(r == rank and cr < at for r, cr in self.crashes):
                raise ValueError(
                    f"rejoin:{rank}@{at} has no crash:{rank}@ROUND "
                    f"directive with ROUND < {at} to rejoin from")
            if any(r == rank and cr == at for r, cr in self.crashes):
                # a tie would make the event walk order-dependent —
                # the 'rounds never tie' invariant crashed() relies on
                raise ValueError(
                    f"crash:{rank}@{at} and rejoin:{rank}@{at} share a "
                    "round; crash/rejoin directives for one rank must "
                    "alternate at distinct rounds")

    @property
    def any_faults(self) -> bool:
        return bool(self.crashes) or bool(self.byz) \
            or bool(self.preempts) or any(
            p > 0 for p in (self.crash_prob, self.straggle_prob,
                            self.drop_prob, self.dup_prob,
                            self.disconnect_prob, self.byz_prob))

    @property
    def any_value_faults(self) -> bool:
        """True iff the spec can corrupt upload VALUES (the engines must
        route updates through faults/adversary.py; omission/timing
        faults never need that)."""
        return bool(self.byz) or self.byz_prob > 0


def parse_fault_spec(text: str) -> FaultSpec:
    """Parse the ``--fault_spec`` mini-grammar: comma/semicolon-separated
    directives::

        crash:RANK@ROUND        deterministic kill of RANK at ROUND
        rejoin:RANK@ROUND       RANK comes back at ROUND (ends a crash
                                window; needs an earlier crash:RANK@R —
                                deterministic churn for the async load
                                harness; crash_prob deaths stay permanent)
        crash_prob:P            per-(round, rank) Bernoulli crash
        straggle:P:MAX_DELAY    with prob P delay sends by U(0, MAX_DELAY) s
        drop:P                  drop outbound protocol messages with prob P
        dup:P                   duplicate outbound messages with prob P
        disconnect:P            tear the connection mid-frame with prob P
        byz:RANK@ROUND:KIND     RANK uploads KIND-corrupted values from
                                ROUND on; KIND = sign_flip | scale:K |
                                gauss:STD | nonfinite
        byz_prob:P[:KIND]       per-(round, rank) transient value fault
                                of KIND (default sign_flip)
        preempt:NDEV@ROUND      device preemption: at ROUND the training
                                mesh loses devices down to NDEV
                                survivors; the engine shrinks
                                client_mesh and resumes from the last
                                checkpoint (elastic plane, ISSUE 20)

    e.g. ``"crash:3@1,rejoin:3@4,drop:0.1,byz:1@0:sign_flip"``. Empty
    string => no faults."""
    crashes: list[tuple[int, int]] = []
    rejoins: list[tuple[int, int]] = []
    byz: list[tuple[int, int, str]] = []
    preempts: list[tuple[int, int]] = []
    kw: dict = {}
    for part in text.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        key, _, rest = part.partition(":")
        key = key.strip()
        try:
            if key in ("crash", "rejoin"):
                rank_s, _, round_s = rest.partition("@")
                (crashes if key == "crash" else rejoins).append(
                    (int(rank_s), int(round_s)))
            elif key == "byz":
                at, _, kind = rest.partition(":")
                rank_s, _, round_s = at.partition("@")
                if not kind:
                    raise ValueError(
                        "byz needs RANK@ROUND:KIND (e.g. byz:1@0:sign_flip)")
                byz.append((int(rank_s), int(round_s),
                            parse_byz_kind(kind)))
            elif key == "byz_prob":
                p_s, _, kind = rest.partition(":")
                kw["byz_prob"] = float(p_s)
                if kind:
                    kw["byz_kind"] = parse_byz_kind(kind)
            elif key == "straggle":
                p_s, _, d_s = rest.partition(":")
                kw["straggle_prob"] = float(p_s)
                kw["straggle_delay"] = float(d_s)
            elif key == "preempt":
                ndev_s, _, round_s = rest.partition("@")
                ndev, at = int(ndev_s), int(round_s)
                if ndev < 1:
                    raise ValueError(
                        "preempt needs NDEV >= 1 survivors "
                        "(preempt:NDEV@ROUND)")
                preempts.append((at, ndev))
            elif key == "crash_prob":
                kw["crash_prob"] = float(rest)
            elif key in ("drop", "dup", "disconnect"):
                kw[f"{key}_prob"] = float(rest)
            else:
                raise ValueError(f"unknown fault directive {key!r}")
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"bad --fault_spec directive {part!r}: {e}") from None
    for name, p in kw.items():
        if name in ("straggle_delay", "byz_kind"):
            continue
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"--fault_spec {name}={p} not in [0, 1]")
    try:
        return FaultSpec(crashes=tuple(crashes), rejoins=tuple(rejoins),
                         byz=tuple(byz),
                         preempts=tuple(sorted(preempts)), **kw)
    except ValueError as e:  # rejoin-without-crash cross-validation
        raise ValueError(f"bad --fault_spec: {e}") from None


class FaultSchedule:
    """The deterministic chaos oracle. Every query is a pure function of
    ``(seed, round, rank[, msg stream, seq])`` — repeated queries and
    fresh instances over the same spec+seed agree bit-for-bit."""

    def __init__(self, spec: FaultSpec, seed: int):
        self.spec = spec
        self.seed = int(seed)
        #: rank -> [(round, is_crash)] sorted by round; FaultSpec
        #: validation guarantees every rejoin strictly follows a crash,
        #: so rounds never tie and the walk in ``crashed`` is unambiguous
        self._life_events: dict[int, list[tuple[int, bool]]] = {}
        for rank, round_idx in spec.crashes:
            self._life_events.setdefault(rank, []).append((round_idx, True))
        for rank, round_idx in spec.rejoins:
            self._life_events.setdefault(rank, []).append((round_idx, False))
        for events in self._life_events.values():
            events.sort(key=lambda e: (e[0], e[1]))

    # ---- per-(round, rank) event draws ----

    def _draw(self, stream: int, round_idx: int, rank: int,
              seq: int | None = None) -> np.random.Generator:
        coords = [self.seed, stream, int(round_idx), int(rank)]
        if seq is not None:
            coords.append(int(seq))
        return np.random.default_rng(coords)

    def crashed(self, round_idx: int, rank: int) -> bool:
        """True iff ``rank`` is dead at ``round_idx``. Deterministic
        ``crash:``/``rejoin:`` directives form alternating windows (the
        latest directive at or before ``round_idx`` decides); a
        probabilistic ``crash_prob`` death is permanent — the wrapper's
        process is gone, and only an explicit rejoin directive (or the
        control plane's re-register path) models a comeback."""
        dead = False
        for at, is_crash in self._life_events.get(rank, ()):
            if at > round_idx:
                break
            dead = is_crash
        if dead:
            return True
        p = self.spec.crash_prob
        if p > 0:
            for r in range(int(round_idx) + 1):
                if self._draw(_STREAM_CRASH, r, rank).random() < p:
                    return True
        return False

    def crash_round(self, rank: int, horizon: int) -> int | None:
        """First round < horizon at which ``rank`` is dead, or None."""
        for r in range(horizon):
            if self.crashed(r, rank):
                return r
        return None

    def byzantine_kind(self, round_idx: int, rank: int) -> str | None:
        """The value-fault kind ``rank`` injects at ``round_idx``, or
        None when it uploads honestly. Deterministic ``byz:`` directives
        are permanent from their round on (latest directive whose round
        has arrived wins); ``byz_prob`` adds a transient per-(round,
        rank) Bernoulli draw of ``byz_kind`` on its own RNG stream."""
        best: tuple[int, str] | None = None
        for r, at, kind in self.spec.byz:
            if r == rank and round_idx >= at and (
                    best is None or at >= best[0]):
                best = (at, kind)
        if best is not None:
            return best[1]
        p = self.spec.byz_prob
        if p > 0 and self._draw(_STREAM_BYZ, round_idx,
                                rank).random() < p:
            return self.spec.byz_kind
        return None

    def straggle_seconds(self, round_idx: int, rank: int) -> float:
        if self.spec.straggle_prob <= 0 or self.spec.straggle_delay <= 0:
            return 0.0
        rng = self._draw(_STREAM_STRAGGLE, round_idx, rank)
        if rng.random() >= self.spec.straggle_prob:
            return 0.0
        return float(rng.random() * self.spec.straggle_delay)

    # ---- per-message draws (seq = per-(round, msg-type) send index) ----

    def drop(self, round_idx: int, rank: int, seq: int) -> bool:
        return (self.spec.drop_prob > 0 and
                self._draw(_STREAM_DROP, round_idx, rank, seq).random()
                < self.spec.drop_prob)

    def duplicate(self, round_idx: int, rank: int, seq: int) -> bool:
        return (self.spec.dup_prob > 0 and
                self._draw(_STREAM_DUP, round_idx, rank, seq).random()
                < self.spec.dup_prob)

    def disconnect(self, round_idx: int, rank: int, seq: int) -> bool:
        return (self.spec.disconnect_prob > 0 and
                self._draw(_STREAM_DISCONNECT, round_idx, rank,
                           seq).random() < self.spec.disconnect_prob)

    # ---- federation-level views ----

    def survivors(self, round_idx: int, client_indices: np.ndarray
                  ) -> np.ndarray:
        """Filter 0-based engine client indices (rank = index + 1) down
        to those alive at ``round_idx``. If the schedule would kill every
        sampled client the original set is returned unchanged — an empty
        round has no reference semantics and would poison the aggregate
        with a 0/0."""
        alive = np.asarray([not self.crashed(round_idx, int(c) + 1)
                            for c in np.asarray(client_indices)], bool)
        if not alive.any():
            return np.asarray(client_indices)
        return np.asarray(client_indices)[alive]

    def active_mask(self, round_idx: int, n_clients: int,
                    active_prob: float = 1.0) -> np.ndarray:
        """DisPFL-style activity combined with crashes: a client is
        active iff its Bernoulli(active) draw succeeds AND it has not
        crashed. With no crash directives this is bit-identical to the
        historical DisPFL draw."""
        a = activity_mask(self.seed, round_idx, n_clients, active_prob)
        dead = np.asarray([self.crashed(round_idx, c + 1)
                           for c in range(n_clients)], bool)
        return a & ~dead

    def trace(self, rounds: int, ranks: range | list[int],
              msgs_per_round: int = 4) -> list[dict]:
        """Materialize the full event table — the replay artifact tests
        pin (two instances over the same spec+seed must produce equal
        traces)."""
        out = []
        for r in range(rounds):
            for k in ranks:
                out.append({
                    "round": r, "rank": int(k),
                    "crashed": self.crashed(r, k),
                    "byzantine": self.byzantine_kind(r, k),
                    "straggle_s": self.straggle_seconds(r, k),
                    "drop": [self.drop(r, k, s)
                             for s in range(msgs_per_round)],
                    "dup": [self.duplicate(r, k, s)
                            for s in range(msgs_per_round)],
                    "disconnect": [self.disconnect(r, k, s)
                                   for s in range(msgs_per_round)],
                })
        return out

    def describe(self) -> str:
        return (f"FaultSchedule(seed={self.seed}, "
                f"{dataclasses.asdict(self.spec)})")
