"""Deterministic Byzantine value-fault transforms (the ``byz:`` grammar).

``faults/schedule.py`` decides WHO is Byzantine WHEN and with what KIND
(a pure function of the config seed — the same contract as
``activity_mask``/``survivors``, so one seed drives the simulated
engines and the multiprocess federation identically). This module
realizes the kinds as jitted pytree transforms on a client's upload::

    sign_flip   u' = ref − (u − ref)            (flip the update delta)
    scale:K     u' = ref + K·(u − ref)          (amplified update)
    gauss:STD   u' = u + N(0, STD²)             (additive Gaussian)
    nonfinite   u' = NaN everywhere             (poison-the-mean probe)

Attacks transform the *upload delta* against the round's broadcast
reference — the model tree the client just received — because clients
upload full parameter trees, not gradients: negating the raw parameters
would be a trivially detectable attack, whereas a flipped or scaled
delta stays inside plausible parameter ranges (and, for ``sign_flip``
inside the clip bound, passes norm-diff clipping untouched — the gap
ISSUE 5's robust aggregators close).

Numerically every kind lowers to one fused per-client form

    d' = mult · (u − ref) + std · N(0, 1);   u' = ref + d'
    u' = NaN where nonfinite

so a whole cohort's attack round is three scalars per client
(``mult``, ``std``, ``nonfinite``) plus a PRNG key — host-precomputable
per round, stackable over a fused ``lax.scan`` window, and applied
inside the jitted round body (``apply_attack_stacked``). Gaussian noise
keys derive from ``(seed, round, rank)`` via ``jax.random.fold_in``, so
the cross-silo client (``attack_update``, eager on its own upload) and
the simulated engine (vmapped over the client axis) inject bitwise-
identical noise.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from neuroimagedisttraining_tpu.faults.schedule import FaultSchedule

PyTree = Any

#: fold-in offset decorrelating attack-noise keys from the engines'
#: per-client training rngs (base.py uses seed + 17)
_KEY_SALT = 23029


def kind_params(kind: str | None) -> tuple[float, float, bool]:
    """``(mult, std, nonfinite)`` numerics for a canonical kind string
    (``schedule.parse_byz_kind`` output) or None (honest client)."""
    if kind is None:
        return 1.0, 0.0, False
    name, _, param = kind.partition(":")
    if name == "sign_flip":
        return -1.0, 0.0, False
    if name == "scale":
        return float(param), 0.0, False
    if name == "gauss":
        return 1.0, float(param), False
    if name == "nonfinite":
        return 1.0, 0.0, True
    raise ValueError(f"unknown byz kind {kind!r}")


def plan_arrays(schedule: FaultSchedule, round_idx: int,
                ranks) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side attack plan for one round over cross-silo ``ranks``:
    ``(mult[C], std[C], nonfinite[C])`` numpy arrays (honest clients get
    the identity row 1/0/False). Pure function of (schedule seed, round,
    rank) — replays identically in any process."""
    mult, std, nan = [], [], []
    for r in np.asarray(ranks):
        m, s, n = kind_params(schedule.byzantine_kind(round_idx, int(r)))
        mult.append(m)
        std.append(s)
        nan.append(n)
    return (np.asarray(mult, np.float32), np.asarray(std, np.float32),
            np.asarray(nan, bool))


def attack_keys(seed: int, round_idx: int, ranks) -> jax.Array:
    """[C] stacked PRNG keys for the round's Gaussian attack noise, one
    per cross-silo rank — ``fold_in(fold_in(key(seed+salt), round),
    rank)``, identical to what ``attack_update`` derives client-side."""
    base = jax.random.fold_in(jax.random.key(int(seed) + _KEY_SALT),
                              round_idx + 1)
    return jax.vmap(lambda r: jax.random.fold_in(base, r))(
        jnp.asarray(np.asarray(ranks), jnp.uint32))


def apply_attack(update: PyTree, reference: PyTree, mult, std, nonfinite,
                 key) -> PyTree:
    """One client's attacked upload (trace-safe; scalars may be traced).
    ``reference`` is the round's broadcast model the delta is taken
    against; each leaf gets its own fold_in(key, leaf_index) noise
    stream so leaf shapes never alias draws.

    Honest rows (the identity plan 1/0/False) pass through BITWISE
    untouched via a select, not by computing ``ref + (u − ref)`` — so a
    round driven with an all-honest plan is bit-identical to one driven
    with no plan at all (the fused-window pins rely on it)."""
    u_leaves, treedef = jax.tree.flatten(update)
    r_leaves = treedef.flatten_up_to(reference)
    mult = jnp.asarray(mult, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    bad = jnp.asarray(nonfinite, bool)
    active = (mult != jnp.float32(1.0)) | (std != jnp.float32(0.0)) | bad
    out = []
    for i, (u, r) in enumerate(zip(u_leaves, r_leaves)):
        u32 = jnp.asarray(u, jnp.float32)
        ref32 = jnp.asarray(r, jnp.float32)
        noise = jax.random.normal(jax.random.fold_in(key, i), u32.shape,
                                  jnp.float32)
        y = ref32 + (u32 - ref32) * mult + std * noise
        y = jnp.where(bad, jnp.float32(jnp.nan), y)
        out.append(jnp.where(active, y, u32).astype(
            jnp.asarray(u).dtype))
    return jax.tree.unflatten(treedef, out)


def apply_attack_stacked(stacked_update: PyTree, reference: PyTree,
                         mult, std, nonfinite, keys) -> PyTree:
    """Vmapped ``apply_attack`` over the leading client axis of a
    stacked upload tree (the engines' round-body integration point);
    ``reference`` is the unstacked broadcast model."""
    return jax.vmap(
        lambda u, m, s, b, k: apply_attack(u, reference, m, s, b, k),
        in_axes=(0, 0, 0, 0, 0))(stacked_update, mult, std, nonfinite,
                                 keys)


def attack_update(schedule: FaultSchedule, seed: int, round_idx: int,
                  rank: int, update: PyTree,
                  reference: PyTree) -> PyTree:
    """Cross-silo client hook: returns ``update`` transformed per this
    rank's scheduled kind (or unchanged when honest this round). Runs
    the SAME jax math as the simulated engines' vmapped path — Gaussian
    draws included — so one seed produces one attack trace in both
    federations. Output leaves are host numpy (the upload payload)."""
    kind = schedule.byzantine_kind(round_idx, rank)
    if kind is None:
        return update
    mult, std, bad = kind_params(kind)
    base = jax.random.fold_in(jax.random.key(int(seed) + _KEY_SALT),
                              round_idx + 1)
    key = jax.random.fold_in(base, jnp.uint32(rank))
    attacked = apply_attack(update, reference, mult, std, bad, key)
    return jax.tree.map(np.asarray, attacked)
