"""Deterministic fault injection + the fault-tolerant control-plane pieces.

Two halves (ISSUE 2):

- ``schedule``: a seeded :class:`FaultSchedule` — a pure function of
  ``(seed, round, rank)`` describing client crashes, straggler delays,
  message drops/duplicates and mid-frame disconnects. The same schedule
  drives the simulated engines (``engines/base.py`` survivor sampling,
  DisPFL's activity draw) and the multiprocess federation, so one config
  seed replays an identical fault trace everywhere.
- ``chaos``: :class:`FaultyCommManager`, a wrapper applying the schedule
  to any ``BaseCommManager`` (socket or broker transport) without
  touching transport code.

The tolerance the chaos forces (deadline + quorum aggregation, heartbeat
suspicion, rejoin, stale/duplicate rejection) lives in
``distributed/cross_silo.py``; this package only *produces* failures.
"""

from neuroimagedisttraining_tpu.faults.schedule import (
    BYZ_KINDS,
    FaultSchedule,
    FaultSpec,
    activity_mask,
    parse_byz_kind,
    parse_fault_spec,
)
from neuroimagedisttraining_tpu.faults.chaos import FaultyCommManager

__all__ = [
    "BYZ_KINDS",
    "FaultSchedule",
    "FaultSpec",
    "FaultyCommManager",
    "activity_mask",
    "parse_byz_kind",
    "parse_fault_spec",
]
