"""``FaultyCommManager``: apply a :class:`FaultSchedule` to any transport.

A decorator over the ``BaseCommManager`` 5-method contract
(distributed/comm.py): the wrapped transport's code is untouched — the
wrapper sits between the manager and its observers on the receive side
and in front of ``send_message`` on the send side, and consults the
schedule for every protocol message.

Fault semantics:

- **crash** — from the crash round on, the peer goes silent: inbound
  dispatch stops (the manager's blocking loop returns, so the owning
  process/thread winds down exactly like a real death) and every send is
  swallowed. Peers observe the same thing a SIGKILL produces: no more
  frames, no FIN handshake at the protocol level. The latch is
  PERMANENT by design — a ``rejoin:`` directive cannot revive a wound-
  down process, so ``distributed/run.py`` rejects rejoin specs at
  startup; deterministic rejoin lives where a "process" is cheap to
  resurrect (the asyncfl load harness's simulated clients, or a
  replacement OS process using the server's late re-register path).
- **straggle** — outbound sends sleep the scheduled delay first.
- **drop** — the send silently never happens.
- **duplicate** — the frame is sent twice (the server's round-tagged
  dedup must make this harmless).
- **disconnect** — the frame is torn mid-write: on the socket transport
  a short-lived connection sends a length prefix promising more bytes
  than follow, then closes (the receiver's ``_recv_exact`` sees EOF and
  drops the partial frame); transports without per-frame connections
  degrade to a drop — the observable outcome (message lost) is the same.

Determinism: per-message draws are indexed by ``(round, rank,
crc32(msg_type), seq-within-type)``, so the decision for e.g. the
round-3 model upload does not depend on how many timing-dependent
heartbeats preceded it. Heartbeats (liveness signals) are exempt from
drop/dup/disconnect — their loss is modeled by ``crash``.
"""

from __future__ import annotations

import logging
import socket
import time
import zlib

from neuroimagedisttraining_tpu.distributed import message as M
from neuroimagedisttraining_tpu.distributed.comm import (
    BaseCommManager,
    Observer,
)
from neuroimagedisttraining_tpu.faults.schedule import FaultSchedule

log = logging.getLogger("neuroimagedisttraining_tpu.faults")


class FaultyCommManager(BaseCommManager, Observer):
    """Wrap ``inner`` so every message it sends/receives is subject to
    ``schedule``'s events for ``rank``. Registers itself as the inner
    manager's only observer and re-dispatches to its own observers."""

    def __init__(self, inner: BaseCommManager, schedule: FaultSchedule,
                 rank: int):
        self.inner = inner
        self.schedule = schedule
        self.rank = int(rank)
        self.crashed = False
        self._round = 0             # last round seen on any tagged message
        self._seq: dict[tuple[int, int], int] = {}  # (round, type-crc) -> next seq
        self._observers: list[Observer] = []
        inner.add_observer(self)

    # ---- receive side (Observer over the inner transport) ----

    def receive_message(self, msg_type: str, msg: M.Message) -> None:
        r = msg.get(M.ARG_ROUND_IDX)
        if r is not None:
            self._round = max(self._round, int(r))
        if self.schedule.crashed(self._round, self.rank):
            self._die()
            return
        for obs in list(self._observers):
            obs.receive_message(msg_type, msg)

    def _die(self) -> None:
        if self.crashed:
            return
        self.crashed = True
        log.warning("rank %d: simulated crash at round %d (%s)",
                    self.rank, self._round, self.schedule.describe())
        # stop inbound dispatch: the owning manager's blocking loop
        # returns and the process/thread winds down like a real death
        self.inner.stop_receive_message()

    # ---- send side ----

    def _next_seq(self, round_idx: int, msg_type: str) -> int:
        key = (round_idx, zlib.crc32(msg_type.encode()))
        seq = self._seq.get(key, 0)
        self._seq[key] = seq + 1
        return seq

    def send_message(self, msg: M.Message, **kw) -> None:
        if self.crashed:
            return
        r = msg.get(M.ARG_ROUND_IDX)
        round_idx = int(r) if r is not None else self._round
        if self.schedule.crashed(round_idx, self.rank):
            self._die()
            return
        if msg.msg_type in (M.MSG_TYPE_C2S_HEARTBEAT,
                            M.MSG_TYPE_C2S_REGISTER):
            # heartbeats bypass message-level chaos (their count is
            # timing-dependent; including them would break seq
            # determinism — losing them is modeled by crash). So does
            # registration: a real client retries registering until
            # acknowledged, and dropping the one-shot register frame
            # would deadlock the strict start barrier rather than model
            # an interesting failure.
            self.inner.send_message(msg, **kw)
            return
        seq = self._next_seq(round_idx, msg.msg_type)
        if self.schedule.drop(round_idx, self.rank, seq):
            log.warning("rank %d: dropping %s (round %d seq %d)",
                        self.rank, msg.msg_type, round_idx, seq)
            return
        delay = self.schedule.straggle_seconds(round_idx, self.rank)
        if delay > 0:
            time.sleep(delay)
        if self.schedule.disconnect(round_idx, self.rank, seq):
            log.warning("rank %d: mid-frame disconnect on %s "
                        "(round %d seq %d)", self.rank, msg.msg_type,
                        round_idx, seq)
            self._send_truncated(msg)
            return
        self.inner.send_message(msg, **kw)
        if self.schedule.duplicate(round_idx, self.rank, seq):
            log.warning("rank %d: duplicating %s (round %d seq %d)",
                        self.rank, msg.msg_type, round_idx, seq)
            self.inner.send_message(msg, **kw)

    def _send_truncated(self, msg: M.Message) -> None:
        """Socket transport: write half a frame then slam the connection
        shut — the receiver's listener must survive (comm.py drops the
        partial frame). Transports without per-frame connections (broker)
        degrade to a plain drop."""
        host_map = getattr(self.inner, "host_map", None)
        base_port = getattr(self.inner, "base_port", None)
        if host_map is None or base_port is None:
            return  # pub/sub stream: tearing it would desync ALL topics
        frame = M.frame_bytes(msg)  # prefix promises more than we send
        addr = (host_map[msg.receiver_id], base_port + msg.receiver_id)
        try:
            with socket.create_connection(addr, timeout=5.0) as conn:
                conn.sendall(frame[: 8 + max(1, (len(frame) - 8) // 2)])  # nidt: allow[lock-send] -- fault injection writes a deliberately torn frame on a fresh per-call connection; no concurrent writer exists
        except OSError:
            pass  # receiver gone — the message is lost either way

    # ---- delegated contract ----

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        self.inner.stop_receive_message()
