"""Experiment harness: ``python -m neuroimagedisttraining_tpu ...``.

Replaces the reference's per-algorithm ``main_<algo>.py`` entry points
(fedml_experiments/standalone/sailentgrads/main_sailentgrads.py:31-281)
with ONE CLI: ``--algorithm`` selects the engine, the flag surface keeps
the reference's names and defaults (add_args, main_sailentgrads.py:31-127;
Ditto lamda/local_epochs main_ditto.py:79,101; SubAvg
each_prune_ratio/dist_thresh/acc_thresh main_subavg.py:105-108), and the
run follows the reference harness contract: deterministic seeding
(main_sailentgrads.py:264-268), experiment-identity string, file logging
under ``LOG/<dataset>/`` (main_sailentgrads.py:184-192), then
``engine.train()``.

Example (fast smoke):
    python -m neuroimagedisttraining_tpu --algorithm fedavg \
        --dataset synthetic --model 3dcnn_tiny --synthetic_num_subjects 32 \
        --synthetic_shape 12 14 12 --client_num_in_total 4 --comm_round 2 \
        --batch_size 4 --epochs 1
"""

from __future__ import annotations

import argparse
import json
import random
import sys

import numpy as np

from neuroimagedisttraining_tpu.config import (
    DataConfig, ExperimentConfig, FedConfig, OptimConfig, SparsityConfig,
)


def add_args(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    # reference flag surface (main_sailentgrads.py:31-127)
    parser.add_argument("--algorithm", type=str, default="fedavg",
                        help="fedavg | fedprox | salientgrads | dispfl | "
                             "subavg | fedfomo | dpsgd | ditto | local | "
                             "turboaggregate")
    parser.add_argument("--model", type=str, default="3DCNN")
    parser.add_argument("--dataset", type=str, default="ABCD",
                        help="ABCD | abcd_h5 | synthetic | cifar10 | "
                             "cifar100 | tiny")
    parser.add_argument("--data_dir", type=str, default="./data",
                        help="for ABCD/abcd_h5: path to the X/y/site HDF5")
    parser.add_argument("--partition_method", type=str, default="site",
                        help="site | dir | n_cls | my_part | homo | hetero "
                             "| rescale")
    parser.add_argument("--partition_alpha", type=float, default=0.3)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--client_optimizer", type=str, default="sgd")
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--lr_decay", type=float, default=0.998)
    parser.add_argument("--wd", type=float, default=5e-4)
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--grad_clip", type=float, default=10.0,
                        help="global-norm gradient clip (<= 0 disables); "
                             "torch clip_grad_norm_ parity")
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch_order", type=str, default="shuffle",
                        choices=["shuffle", "replacement"],
                        help="minibatch selection: per-epoch shuffled "
                             "strides (reference DataLoader semantics) or "
                             "i.i.d. draws with replacement")
    parser.add_argument("--client_num_in_total", type=int, default=21)
    parser.add_argument("--frac", type=float, default=1.0)
    parser.add_argument("--comm_round", type=int, default=200)
    parser.add_argument("--frequency_of_the_test", type=int, default=1)
    parser.add_argument("--ci", type=int, default=0)
    parser.add_argument("--seed", type=int, default=1024)
    parser.add_argument("--seed_split", type=int, default=42,
                        help="per-site 80/20 train/val split seed "
                             "(independent of --seed so reshuffling "
                             "training noise keeps the split fixed)")
    parser.add_argument("--cs", type=str, default="random")
    parser.add_argument("--neighbor_num", type=int, default=5,
                        help="gossip fan-out when --cs random")
    parser.add_argument("--active", type=float, default=1.0)
    parser.add_argument("--fault_spec", type=str, default="",
                        help="deterministic fault schedule (faults/): "
                             "'crash:RANK@ROUND,crash_prob:P,"
                             "straggle:P:MAX_S,drop:P,dup:P,disconnect:P,"
                             "byz:RANK@ROUND:KIND,byz_prob:P[:KIND]' "
                             "— crashed clients leave the sampled cohort "
                             "(survivor-reweighted rounds); byz clients "
                             "upload KIND-corrupted values (sign_flip | "
                             "scale:K | gauss:STD | nonfinite, "
                             "faults/adversary.py); the same seed "
                             "drives the multiprocess federation")
    parser.add_argument("--wire_codec", type=str, default="none",
                        help="model-update wire codec (codec/): '+'-"
                             "joined stages from {delta, sparse, quant, "
                             "quant16}, e.g. delta+sparse+quant; the "
                             "simulated round applies the codec's lossy "
                             "transform to client updates before "
                             "aggregation (jitted) and accounts encoded "
                             "vs dense bytes in stat_info — parity with "
                             "what distributed.run ships on real "
                             "sockets")
    parser.add_argument("--wire_topk_ratio", type=float, default=0.25,
                        help="wire codec sparse stage for dense engines: "
                             "magnitude top-k keep fraction (per-client "
                             "error feedback re-injects dropped mass "
                             "next round); masked engines use their own "
                             "mask instead")
    parser.add_argument("--round_deadline", type=float, default=0.0,
                        help="cross-silo per-round deadline seconds "
                             "(distributed.run); recorded in the config "
                             "for parity with the multiprocess runner")
    parser.add_argument("--quorum", type=int, default=0,
                        help="min survivor uploads for a deadline round "
                             "to aggregate (0 = all clients)")
    parser.add_argument("--heartbeat_interval", type=float, default=0.0,
                        help="cross-silo clients: liveness beat period "
                             "seconds (0 = off); recorded in the config "
                             "for parity with distributed.run")
    parser.add_argument("--heartbeat_timeout", type=float, default=0.0,
                        help="cross-silo server: mark clients suspect "
                             "once their heartbeat is older than this "
                             "(0 = off)")
    parser.add_argument("--async_server", action="store_true",
                        help="cross-silo server runs the FedBuff-style "
                             "buffered asynchronous control plane "
                             "(asyncfl/, distributed.run): uploads "
                             "aggregate every --buffer_k arrivals with "
                             "staleness weighting instead of a round "
                             "barrier; recorded in the config for "
                             "parity with the multiprocess runner")
    parser.add_argument("--buffer_k", type=int, default=0,
                        help="async server: aggregate every K accepted "
                             "uploads (0 = cohort size, which with zero "
                             "staleness reproduces the synchronous "
                             "server bitwise)")
    parser.add_argument("--staleness_alpha", type=float, default=0.5,
                        help="async server: polynomial staleness weight "
                             "(1 + tau)^-alpha on upload sample counts "
                             "(0 disables down-weighting)")
    parser.add_argument("--max_staleness", type=int, default=20,
                        help="async server: drop uploads based on a "
                             "version more than this many aggregations "
                             "old (also bounds the codec delta-"
                             "reference ring)")
    parser.add_argument("--tag", type=str, default="exp")
    parser.add_argument("--num_classes", type=int, default=1)
    # sparsity family
    parser.add_argument("--dense_ratio", type=float, default=0.5)
    parser.add_argument("--anneal_factor", type=float, default=0.5)
    parser.add_argument("--erk_power_scale", type=float, default=1.0)
    parser.add_argument("--uniform", action="store_true")
    parser.add_argument("--static", action="store_true")
    parser.add_argument("--dis_gradient_check", action="store_true")
    parser.add_argument("--different_initial", action="store_true")
    parser.add_argument("--diff_spa", action="store_true")
    parser.add_argument("--save_masks", action="store_true")
    # SalientGrads (note: the reference's `--snip_mask type=bool` makes any
    # string truthy, main_sailentgrads.py:125; we use an explicit off switch)
    parser.add_argument("--no_snip_mask", action="store_true",
                        help="dense escape hatch (snip_mask=False)")
    parser.add_argument("--itersnip_iteration", type=int, default=1)
    parser.add_argument("--stratified_sampling", action="store_true")
    # Ditto (main_ditto.py:79,101)
    parser.add_argument("--lamda", type=float, default=0.5)
    parser.add_argument("--local_epochs", type=int, default=1)
    # Sub-FedAvg (main_subavg.py:105-108)
    parser.add_argument("--each_prune_ratio", type=float, default=0.1)
    parser.add_argument("--dist_thresh", type=float, default=0.001)
    parser.add_argument("--acc_thresh", type=float, default=0.5)
    # FedFomo
    parser.add_argument("--fomo_m", type=int, default=5)
    parser.add_argument("--val_fraction", type=float, default=0.0)
    # robust aggregation (RobustAggregator args, robust_aggregation.py:32-36)
    parser.add_argument("--mpc_n_shares", type=int, default=3,
                        help="TurboAggregate: additive shares per client "
                             "update")
    parser.add_argument("--mpc_frac_bits", type=int, default=16,
                        help="TurboAggregate: fixed-point fraction bits "
                             "for GF(p) quantization")
    parser.add_argument("--mpc_backend", type=str, default="device",
                        choices=("device", "host"),
                        help="TurboAggregate MPC stage: 'device' (jitted "
                             "uint32 mod-p on the accelerator, default) | "
                             "'host' (numpy path modeling the "
                             "client<->server boundary)")
    # privacy plane (privacy/, ISSUE 8)
    parser.add_argument("--secure_quant", action="store_true",
                        help="secure QUANTIZED aggregation "
                             "(privacy/secure_quant.py): the simulated "
                             "round aggregates through the jitted GF(p) "
                             "integer-weight fold (the builder's codec-"
                             "family stage, engines/program.py — bitwise "
                             "the host SlotAccumulator fold), so round "
                             "metrics reflect exactly what the encoded "
                             "secure wire would deliver; the wire itself "
                             "lives on the cross-silo/async planes "
                             "(distributed.run). Needs "
                             "--secure_quant_field_bits 32 (the one-"
                             "phase capacity bound)")
    parser.add_argument("--secure_quant_field_bits", type=int, default=16,
                        choices=(8, 16, 32),
                        help="secure_quant field width: p = largest prime "
                             "below 2^bits (the wire ships one uintN "
                             "residue per parameter)")
    parser.add_argument("--secure_quant_frac_bits", type=int, default=10,
                        help="secure_quant fixed-point fraction bits; the "
                             "aggregate range value_bound * 2^frac_bits "
                             "must stay inside p/2 (checked at startup)")
    parser.add_argument("--dp_clip", type=float, default=0.0,
                        help="dpsgd round-level DP: clip each client's "
                             "update delta (vs its consensus point) to "
                             "this L2 bound before it reaches any "
                             "neighbor (0 = off)")
    parser.add_argument("--dp_sigma", type=float, default=0.0,
                        help="dpsgd round-level DP: Gaussian noise "
                             "multiplier — noise stddev is dp_sigma * "
                             "dp_clip, drawn inside the jitted round "
                             "from config-folded jax keys; the RDP "
                             "accountant (privacy/accountant.py) reports "
                             "the running per-silo (epsilon, dp_delta) "
                             "in stat_info (0 = off; requires --dp_clip)")
    parser.add_argument("--dp_delta", type=float, default=1e-5,
                        help="target delta for the RDP -> (epsilon, "
                             "delta) conversion (dpsgd DP and the "
                             "weak_dp defense accountant)")
    parser.add_argument("--defense_type", "--defense", dest="defense_type",
                        type=str, default="none",
                        help="none | norm_diff_clipping | weak_dp | "
                             "trimmed_mean | median | krum | multi_krum | "
                             "geometric_median — the clip family applies "
                             "per client before the weighted mean "
                             "(reference RobustAggregator parity); the "
                             "order-statistic family (core/robust.py, "
                             "ISSUE 5) replaces the mean and tolerates "
                             "up to --byz_f Byzantine clients. Runs "
                             "inside the jitted round body, so fused "
                             "--rounds_per_dispatch windows stay bitwise-"
                             "equal to the sequential loop")
    parser.add_argument("--norm_bound", type=float, default=5.0)
    parser.add_argument("--stddev", type=float, default=0.05)
    parser.add_argument("--byz_f", type=int, default=1,
                        help="assumed Byzantine client count f for the "
                             "order-statistic defenses: trim depth per "
                             "side (trimmed_mean), Krum neighborhood "
                             "(sampled cohort must be >= f + 3; "
                             "trimmed_mean/median need 2f < n)")
    parser.add_argument("--geomed_iters", type=int, default=8,
                        help="geometric_median: fixed Weiszfeld "
                             "iteration count (trace-static)")
    # 3D-model rematerialization policy (PROFILE.md)
    parser.add_argument("--remat", type=str, default="auto",
                        help="auto | none | stem | all")
    # mixed-precision train step (ISSUE 10, core/optim.py)
    parser.add_argument("--precision", type=str, default="fp32",
                        choices=("fp32", "bf16_mixed"),
                        help="train-step compute dtype: fp32 (bitwise-"
                             "identical to the legacy tree) | bf16_mixed "
                             "(bf16 compute/activations, fp32 MASTER "
                             "weights + momentum + loss; checkpoints and "
                             "every aggregation/codec/secure plane see "
                             "only the fp32 master weights)")
    parser.add_argument("--loss_scale", type=float, default=1.0,
                        help="fixed loss-scale constant for bf16_mixed "
                             "(static scaling: loss * S before grad, "
                             "f32 grads / S after); 1.0 = off — the "
                             "pinned default, since bf16 keeps f32's "
                             "exponent range. Rejected under fp32")
    parser.add_argument("--fused_update", action="store_true",
                        help="fuse the SGD tail (global-norm clip + "
                             "weight decay + momentum + lr update + "
                             "sparse-mask re-apply) into one Pallas "
                             "pass over the params "
                             "(ops/fused_update.py; XLA fallback off-"
                             "TPU, bit-parity with the unfused chain "
                             "pinned). SGD only")
    # synthetic data knobs (tests / demos without the private cohort)
    parser.add_argument("--synthetic_num_subjects", type=int, default=256)
    parser.add_argument("--synthetic_shape", type=int, nargs=3,
                        default=[121, 145, 121])
    parser.add_argument("--synthetic_signal", type=float, default=12.0,
                        help="class-signal amplitude of the synthetic "
                             "cohort (vs sigma-8 voxel noise); lower = "
                             "harder task")
    # infra
    parser.add_argument("--log_dir", type=str, default="LOG")
    parser.add_argument("--streaming", action="store_true",
                        help="host-stream the cohort per round instead of "
                             "keeping it device-resident (cohorts > HBM); "
                             "supported by all ten algorithms (fedfomo "
                             "additionally needs --val_fraction > 0: its "
                             "small val shards stay resident)")
    parser.add_argument("--stream_chunk_clients", type=int, default=0,
                        help="clients per host-fetched chunk in streaming "
                             "eval / SNIP scoring / chunked DisPFL rounds "
                             "(0 = auto)")
    parser.add_argument("--checkpoint_dir", type=str, default="")
    parser.add_argument("--checkpoint_every", type=int, default=0)
    parser.add_argument("--multihost_coordinator", type=str, default="",
                        help="host:port of process 0; joins this process "
                             "to a multi-host JAX runtime (TPU pod) before "
                             "mesh construction (jax.distributed)")
    parser.add_argument("--process_id", type=int, default=0,
                        help="this process's rank in the multi-host "
                             "runtime")
    parser.add_argument("--num_processes", type=int, default=1,
                        help="total processes in the multi-host runtime")
    parser.add_argument("--virtual_devices", type=int, default=0,
                        help="provision N virtual CPU devices (mesh "
                             "simulation without TPU hardware)")
    parser.add_argument("--mesh_shape", type=int, nargs="*", default=[],
                        help="device mesh layout: one value = first-N 1-D "
                             "clients mesh; two values (silos cores) = "
                             "two-level cross-silo mesh (silo aggregation "
                             "on ICI, cross-silo on DCN)")
    parser.add_argument("--profile_dir", type=str, default="",
                        help="capture a jax.profiler trace of training "
                             "into this dir (TensorBoard-loadable)")
    # observability plane (obs/, ISSUE 9)
    parser.add_argument("--trace_out", type=str, default="",
                        help="write the run's host-span timeline "
                             "(round/window/eval spans at dispatch "
                             "boundaries) as Chrome trace-event JSON, "
                             "Perfetto-loadable (obs/trace.py); with "
                             "--profile_dir each span also opens a "
                             "jax.profiler.TraceAnnotation so host "
                             "spans line up with the XLA timeline. "
                             "Multi-process planes (distributed/run.py "
                             "--ingest_workers) treat the bare path as "
                             "the MERGED trace and suffix per-process "
                             "secondaries .wN (obs/fanin.py)")
    parser.add_argument("--metrics_port", type=int, default=0,
                        help="serve /metrics (Prometheus text "
                             "exposition of the obs registry: stat_info "
                             "accumulators, round metrics, DP epsilon) "
                             "+ /healthz during training (obs/http.py); "
                             "0 = off. The endpoint is unauthenticated "
                             "— bind scope via --metrics_host")
    parser.add_argument("--metrics_host", type=str, default="0.0.0.0",
                        help="interface the metrics endpoint binds "
                             "(default all interfaces; pass 127.0.0.1 "
                             "on shared hosts)")
    parser.add_argument("--profile_session", type=str, default="",
                        help="run the declarative profile session "
                             "(obs/probe.py: PROFILE.md's probe "
                             "checklist as a manifest through the "
                             "shipped driver with the dispatch-boundary "
                             "profiler armed) and write the machine-"
                             "readable artifact here instead of "
                             "training; PROFILE_MODEL/PROFILE_SHAPE/"
                             "PROFILE_BATCH env size the cells and "
                             "--profile_manifest replaces the probe "
                             "list. scripts/run_profile_session.sh is "
                             "the push-button wrapper")
    parser.add_argument("--profile_manifest", type=str, default="",
                        help="JSON probe manifest for "
                             "--profile_session (a [{name, cell}] "
                             "array; default: obs/probe.py's declared "
                             "list)")
    parser.add_argument("--peak_flops", type=float, default=0.0,
                        help="device peak flop/s for the nidt_mfu "
                             "gauge's denominator (total across local "
                             "devices); 0 = the obs/compute.py device-"
                             "kind estimate (NIDT_PEAK_FLOPS env also "
                             "overrides; unknown backends publish "
                             "sustained TFLOP/s only)")
    parser.add_argument("--flight_events", type=int, default=256,
                        help="flight-recorder ring capacity "
                             "(obs/flight.py); the ring dumps to "
                             "LOG/<dataset>/<identity>.flight.json on "
                             "any fatal failure (failure_context)")
    # training-health plane (obs/health.py + obs/rules.py, ISSUE 15)
    parser.add_argument("--health_stats", action="store_true",
                        help="arm the in-dispatch federation-"
                             "statistics leg on every declared round "
                             "program (engines/program.py): per-client "
                             "update L2 norms, cosine-to-aggregate, "
                             "update-norm dispersion, global param/"
                             "update norms and mask health, computed "
                             "INSIDE the jitted round and fetched only "
                             "in the existing batched host-boundary "
                             "device_get — armed rounds are bitwise-"
                             "identical to disarmed ones, published as "
                             "nidt_health_* on /metrics")
    parser.add_argument("--health_rules", type=str, default="",
                        help="JSON manifest of anomaly rules "
                             "(obs/rules.py: metric selector, window, "
                             "comparator, threshold, severity, "
                             "for_rounds debounce) extending the "
                             "built-in set (same-named rules "
                             "override); unknown metric names fail at "
                             "startup against the declared-name list "
                             "(obs/names.py)")
    parser.add_argument("--health_gate", action="store_true",
                        help="exit nonzero when the run's WORST health "
                             "status was not ok (any anomaly rule "
                             "fired), after writing the machine-"
                             "readable verdict to "
                             "LOG/<dataset>/<identity>.health.json — "
                             "the CI spelling of 'this run trained "
                             "healthily'")
    parser.add_argument("--metrics_out", type=str, default="",
                        help="append one metrics-registry JSONL record "
                             "per round at the engine host boundary, "
                             "each with monotonic round/seq join keys "
                             "(obs/metrics.py dump_jsonl) — the sink "
                             "analysis/run_report.py joins with the "
                             "flight dump and health verdict")
    parser.add_argument("--actions", type=str, default="dry_run",
                        choices=("off", "dry_run", "on"),
                        help="reflex plane (obs/actions.py, ISSUE 20): "
                             "what a firing health rule's declared "
                             "action DOES. off = rules only observe; "
                             "dry_run (default) = every would-fire "
                             "dispatch is logged and flight-recorded "
                             "with its rule as provenance but nothing "
                             "changes; on = actions apply (quarantine "
                             "the diverging silo, escalate the "
                             "defense ladder, adapt the async buffer, "
                             "freeze-and-rollback to the last healthy "
                             "state)")
    parser.add_argument("--dp_epsilon_budget", type=float, default=0.0,
                        help="epsilon budget the built-in DP health "
                             "rules judge against (obs/rules.py): "
                             "dp-budget-exceeded fires critical once "
                             "the running epsilon crosses it, "
                             "dp-burn-rate warns when a round burns "
                             "over 2x the uniform budget/comm_round "
                             "rate; 0 = no budget rules")
    parser.add_argument("--compile_cache", "--compile_cache_dir",
                        dest="compile_cache_dir", type=str, default=None,
                        help="persistent XLA compile cache dir (repeat "
                             "experiments skip the ~30s 3D-CNN round "
                             "compile); unset falls back to "
                             "$NIDT_COMPILE_CACHE, then "
                             "/tmp/nidt_jax_cache; empty string disables")
    parser.add_argument("--client_mesh", type=int, default=0,
                        help="shard the sampled-client axis of every "
                             "jitted round program over a client mesh of "
                             "exactly N devices (parallel/cohort.py): "
                             "per-device local training on client "
                             "shards, aggregation on all-gathered "
                             "stacks, bitwise-equal to the unsharded "
                             "round; non-tiling cohorts (21 sites on 8 "
                             "devices) pad with zero-weight rows. "
                             "Engines/modes without a declared sharded "
                             "round body (engines/program.py) fall back "
                             "with a logged + counted reason "
                             "(nidt_fallback_total on /metrics). "
                             "Combine with --virtual_devices N to "
                             "simulate without TPU hardware")
    parser.add_argument("--rounds_per_dispatch", type=int, default=1,
                        help="fuse up to K rounds into ONE lax.scan "
                             "dispatch when the federation is resident "
                             "and host-free between rounds (sampling/rng/"
                             "lr precomputed per round; eval/checkpoint "
                             "hooks fire at window boundaries). The "
                             "round-program builder (engines/program.py) "
                             "compiles the window for every engine with "
                             "declared stages — fedavg/fedprox/"
                             "salientgrads/ditto/dpsgd/subavg; engines "
                             "that cross the host each round fall back "
                             "to 1 with a logged + counted reason "
                             "(nidt_fallback_total)")
    parser.add_argument("--recipe", type=str, default="",
                        help="apply a committed autotune recipe "
                             "(tune/recipe.py) as config DEFAULTS "
                             "before any conflict check: a path to "
                             "bench_matrix/recipes/<device_kind>.json, "
                             "or 'auto' to resolve the committed recipe "
                             "for the visible device kind at startup. "
                             "Explicit CLI flags win over recipe values "
                             "(each override is logged + counted via "
                             "nidt_fallback_total{plane='recipe'}); a "
                             "truncated/tampered/mismatched recipe dies "
                             "at argparse. Loading a recipe also arms "
                             "the mfu-below-recipe drift rule "
                             "(obs/rules.py) against the recipe's "
                             "recorded score")
    return parser


def config_from_args(args: argparse.Namespace) -> ExperimentConfig:
    return ExperimentConfig(
        model=args.model, num_classes=args.num_classes,
        algorithm=args.algorithm, seed=args.seed, tag=args.tag,
        mesh_shape=tuple(args.mesh_shape),
        data=DataConfig(
            dataset=args.dataset.lower(), data_dir=args.data_dir,
            partition_method=args.partition_method,
            partition_alpha=args.partition_alpha,
            synthetic_num_subjects=args.synthetic_num_subjects,
            synthetic_shape=tuple(args.synthetic_shape),
            synthetic_signal=args.synthetic_signal,
            val_fraction=args.val_fraction,
            seed_split=args.seed_split),
        optim=OptimConfig(
            client_optimizer=args.client_optimizer, lr=args.lr,
            lr_decay=args.lr_decay, wd=args.wd, momentum=args.momentum,
            grad_clip=args.grad_clip,
            batch_size=args.batch_size, epochs=args.epochs,
            batch_order=args.batch_order,
            precision=args.precision, loss_scale=args.loss_scale,
            fused_update=args.fused_update),
        fed=FedConfig(
            client_num_in_total=args.client_num_in_total, frac=args.frac,
            comm_round=args.comm_round, cs=args.cs, active=args.active,
            neighbor_num=args.neighbor_num,
            fault_spec=args.fault_spec,
            wire_codec=args.wire_codec,
            wire_topk_ratio=args.wire_topk_ratio,
            round_deadline=args.round_deadline, quorum=args.quorum,
            heartbeat_interval=args.heartbeat_interval,
            heartbeat_timeout=args.heartbeat_timeout,
            async_server=args.async_server, buffer_k=args.buffer_k,
            staleness_alpha=args.staleness_alpha,
            max_staleness=args.max_staleness,
            lamda=args.lamda, local_epochs=args.local_epochs,
            fomo_m=args.fomo_m, mpc_n_shares=args.mpc_n_shares,
            mpc_frac_bits=args.mpc_frac_bits, mpc_backend=args.mpc_backend,
            secure_quant=args.secure_quant,
            secure_quant_field_bits=args.secure_quant_field_bits,
            secure_quant_frac_bits=args.secure_quant_frac_bits,
            dp_clip=args.dp_clip, dp_sigma=args.dp_sigma,
            dp_delta=args.dp_delta,
            dp_epsilon_budget=args.dp_epsilon_budget,
            defense_type=args.defense_type,
            norm_bound=args.norm_bound, stddev=args.stddev,
            byz_f=args.byz_f, geomed_iters=args.geomed_iters,
            rounds_per_dispatch=args.rounds_per_dispatch,
            client_mesh=args.client_mesh,
            frequency_of_the_test=args.frequency_of_the_test,
            ci=bool(args.ci)),
        sparsity=SparsityConfig(
            dense_ratio=args.dense_ratio, anneal_factor=args.anneal_factor,
            erk_power_scale=args.erk_power_scale, uniform=args.uniform,
            static=args.static, dis_gradient_check=args.dis_gradient_check,
            different_initial=args.different_initial, diff_spa=args.diff_spa,
            snip_mask=not args.no_snip_mask,
            itersnip_iterations=args.itersnip_iteration,
            stratified_sampling=args.stratified_sampling,
            each_prune_ratio=args.each_prune_ratio,
            dist_thresh=args.dist_thresh, acc_thresh=args.acc_thresh,
            save_masks=args.save_masks),
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        remat=args.remat,
        recipe=args.recipe,
        stream_chunk_clients=args.stream_chunk_clients,
        log_dir=args.log_dir,
        trace_out=args.trace_out, metrics_port=args.metrics_port,
        flight_events=args.flight_events,
        health_stats=args.health_stats, health_rules=args.health_rules,
        health_gate=args.health_gate, metrics_out=args.metrics_out,
        actions=args.actions)


def build_experiment(cfg: ExperimentConfig, streaming: bool = False,
                     mesh=None, console: bool = True):
    """Data dispatch (load_data, main_sailentgrads.py:130-160) + model +
    trainer + engine wiring. Returns the ready engine."""
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.data import partition as P
    from neuroimagedisttraining_tpu.data.federate import federate_cohort
    from neuroimagedisttraining_tpu.data.hdf5 import load_abcd_hdf5
    from neuroimagedisttraining_tpu.data.stream import StreamingFederation
    from neuroimagedisttraining_tpu.data.synthetic import generate_synthetic_abcd
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    d = cfg.data
    dataset = d.dataset.lower()
    log = ExperimentLogger(cfg.log_dir, dataset, cfg.identity(),
                           console=console)
    log.info("config: %s", cfg.to_json())

    stream = None
    cohort = None
    if dataset in ("abcd", "abcd_h5"):
        cohort = load_abcd_hdf5(d.data_dir, lazy=streaming)
    elif dataset == "synthetic":
        cohort = generate_synthetic_abcd(
            num_subjects=d.synthetic_num_subjects,
            shape=d.synthetic_shape,
            signal=d.synthetic_signal,
            num_sites=max(4, cfg.fed.client_num_in_total // 4),
            seed=cfg.seed)
    elif dataset in ("cifar10", "cifar100", "tiny", "synthetic_vision"):
        if streaming:
            raise ValueError("streaming mode is for ABCD-scale cohorts")
        from neuroimagedisttraining_tpu.data.vision import federate_vision
        method = d.partition_method if d.partition_method != "site" else "dir"
        fed, info = federate_vision(
            "cifar10" if dataset == "synthetic_vision" else dataset,
            d.data_dir, method, d.partition_alpha,
            cfg.fed.client_num_in_total, mesh=mesh,
            val_fraction=d.val_fraction, seed=cfg.seed,
            synthetic=dataset == "synthetic_vision",
            num_classes=cfg.num_classes if cfg.num_classes > 1 else None)
        log.info("partition: %s", json.dumps(info.get("train_counts")))
    else:
        raise ValueError(
            f"dataset {dataset!r} has no loader (have: abcd/abcd_h5/"
            "synthetic/cifar10/cifar100/tiny/synthetic_vision)")

    if cohort is None:
        pass  # vision federation already built above
    elif streaming:
        if d.partition_method != "site":
            raise ValueError("streaming mode currently partitions by site")
        from neuroimagedisttraining_tpu.data.federate import DATA_SPLIT_SEED

        # same split seed as federate_cohort's resident path: a streamed
        # run must see the SAME train/test/val rows as a resident one
        train_map, test_map, _ = P.site_partition(cohort["site"],
                                                  seed=DATA_SPLIT_SEED)
        # NOTE: a sampled-set size that does not tile the mesh (e.g. the
        # north-star 100 clients at frac 0.1 on 8 devices) is handled by
        # the engines' stream_sampling padding (zero-weight pad clients),
        # so no tiling restriction applies to --frac
        if mesh is not None and cfg.stream_chunk_clients > 0 and \
                cfg.stream_chunk_clients % mesh.devices.size != 0:
            raise ValueError(
                f"--stream_chunk_clients ({cfg.stream_chunk_clients}) must "
                f"be a multiple of the {mesh.devices.size}-device mesh so "
                "each streamed chunk's NamedSharding device_put tiles the "
                "client axis (otherwise XLA rejects the put mid-run)")
        val_map = None
        if d.val_fraction > 0:
            from neuroimagedisttraining_tpu.data.federate import (
                carve_val_split,
            )

            val_map, train_map = carve_val_split(train_map, d.val_fraction,
                                                 seed=DATA_SPLIT_SEED)
        stream = StreamingFederation(cohort["X"], cohort["y"], train_map,
                                     test_map, mesh=mesh, val_map=val_map)
        fed = None
    else:
        fed, info = federate_cohort(
            cohort, partition_method=d.partition_method,
            client_number=cfg.fed.client_num_in_total,
            alpha=d.partition_alpha, mesh=mesh,
            val_fraction=d.val_fraction)
        log.info("partition: %s", json.dumps(info.get("train_counts")))

    # remat policy for the 3D family (PROFILE.md): no-remat is faster
    # (b128 x 1 client/core measured 768 vs 611 samples/s against stem
    # remat, round 3) and up to ~128 full-size fp32 samples fit in
    # flight per chip without it; above that use stem remat (f0+f1 —
    # same speed as full remat, less HBM). The cutoff is precision-
    # aware (core/optim.py REMAT_AUTO_SAMPLES): bf16_mixed stores
    # activations at half the bytes, so the same headroom carries 2x
    # the samples before recompute pays for itself.
    remat: bool | str | None
    if cfg.remat == "auto":
        import jax

        from neuroimagedisttraining_tpu.core.optim import (
            remat_auto_samples_threshold,
        )

        n_dev = max(1, len(jax.devices()) if mesh is None
                    else mesh.devices.size)
        per_dev = -(-cfg.fed.client_num_per_round // n_dev)
        threshold = remat_auto_samples_threshold(cfg.optim.precision)
        remat = (False if per_dev * cfg.optim.batch_size <= threshold
                 else "stem")
    else:
        remat = {"none": False, "stem": "stem", "all": True}[cfg.remat]
    # precision contract (ISSUE 10): the model's flax dtype IS the
    # compute precision; master weights stay f32 (flax param_dtype
    # default), so every plane outside the jitted step — aggregation,
    # codec, secure, checkpoints — sees float32 regardless
    from neuroimagedisttraining_tpu.core.optim import compute_dtype

    model = create_model(cfg.model, num_classes=cfg.num_classes, remat=remat,
                         dtype=compute_dtype(cfg.optim.precision))
    trainer = LocalTrainer(model, cfg.optim, num_classes=cfg.num_classes)
    return create_engine(cfg.algorithm, cfg, fed, trainer, mesh=mesh,
                         logger=log, stream=stream)


def main(argv: list[str] | None = None) -> int:
    parser = add_args(argparse.ArgumentParser(
        prog="neuroimagedisttraining_tpu"))
    args = parser.parse_args(argv)

    # virtual devices provision BEFORE any backend touch — including
    # the --recipe auto device-kind resolution just below
    if args.virtual_devices:
        from neuroimagedisttraining_tpu.parallel.mesh import (
            provision_virtual_devices,
        )
        provision_virtual_devices(args.virtual_devices)

    # autotune recipe (ISSUE 19, tune/recipe.py): applied as config
    # DEFAULTS before the conflict checks below, so a recipe knob that
    # conflicts with an explicit flag dies at argparse exactly like a
    # hand-spelled config; explicit flags win with a logged + counted
    # override (nidt_fallback_total{plane="recipe"})
    recipe_doc = None
    if args.recipe:
        from neuroimagedisttraining_tpu.tune import recipe as tune_recipe

        try:
            recipe_doc = tune_recipe.resolve_and_load(args.recipe)
            tune_recipe.apply_recipe(
                args, recipe_doc,
                argv if argv is not None else sys.argv[1:])
        except (OSError, ValueError) as e:
            parser.error(f"--recipe: {e}")

    # privacy-plane flag conflicts die AT ARGPARSE with the resolution
    # named (ISSUE 8 satellite) — the engine constructors reject these
    # too, but only after the data/model build, deep in a stack trace
    if args.algorithm.lower() == "turboaggregate":
        from neuroimagedisttraining_tpu.core import robust

        if args.wire_codec not in ("", "none"):
            parser.error(
                "--wire_codec does not compose with the secure "
                "turboaggregate engine (the codec's float stages would "
                "corrupt the GF(p) share embedding). The compressed "
                "secure wire is --secure_quant on the cross-silo runner "
                "(distributed.run); see ARCHITECTURE.md 'Privacy plane'")
        if args.defense_type in robust.ROBUST_AGGREGATORS:
            parser.error(
                f"--defense {args.defense_type} does not compose with "
                "secure aggregation (no per-client plaintext to select "
                "over); the clip family (norm_diff_clipping, weak_dp) "
                "composes client-side — see ARCHITECTURE.md 'Privacy "
                "plane'")
    if args.dp_sigma > 0 and args.dp_clip <= 0:
        parser.error("--dp_sigma needs --dp_clip > 0 (the clip bound is "
                     "the sensitivity the noise multiplier is stated "
                     "against)")
    # health-plane config dies AT ARGPARSE (ISSUE 15 satellite): a
    # negative budget or a broken/unknown-metric rule manifest must
    # fail here, never as a silently-never-firing rule mid-run
    if args.dp_epsilon_budget < 0:
        parser.error(f"--dp_epsilon_budget must be >= 0 (got "
                     f"{args.dp_epsilon_budget})")
    if args.dp_epsilon_budget > 0 and args.dp_sigma <= 0 \
            and args.defense_type != "weak_dp":
        parser.error(
            "--dp_epsilon_budget needs an armed noise path to budget "
            "(--dp_sigma/--dp_clip on a DP engine, or --defense "
            "weak_dp): without one the accountant records nothing and "
            "the budget rules can never fire")
    if args.health_rules:
        from neuroimagedisttraining_tpu.obs import names as obs_names
        from neuroimagedisttraining_tpu.obs import rules as obs_rules

        try:
            for r in obs_rules.load_rules(args.health_rules):
                # full validation (unknown metric names included), not
                # just the schema — a typo'd rule must die HERE with
                # the known-names list, not as a traceback after the
                # data/model build
                r.validate(obs_names.DECLARED)
        except (OSError, ValueError, TypeError) as e:
            parser.error(f"--health_rules: {e}")
    # precision-contract conflicts die AT ARGPARSE with the resolution
    # named (core/optim.validate_precision re-checks at trainer build)
    if args.loss_scale != 1.0 and args.precision != "bf16_mixed":
        parser.error(
            f"--loss_scale {args.loss_scale} needs --precision "
            "bf16_mixed: under fp32 the scale/unscale pair would only "
            "perturb rounding and break the bitwise-f32 contract")
    if args.fused_update and args.client_optimizer != "sgd":
        parser.error(
            "--fused_update fuses the SGD clip/momentum/update tail "
            f"(ops/fused_update.py); --client_optimizer "
            f"{args.client_optimizer} has no fused kernel and would "
            "silently train un-fused")
    if args.dp_sigma > 0 or args.dp_clip > 0:
        # one source of truth: the same supports_dp attribute the
        # engine ctor gates on (an engine gaining the transform later
        # must not stay rejected here)
        from neuroimagedisttraining_tpu.engines import ENGINES

        cls = ENGINES.get(args.algorithm.lower())
        if cls is None or not cls.supports_dp:
            ok = sorted({c.name for c in ENGINES.values()
                         if c.supports_dp})
            parser.error(
                f"--dp_clip/--dp_sigma need an engine with the round-"
                f"level DP transform; algorithm {args.algorithm!r} "
                f"would train un-noised while the accountant reported "
                f"epsilon (supported: {ok})")
    if args.secure_quant:
        # privacy-plane conflicts die AT ARGPARSE with the resolution
        # named (the engine ctor re-checks, but only after the
        # data/model build, deep in a stack trace)
        from neuroimagedisttraining_tpu.core import robust
        from neuroimagedisttraining_tpu.engines import ENGINES

        cls = ENGINES.get(args.algorithm.lower())
        if cls is None or not cls.supports_secure_quant:
            ok = sorted({c.name for c in ENGINES.values()
                         if c.supports_secure_quant})
            parser.error(
                f"--secure_quant needs an engine whose round routes the "
                f"builder's default aggregation tail; algorithm "
                f"{args.algorithm!r} has no server fold for the field "
                f"algebra to replace (supported: {ok})")
        if args.wire_codec not in ("", "none"):
            parser.error(
                "--secure_quant does not compose with --wire_codec "
                "(the codec's float stages would corrupt the GF(p) "
                "residue embedding); see ARCHITECTURE.md 'Privacy "
                "plane'")
        if args.defense_type in robust.ROBUST_AGGREGATORS:
            parser.error(
                f"--defense {args.defense_type} does not compose with "
                "--secure_quant (no per-client plaintext to select "
                "over); the clip family (norm_diff_clipping, weak_dp) "
                "composes client-side — see ARCHITECTURE.md 'Privacy "
                "plane'")
        # field-geometry headroom fails at argparse here exactly like
        # distributed.run's startup check — misconfigured frac/field
        # bits must never surface as silent field wraparound
        from neuroimagedisttraining_tpu.privacy import (
            QuantSpec, check_headroom,
        )

        try:
            check_headroom(
                QuantSpec.from_bits(args.secure_quant_field_bits,
                                    args.secure_quant_frac_bits,
                                    args.mpc_n_shares),
                args.client_num_in_total)
        except ValueError as e:
            parser.error(str(e))

    if args.profile_session:
        # push-button profile session (ISSUE 14, obs/probe.py): the
        # declarative probe manifest through the shipped driver with
        # the dispatch-boundary profiler armed — replaces PROFILE.md's
        # hand-run checklist; normal training is skipped
        import jax

        from neuroimagedisttraining_tpu.obs import compute as obs_compute
        from neuroimagedisttraining_tpu.obs import probe as obs_probe

        if args.peak_flops > 0:
            obs_compute.PROFILER.set_peak_flops(args.peak_flops)
        manifest = (obs_probe.load_manifest(args.profile_manifest)
                    if args.profile_manifest
                    else obs_probe.default_manifest(len(jax.devices())))
        doc = obs_probe.run_session(manifest, args.profile_session,
                                    trace_out=args.trace_out)
        return 0 if obs_probe.session_ok(doc) else 1

    if args.multihost_coordinator:
        # join the pod-wide JAX runtime BEFORE any backend touch so the
        # mesh below spans every host's chips (SURVEY §2.9 DCN row; see
        # README "Multi-host TPU pods" for the per-host launch recipe)
        from neuroimagedisttraining_tpu.distributed.cross_silo import (
            init_multihost,
        )
        init_multihost(args.multihost_coordinator, args.num_processes,
                       args.process_id)

    from neuroimagedisttraining_tpu.utils.compile_cache import (
        enable_compile_cache,
    )
    enable_compile_cache(args.compile_cache_dir)

    # deterministic seeding (main_sailentgrads.py:264-268)
    random.seed(args.seed)
    np.random.seed(args.seed)  # nidt: allow[determinism-global-random] -- reference-parity entry seeding (main_sailentgrads.py:264-268), single-threaded startup

    # vision datasets imply their class counts unless overridden
    _vision_classes = {"cifar10": 10, "synthetic_vision": 10,
                       "cifar100": 100, "tiny": 200}
    if args.num_classes == 1 and args.dataset.lower() in _vision_classes:
        args.num_classes = _vision_classes[args.dataset.lower()]

    cfg = config_from_args(args)
    # mesh applies to both residency modes: under --streaming each round's
    # sampled-client buffers are device_put sharded over the client axis —
    # on a two-level (silos, clients) mesh the axis maps over BOTH mesh
    # axes silo-major (data/stream.py::_put), so the engine's silo-first
    # aggregation routing is preserved while the cohort streams from host
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh
    if args.streaming and not cfg.mesh_shape and not cfg.fed.client_mesh:
        mesh = None  # plain single-device streaming feed
    elif cfg.fed.client_mesh > 0 and not cfg.mesh_shape:
        # --client_mesh N builds the 1-D N-device client mesh it shards
        # over (an explicit --mesh_shape wins and must agree — the
        # engine validates the sizes at startup)
        mesh = make_mesh(num_devices=cfg.fed.client_mesh)
    else:
        mesh = make_mesh(shape=cfg.mesh_shape)
    engine = build_experiment(cfg, streaming=args.streaming, mesh=mesh)
    from neuroimagedisttraining_tpu.utils.profiling import (
        failure_context, profile_trace,
    )
    # observability plane (obs/, ISSUE 9): span tracer (annotating the
    # XLA timeline when --profile_dir is also set), live /metrics
    # endpoint, and the flight recorder's failure-dump destination —
    # all host-side, armed only when asked for
    import os

    from neuroimagedisttraining_tpu.obs import flight as obs_flight
    from neuroimagedisttraining_tpu.obs import trace as obs_trace
    from neuroimagedisttraining_tpu.obs.http import start_metrics_server

    obs_flight.configure(
        capacity=cfg.flight_events,
        path=os.path.join(engine.log.dir,
                          cfg.identity() + ".flight.json"))
    if cfg.trace_out:
        obs_trace.arm(cfg.trace_out,
                      annotate=bool(args.profile_dir),
                      tags={"algorithm": cfg.algorithm,
                            "seed": cfg.seed})
    # compute-plane gauges (obs/compute.py, ISSUE 14): the dispatch
    # profiler is always on; --peak_flops arms the MFU denominator and
    # /healthz carries the compute block (wedged vs slow dispatch)
    from neuroimagedisttraining_tpu.obs import compute as obs_compute
    from neuroimagedisttraining_tpu.obs import health as obs_health
    from neuroimagedisttraining_tpu.obs import rules as obs_rules

    if args.peak_flops > 0:
        obs_compute.PROFILER.set_peak_flops(args.peak_flops)
    # anomaly-rule engine (obs/rules.py, ISSUE 15): the built-in
    # manifest parameterized by this run's budget/schedule, extended by
    # --health_rules; evaluated at every engine host boundary
    # (publish_stat_info) and reported on /healthz
    # a loaded recipe arms its drift rule (mfu-below-recipe): live MFU
    # sagging under the recipe's recorded score flight-records
    # retune_recommended (tune/recipe.py drift_rules)
    extra_rules = ()
    if recipe_doc is not None:
        from neuroimagedisttraining_tpu.tune import recipe as tune_recipe

        extra_rules = tune_recipe.drift_rules(recipe_doc)
    hrules = obs_rules.configure(
        manifest_path=args.health_rules,
        dp_epsilon_budget=cfg.fed.dp_epsilon_budget,
        comm_round=cfg.fed.comm_round,
        max_staleness=cfg.fed.max_staleness,
        extra_rules=extra_rules)
    # reflex plane (obs/actions.py, ISSUE 20): arm the action bus the
    # firing rules dispatch into; the engine registers its handlers at
    # train() start. LOCAL handle — disarm() precedes the verdict
    # write, exactly like ``hrules``.
    from neuroimagedisttraining_tpu.obs import actions as obs_actions

    bus = obs_actions.configure(cfg.actions)
    msrv = start_metrics_server(
        cfg.metrics_port, host=args.metrics_host,
        health_probe=lambda: {
            "compute": obs_compute.PROFILER.health(),
            # fast-path coverage next to the compute block (ISSUE 15
            # satellite): a run silently degraded to K=1 unsharded
            # reads differently from a healthy one at the probe
            "fallbacks": obs_health.fallback_block(),
            "health": obs_rules.health_block(),
            # the last reflex dispatches, rule provenance included
            "actions": bus.actions_block()})
    try:
        with failure_context(name=cfg.identity()), \
                profile_trace(args.profile_dir,
                              enabled=bool(args.profile_dir)):
            result = engine.train()
    finally:
        # the rule engine's lifetime is the run's — disarm on EVERY
        # exit path (tests drive several runs per process; a stale
        # engine must not keep evaluating later runs' boundaries
        # against this run's state). The local ``hrules`` handle below
        # still reads the verdict after disarming.
        obs_rules.disarm()
        obs_actions.disarm()  # local ``bus`` handle outlives disarm too
        if cfg.trace_out:
            out = obs_trace.dump()
            if out:
                print(f"[obs] host-span trace written to {out} "
                      "(load in Perfetto / chrome://tracing)",
                      flush=True)
        if msrv is not None:
            msrv.close()

    # persist the stat accumulators (the reference pickles stat_info at end
    # of training and crashed when the results dir was missing,
    # subavg_api.py:218-220 / subavg/error3437295.err — the logger already
    # created its dir, which is the single source of truth for the layout)
    from neuroimagedisttraining_tpu.utils.logging import _jsonable

    stats_path = os.path.join(engine.log.dir, cfg.identity() + ".stats.json")
    with open(stats_path, "w") as f:
        json.dump(_jsonable({k: v for k, v in engine.stat_info.items()
                             if not k.startswith("final_masks")}),
                  f, default=str)

    # end-of-run health verdict (ISSUE 15): always written (the run
    # report joins it); --health_gate additionally turns a non-ok WORST
    # status into a nonzero exit — a run that diverged and recovered
    # still failed its gate
    verdict = hrules.verdict()
    # the reflex action log rides in the verdict (and from there into
    # run_report): deliberately timestamp-free, so twin seeded chaos
    # runs produce byte-identical blocks (the replayability contract)
    verdict["actions"] = bus.actions_block()
    verdict_path = os.path.join(engine.log.dir,
                                cfg.identity() + ".health.json")
    with open(verdict_path, "w") as f:
        json.dump(verdict, f, indent=1, default=str)

    final = {k: v for k, v in result.items()
             if k in ("final_global", "final_personal", "mask_density")}
    # ONE result line (the last stdout line IS the machine-readable
    # result — tests/test_cli.py's contract); the health summary rides
    # inside it rather than as a second line
    print(json.dumps({
        "identity": cfg.identity(), **final,
        "health": {k: verdict[k] for k in
                   ("status", "worst_status", "alerts_total",
                    "rounds_evaluated")},
        "health_verdict_path": verdict_path}, default=float))
    if args.health_gate and verdict["worst_status"] != "ok":
        # stderr: the LAST stdout line must stay the machine-readable
        # result (tests/test_cli.py's contract)
        print(f"[health] gate FAILED: worst status "
              f"{verdict['worst_status']!r} "
              f"({verdict['alerts_total']} alert(s); see "
              f"{verdict_path})", file=sys.stderr, flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
