"""neuroimagedisttraining_tpu — a TPU-native federated-learning framework for neuroimaging.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
bishalth01/NeuroImageDistTraining (a FedML-derived PyTorch/CUDA framework):
federated training of 3D CNNs over neuroimaging cohorts (ABCD sex
classification), with nine FL algorithms (FedAvg, SalientGrads, Sub-FedAvg,
D-PSGD, Ditto, FedFomo, DisPFL, Local-only, TurboAggregate), sparse-mask
training, robust aggregation, non-IID partitioners, and a distributed
control plane.

Design stance (TPU-first, not a port):

- **State is data.** A federation is a pytree with a leading client axis
  (``[C, ...]``); there are no client objects, no deepcopied state dicts.
- **A round is one jitted SPMD program.** Local training for all clients runs
  as ``vmap`` over the client axis, sharded over a ``jax.sharding.Mesh``
  axis ``"clients"`` — one (or more) simulated clients per TPU core.
- **Aggregation is a collective.** Weighted FedAvg is a mean over the sharded
  client axis, lowered by XLA to an ICI all-reduce — not a Python loop over
  state dicts (reference: fedml_api/standalone/fedavg/fedavg_api.py:102-117).
- **Saliency without model surgery.** SNIP scores are computed as
  ``|w * grad_w L|`` — mathematically identical to the reference's
  monkey-patched ``|grad_mask L|`` at mask=1
  (reference: fedml_api/standalone/sailentgrads/snip.py:9-16).
"""

__version__ = "0.1.0"

from neuroimagedisttraining_tpu.config import (  # noqa: F401
    DataConfig,
    FedConfig,
    OptimConfig,
    SparsityConfig,
    ExperimentConfig,
)
