"""Declarative profile-session driver (ISSUE 14): PROFILE.md's hand-run
probe checklist as a probe MANIFEST, executed push-button into one
machine-readable artifact.

Every TPU-tunnel session so far re-ran a prose checklist (PROFILE.md
rounds 8/9: "run precision bench at flagship shape", "re-read mask_ms",
"sweep remat x batch") by hand and pasted numbers back into markdown.
This module makes the session a FUNCTION: each :class:`Probe` names one
config cell (precision x remat x fused x client_mesh x
rounds_per_dispatch), the driver runs it through the SHIPPED engine
driver (``engine.train()`` — the same window planner / fused scan /
sharded dispatch path production runs, not a bench-only loop) with the
dispatch-boundary profiler armed (obs/compute.py), and the session
emits ``bench_matrix/profile_session.json``:

- per probe: wall, per-round ms, exact dispatch/compile counts
  (deterministic compile facts the bench gate pins with ``eq``),
  sustained TFLOP/s and — when the device peak is known — the MFU
  sample for the last boundary window;
- once per session: the XLA ``cost_analysis`` FLOPs of one lowered
  training step reconciled against the analytic ``ops/flops.py``
  counter (ratio RECORDED, neither side silently trusted) and the
  ``memory_analysis`` byte accounting;
- a live ``/metrics`` + ``/healthz`` self-scrape over real HTTP
  (``metrics_scrape_ok`` / ``healthz_compute_ok`` — the structural
  proof the gauges this PR promises actually serve).

``analysis/bench_gate.py`` gates the artifact: structural cells
(manifest fingerprint, dispatch counts, scrape booleans) exactly,
wall/TFLOPs at the drift-tolerant ratio tripwires every other wall
cell uses. Entry points::

    scripts/run_profile_session.sh                 # the push-button
    python -m neuroimagedisttraining_tpu.obs.probe --out X.json
    python -m neuroimagedisttraining_tpu ... --profile_session X.json

Env knobs (the bench.py convention): PROFILE_MODEL / PROFILE_SHAPE /
PROFILE_BATCH / PROFILE_LOCAL / PROFILE_CLIENTS / PROFILE_ROUNDS size
the cells (defaults are the CPU-harness smoke shape; the TPU session
exports the flagship shape — PROFILE.md round 10). A custom manifest
JSON (``--manifest``) replaces the default probe list; cells it names
ride the same driver.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
from typing import Any

__all__ = ["Probe", "default_manifest", "load_manifest", "run_probe",
           "run_session", "session_ok", "main", "validate_cell_value",
           "remat_policy"]

#: config-cell keys a probe may set; anything else in a manifest cell
#: is a spelling error and fails loudly at load (declarative probes
#: must not silently ignore a knob)
CELL_KEYS = ("precision", "fused_update", "remat", "client_mesh",
             "rounds_per_dispatch", "batch")

#: legal remat spellings in a cell: the CLI policy strings plus the
#: historic manifest booleans (True -> full remat, False -> off)
REMAT_CELL_VALUES = ("none", "stem", "all")


def remat_policy(value) -> bool | str:
    """Map a cell's remat value onto ``LocalTrainer(remat=...)``: bools
    pass through, the CLI policy strings map {"none": off, "stem":
    stem-only, "all": full}."""
    if isinstance(value, bool):
        return value
    return {"none": False, "stem": "stem", "all": True}[value]


def validate_cell_value(key: str, value) -> None:
    """Per-axis domain check (ValueError on violation) — shared by the
    manifest loader and the autotuner's space generator (tune/space.py)
    so neither can propose a cell the driver would choke on."""
    def die(expect: str) -> None:
        raise ValueError(f"cell key {key}={value!r} out of domain: "
                         f"expected {expect}")

    if key == "precision":
        from neuroimagedisttraining_tpu.core.optim import PRECISIONS
        if value not in PRECISIONS:
            die(f"one of {PRECISIONS}")
    elif key == "fused_update":
        if not isinstance(value, bool):
            die("a bool")
    elif key == "remat":
        if not isinstance(value, bool) and value not in REMAT_CELL_VALUES:
            die(f"a bool or one of {REMAT_CELL_VALUES}")
    elif key == "client_mesh":
        if isinstance(value, bool) or not isinstance(value, int) or value < 0:
            die("an int >= 0")
    elif key == "rounds_per_dispatch":
        if isinstance(value, bool) or not isinstance(value, int) or value < 1:
            die("an int >= 1")
    elif key == "batch":
        if isinstance(value, bool) or not isinstance(value, int) or value < 1:
            die("an int >= 1")
    else:
        raise ValueError(f"unknown cell key {key!r}; declarable keys: "
                         f"{CELL_KEYS}")


@dataclasses.dataclass(frozen=True)
class Probe:
    """One declared probe: a name and the config cell it pins. Cell
    values ride ``ExperimentConfig`` knobs verbatim; unset knobs keep
    the shipped defaults, so a probe IS a reproducible CLI spelling."""

    name: str
    cell: dict

    def __post_init__(self):
        bad = set(self.cell) - set(CELL_KEYS)
        if bad:
            raise ValueError(
                f"probe {self.name!r} names unknown cell keys "
                f"{sorted(bad)}; declarable keys: {CELL_KEYS}")
        for key, value in self.cell.items():
            try:
                validate_cell_value(key, value)
            except ValueError as e:
                raise ValueError(f"probe {self.name!r}: {e}") from None


def default_manifest(n_devices: int = 1) -> tuple[Probe, ...]:
    """PROFILE.md's queued probe list, declared (round-9 items 1/2/4):
    the precision step-ratio pair, the fused-update delta, the remat
    product, the fused-dispatch amortization, and — when a client mesh
    is available — the cohort-sharded dispatch. One cell each."""
    probes = [
        Probe("fp32_baseline", {"precision": "fp32"}),
        Probe("bf16", {"precision": "bf16_mixed"}),
        Probe("bf16_fused", {"precision": "bf16_mixed",
                             "fused_update": True}),
        Probe("bf16_remat", {"precision": "bf16_mixed", "remat": True}),
        Probe("fused_dispatch_k4", {"precision": "fp32",
                                    "rounds_per_dispatch": 4}),
    ]
    if n_devices > 1:
        probes.append(Probe("cohort_sharded",
                            {"precision": "fp32",
                             "client_mesh": n_devices}))
    return tuple(probes)


def load_manifest(path: str) -> tuple[Probe, ...]:
    """A manifest file is a JSON list of ``{"name", "cell"}`` objects —
    the declarative form a future session edits instead of editing
    driver code."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, list) or not doc:
        raise ValueError(f"manifest {path}: expected a non-empty JSON "
                         "list of {name, cell} objects")
    return tuple(Probe(p["name"], dict(p.get("cell", {}))) for p in doc)


def _env_meta() -> dict:
    return {
        "model": os.environ.get("PROFILE_MODEL", "3dcnn_tiny"),
        "shape": tuple(int(s) for s in os.environ.get(
            "PROFILE_SHAPE", "12,14,12").split(",")),
        "batch": int(os.environ.get("PROFILE_BATCH", 8)),
        "n_local": int(os.environ.get("PROFILE_LOCAL", 16)),
        "clients": int(os.environ.get("PROFILE_CLIENTS", 4)),
        "rounds": int(os.environ.get("PROFILE_ROUNDS", 5)),
    }


def _make_fed(meta: dict):
    """Seeded synthetic federation at the session shape (the bench
    cells' construction — deterministic in the key, no disk)."""
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.data.federate import FederatedData

    kx, ky = jax.random.split(jax.random.key(20))
    C, n_local = meta["clients"], meta["n_local"]
    shape = tuple(meta["shape"])
    X = jax.random.randint(kx, (C, n_local) + shape, 0, 255,
                           dtype=jnp.int32).astype(jnp.uint8)
    y = jax.random.randint(ky, (C, n_local), 0, 2, dtype=jnp.int32)
    n = jnp.full((C,), n_local, jnp.int32)
    return FederatedData(X_train=X, y_train=y, n_train=n,
                         X_test=X[:, :4], y_test=y[:, :4],
                         n_test=jnp.full((C,), 4, jnp.int32))


def run_probe(probe: Probe, meta: dict, fed, log) -> dict:
    """One probe through the SHIPPED driver: build the cell's engine,
    ``engine.train()``, read the exact dispatch/compile counts off its
    round program and the MFU/TFLOPs samples off the profiler."""
    import jax

    from neuroimagedisttraining_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, OptimConfig,
    )
    from neuroimagedisttraining_tpu.core.optim import compute_dtype
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.engines import create_engine
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.obs import compute as obs_compute
    from neuroimagedisttraining_tpu.parallel.mesh import make_mesh

    cell = dict(probe.cell)
    cm = int(cell.get("client_mesh", 0))
    if cm > 1 and len(jax.devices()) < cm:
        return {"config": cell, "ran": False,
                "skip_reason": f"client_mesh={cm} needs {cm} devices, "
                               f"{len(jax.devices())} visible "
                               "(--virtual_devices provisions them)"}
    precision = cell.get("precision", "fp32")
    optim = OptimConfig(lr=1e-3,
                        batch_size=int(cell.get("batch", meta["batch"])),
                        epochs=1, precision=precision,
                        fused_update=bool(cell.get("fused_update",
                                                   False)))
    cfg = ExperimentConfig(
        model=meta["model"], num_classes=1, algorithm="fedavg",
        data=DataConfig(dataset="synthetic"), optim=optim,
        fed=FedConfig(client_num_in_total=meta["clients"],
                      comm_round=meta["rounds"],
                      rounds_per_dispatch=int(
                          cell.get("rounds_per_dispatch", 1)),
                      client_mesh=cm,
                      frequency_of_the_test=10 ** 9),
        log_dir="/tmp/nidt_profile", tag=f"probe-{probe.name}")
    trainer = LocalTrainer(
        create_model(meta["model"], num_classes=1,
                     dtype=compute_dtype(precision),
                     remat=remat_policy(cell.get("remat", False))),
        optim, num_classes=1)
    mesh = make_mesh(num_devices=cm) if cm > 1 else None
    engine = create_engine("fedavg", cfg, fed, trainer, logger=log,
                           mesh=mesh)
    # a probe never inherits its predecessor's MFU/TFLOPs samples: a
    # cell whose run closes no boundary must report None, not a stale
    # number in a committed artifact
    obs_compute.PROFILER.clear_samples()
    t0 = time.perf_counter()
    result = engine.train()
    wall = time.perf_counter() - t0
    prof = obs_compute.PROFILER.snapshot()
    hist = result.get("history") or [{}]
    return {
        "config": cell,
        "ran": True,
        "skip_reason": None,
        "wall_s": round(wall, 4),
        "round_ms": round(wall / meta["rounds"] * 1e3, 2),
        "dispatches": int(engine.program.dispatches),
        "compiles": int(engine.program.built),
        "sustained_tflops": prof.get("last_sustained_tflops"),
        "mfu": prof.get("last_mfu"),
        "train_loss_final": hist[-1].get("train_loss"),
    }


def _scrape(port: int) -> tuple[bool, bool]:
    """(metrics_scrape_ok, healthz_compute_ok): a REAL HTTP scrape of
    the live endpoint — the structural proof ``nidt_dispatch_ms`` /
    ``nidt_sustained_tflops``/``nidt_mfu`` and the ``/healthz`` compute
    block actually serve (the CI smoke the ISSUE names)."""
    from urllib.request import urlopen

    from neuroimagedisttraining_tpu.obs import names as obs_names

    try:
        body = urlopen(f"http://127.0.0.1:{port}/metrics",
                       timeout=5).read().decode()
        # _bucket is the Prometheus exposition suffix of the histogram
        metrics_ok = (obs_names.DISPATCH_MS + "_bucket" in body
                      and obs_names.COMPILES_TOTAL in body
                      and (obs_names.SUSTAINED_TFLOPS in body
                           or obs_names.MFU in body))
        health = json.loads(urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5).read())
        comp = health.get("compute") or {}
        health_ok = (comp.get("dispatches", 0) > 0
                     and comp.get("compiles", 0) > 0)
        return bool(metrics_ok), bool(health_ok)
    except Exception:  # noqa: BLE001 — the artifact records the failure
        return False, False


def run_session(manifest: tuple[Probe, ...], out_path: str,
                trace_out: str = "") -> dict:
    """The whole session: arm the obs plane, run every probe through
    the shipped driver, reconcile the XLA/analytic cost models once,
    self-scrape the live endpoint, write the artifact."""
    import jax

    from neuroimagedisttraining_tpu.core.optim import compute_dtype
    from neuroimagedisttraining_tpu.core.trainer import LocalTrainer
    from neuroimagedisttraining_tpu.config import OptimConfig
    from neuroimagedisttraining_tpu.models import create_model
    from neuroimagedisttraining_tpu.obs import compute as obs_compute
    from neuroimagedisttraining_tpu.obs import trace as obs_trace
    from neuroimagedisttraining_tpu.obs.http import MetricsServer
    from neuroimagedisttraining_tpu.utils.logging import ExperimentLogger

    meta = _env_meta()
    log = ExperimentLogger("/tmp/nidt_profile", "synthetic",
                           "profile_session", console=False)
    if trace_out:
        obs_trace.arm(trace_out, tags={"session": "profile"})
    srv = MetricsServer(
        0, health_probe=lambda: {
            "compute": obs_compute.PROFILER.health()})
    fed = _make_fed(meta)
    probes: dict[str, dict] = {}
    completed = 0
    t0 = time.perf_counter()
    try:
        for probe in manifest:
            print(f"[profile] probe {probe.name}: {probe.cell}",
                  flush=True)
            try:
                probes[probe.name] = run_probe(probe, meta, fed, log)
            except Exception as e:  # noqa: BLE001 — one blown probe
                # (flagship OOM mid-TPU-session) must not lose the
                # completed probes' results: record, continue, and the
                # probes_completed < n_probes verdict fails the session
                probes[probe.name] = {
                    "config": dict(probe.cell), "ran": False,
                    "skip_reason": f"error: {type(e).__name__}: {e}"}
            if probes[probe.name]["ran"]:
                completed += 1
            else:
                print(f"[profile]   skipped: "
                      f"{probes[probe.name]['skip_reason']}", flush=True)

        # cost-model reconciliation, once per session at the session
        # shape (compile=True: the memory_analysis bytes ride the
        # artifact; the double compile is a session cost, never a
        # hot-path one)
        trainer = LocalTrainer(
            create_model(meta["model"], num_classes=1,
                         dtype=compute_dtype("fp32")),
            OptimConfig(lr=1e-3, batch_size=meta["batch"], epochs=1),
            num_classes=1)
        xla = obs_compute.analyze_train_step(
            trainer, tuple(meta["shape"]), meta["batch"], compile=True)
        metrics_ok, health_ok = _scrape(srv.port)
    finally:
        # the endpoint thread and the armed tracer must not outlive the
        # session, even when a probe or the reconciliation raises
        srv.close()
        if trace_out:
            obs_trace.dump()
            obs_trace.disarm()

    fingerprint = json.dumps({p.name: p.cell for p in manifest},
                             sort_keys=True)
    doc = {
        "metric": "profile_session",
        "meta": {
            **{k: (list(v) if isinstance(v, tuple) else v)
               for k, v in meta.items()},
            "device_kind": getattr(jax.devices()[0], "device_kind",
                                   "unknown"),
            "n_devices": len(jax.devices()),
            "peak_flops": obs_compute.peak_flops_estimate() or None,
            "jax": jax.__version__,
        },
        "probes": probes,
        "xla": {"train_step": xla},
        "session": {
            "n_probes": len(manifest),
            "probes_completed": completed,
            "structural_fingerprint": fingerprint,
            "metrics_scrape_ok": metrics_ok,
            "healthz_compute_ok": health_ok,
            "wall_s": round(time.perf_counter() - t0, 2),
        },
        "notes": (
            "Shipped-driver probes (engine.train()) with the dispatch-"
            "boundary profiler armed (obs/compute.py). Dispatch/compile "
            "counts and the scrape booleans are deterministic compile "
            "facts; wall and TFLOP/s cells drift with the box (the "
            "bench gate's 0.5/2.0 ratio tripwires apply); nidt_mfu "
            "publishes only where a device peak is known "
            "(NIDT_PEAK_FLOPS overrides). CPU-harness numbers are "
            "harness evidence — the flagship-shape TPU session exports "
            "PROFILE_MODEL/PROFILE_SHAPE/PROFILE_BATCH (PROFILE.md "
            "round 10)."),
    }
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    print(f"[profile] session artifact: {out_path} "
          f"({completed}/{len(manifest)} probes, "
          f"scrape_ok={metrics_ok})", flush=True)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m neuroimagedisttraining_tpu.obs.probe",
        description=__doc__.split("\n\n")[0])
    ap.add_argument("--out", type=str,
                    default="bench_matrix/profile_session.json",
                    help="artifact path (the committed cell lives at "
                         "bench_matrix/profile_session.json)")
    ap.add_argument("--manifest", type=str, default="",
                    help="JSON probe manifest replacing the default "
                         "list (a [{name, cell}] array)")
    ap.add_argument("--trace_out", type=str, default="",
                    help="also write the session's host-span Chrome "
                         "trace here")
    ap.add_argument("--virtual_devices", type=int, default=0,
                    help="provision N virtual CPU devices before the "
                         "first backend touch (arms the cohort_sharded "
                         "probe off-TPU)")
    args = ap.parse_args(argv)
    if args.virtual_devices:
        from neuroimagedisttraining_tpu.parallel.mesh import (
            provision_virtual_devices,
        )
        provision_virtual_devices(args.virtual_devices)
    import jax

    manifest = (load_manifest(args.manifest) if args.manifest
                else default_manifest(len(jax.devices())))
    doc = run_session(manifest, args.out, trace_out=args.trace_out)
    ok = session_ok(doc)
    return 0 if ok else 1


def session_ok(doc: dict) -> bool:
    """The push-button success contract: every declared probe ran AND
    both live-endpoint self-scrapes held (``/metrics`` samples and the
    ``/healthz`` compute block) — the exit-code mirror of the gate's
    structural cells, shared by this CLI and ``--profile_session``."""
    s = doc["session"]
    return bool(s["probes_completed"] == s["n_probes"]
                and s["metrics_scrape_ok"] and s["healthz_compute_ok"])


if __name__ == "__main__":
    sys.exit(main())
