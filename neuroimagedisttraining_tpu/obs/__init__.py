"""``obs/`` — the unified telemetry plane (ISSUE 9).

Three host-side instruments, one import surface:

- :mod:`obs.trace` — a dependency-free, thread-safe span tracer emitting
  Chrome trace-event JSON (Perfetto-loadable), with an adapter that opens
  a matching ``jax.profiler.TraceAnnotation`` per span so the host
  timeline lines up with the XLA timeline under ``--profile_dir``.
- :mod:`obs.metrics` — a registry of labeled Counters / Gauges /
  Histograms with ``snapshot()``, Prometheus text exposition, and a
  JSONL sink. The single home that ``stat_info``, comm ``byte_stats``,
  async buffer occupancy / staleness, sync round wall, strikes /
  quarantines, and per-silo DP epsilon publish into.
- :mod:`obs.flight` — a bounded ring flight recorder of structured
  control-plane events, dumped to JSON by ``failure_context`` and on
  audit failure (the chaos post-mortem).
- :mod:`obs.http` — a stdlib-only ``/metrics`` + ``/healthz`` endpoint
  (``--metrics_port``).
- :mod:`obs.fanin` — federation-wide fan-in (ISSUE 13): worker
  processes ship registry snapshots, span chunks and flight events
  over the ingest pipes; the root merges them into ONE worker-labeled
  Prometheus exposition (with staleness gauges), ONE clock-aligned
  Chrome trace, and ONE flight dump with per-worker provenance. The
  wire trace context (``trace.make_trace_ctx`` riding
  ``ARG_TRACE_CTX``) links one upload's client->worker->root lifecycle
  as Perfetto flow events.
- :mod:`obs.compute` — the COMPUTE-plane profiler (ISSUE 14): host
  wall per compiled-program dispatch (``nidt_dispatch_ms`` with the
  compile-vs-execute phase split), the ``nidt_compiles_total``
  recompile tripwire, live ``nidt_mfu``/``nidt_sustained_tflops``
  gauges closed at already-synced host boundaries (zero added device
  syncs), XLA cost/memory accounting reconciled against the analytic
  ``ops/flops.py`` counter, and the ``/healthz`` compute block.
- :mod:`obs.probe` — the declarative profile-session driver
  (ISSUE 14): PROFILE.md's probe checklist as a manifest of config
  cells run through the SHIPPED driver, emitting the bench-gated
  ``bench_matrix/profile_session.json``
  (``scripts/run_profile_session.sh`` / ``--profile_session``).

THE HOST-BOUNDARY RULE: none of this may run inside a jitted/vmapped/
shard_mapped body. Clocks (``time.monotonic``/``perf_counter``) and
registry mutation inside a traced function either bake one Python value
into the compiled executable or force a host sync mid-dispatch;
instrumentation sits only at the existing host boundaries
(``_flush_nonfinite``, fused-window edges, server accept/aggregate
paths, selector-loop ticks). nidtlint's ``obs-discipline`` family
(analysis/obs_discipline.py) machine-checks this.

Everything is off-by-default cheap: the tracer disarmed returns a
shared no-op context manager (no allocation), the flight ring is one
bounded deque append, and the registry can be disarmed wholesale
(``metrics.disable()``) for A/B overhead measurement
(bench.py ``obs_overhead`` cell).
"""

from neuroimagedisttraining_tpu.obs import compute, fanin, flight, metrics, trace  # noqa: F401
from neuroimagedisttraining_tpu.obs.flight import FLIGHT, FlightRecorder  # noqa: F401
from neuroimagedisttraining_tpu.obs.metrics import (  # noqa: F401
    REGISTRY,
    MetricsRegistry,
)
from neuroimagedisttraining_tpu.obs.trace import TRACER, SpanTracer, span  # noqa: F401

__all__ = [
    "FLIGHT",
    "FlightRecorder",
    "REGISTRY",
    "MetricsRegistry",
    "TRACER",
    "SpanTracer",
    "span",
    "compute",
    "fanin",
    "flight",
    "metrics",
    "trace",
]
