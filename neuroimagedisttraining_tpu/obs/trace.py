"""Span tracer: host-side timeline, Chrome trace-event JSON out.

Frostig et al. 2018 (PAPERS.md, JAX/SysML): under asynchronous dispatch
the host thread races ahead of the accelerator, so host observability is
only meaningful at the host<->XLA seams the dispatch model defines — a
span here measures HOST time between dispatch boundaries (enqueue a
fused window, block on an eval result), never device time, and must
never ADD a sync to read a clock. The complementary device timeline is
``jax.profiler`` (``--profile_dir``); the adapter below opens a matching
``jax.profiler.TraceAnnotation`` per span so the two line up in one
XProf/Perfetto view.

Design constraints (ISSUE 9):

- dependency-free: stdlib only; jax is imported lazily and only when the
  caller armed the annotation adapter.
- thread-safe: every server handler thread / selector loop / engine
  driver appends to one per-process buffer under a lock; events carry
  the OS thread id so Perfetto lays threads out as separate tracks.
- nestable: spans are ordinary context managers; Chrome "X" (complete)
  events nest by time containment per thread, so no explicit parent
  bookkeeping is needed (``tests/test_obs.py`` pins containment).
- off-by-default cheap: disarmed, ``span()`` returns a shared no-op
  context manager — no allocation, no clock read, one attribute test.

Output: ``{"traceEvents": [...], "displayTimeUnit": "ms"}`` with "X"
events ``{name, ph, ts, dur, pid, tid, args}`` (ts/dur in microseconds
since arm time, monotonic clock) — the Chrome trace-event format
Perfetto and ``chrome://tracing`` load directly.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

__all__ = ["SpanTracer", "TRACER", "span", "instant", "flow", "arm",
           "disarm", "dump", "make_trace_ctx", "flow_id_of"]

#: flow-event phases (Chrome trace-event format): start / step / end —
#: Perfetto draws an arrow chain through the slices that enclose them
FLOW_PHASES = ("s", "t", "f")


def make_trace_ctx(rank: int, seq: int) -> dict:
    """Wire trace context (ISSUE 13): the Dapper lesson is that per-hop
    telemetry without PROPAGATED context cannot answer "where did this
    upload's latency go" — so the client stamps one of these on every
    upload frame (``distributed.message.ARG_TRACE_CTX``) and every hop
    (worker admission, root merge/aggregate) emits a flow event carrying
    the same id, turning one upload into a causally-linked Perfetto
    track. ``trace_id`` is unique per (sender, upload); ``span_id``
    names the sender's originating span."""
    return {"trace_id": (int(rank) << 24) | (int(seq) & 0xFFFFFF),
            "span_id": int(rank)}


def flow_id_of(ctx) -> int | None:
    """The Perfetto flow id of a wire trace context; None for a missing
    or malformed context (a version-skewed client must never crash a
    telemetry path)."""
    if not isinstance(ctx, dict):
        return None
    tid = ctx.get("trace_id")
    if isinstance(tid, bool) or not hasattr(tid, "__index__"):
        return None  # ints only (msgpack may hand back numpy scalars)
    return int(tid)


class _NullSpan:
    """Shared no-op context manager — the disarmed fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    """One live span: records a Chrome "X" event on exit; optionally
    holds a matching ``jax.profiler.TraceAnnotation`` open for its
    lifetime (the host<->XLA alignment adapter)."""

    __slots__ = ("_tracer", "name", "args", "_t0", "_ann")

    def __init__(self, tracer: "SpanTracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._t0 = 0
        self._ann = None

    def __enter__(self):
        t = self._tracer
        if t._annotate:
            try:
                import jax

                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:  # noqa: BLE001 — tracing must never be
                # the thing that kills a run (no jax, profiler torn down)
                self._ann = None
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:  # noqa: BLE001 — see __enter__
                pass
        self._tracer._record(self.name, self._t0, t1, self.args)
        return False


class SpanTracer:
    """Per-process span buffer. Arm with an output path; every
    ``span()`` between arm and ``dump()`` lands in the trace. Tracer-
    level ``tags`` (rank, role, ...) merge into every event's args —
    the per-process key the multi-silo timeline is joined on."""

    #: event-buffer cap (~80 MB of dicts at ~300 B/event): a multi-hour
    #: armed run must not grow host memory without bound — events past
    #: the cap are DROPPED and counted (bounded-buffer honesty, the
    #: flight ring's rule), keeping the PREFIX of the run, which is
    #: what a Perfetto session of a long run gets opened on anyway
    DEFAULT_MAX_EVENTS = 1 << 18

    def __init__(self):
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._armed = False
        self._annotate = False
        self._path: str | None = None
        self._tags: dict[str, Any] = {}
        self._epoch_ns = time.perf_counter_ns()
        self._max_events = self.DEFAULT_MAX_EVENTS
        self._dropped = 0

    # ---- lifecycle ----

    @property
    def armed(self) -> bool:
        return self._armed

    @property
    def epoch_ns(self) -> int:
        """The ``perf_counter_ns`` instant event timestamps are relative
        to — the rebase anchor the cross-process merge
        (``obs/fanin.py``) aligns worker timelines with."""
        return self._epoch_ns

    def arm(self, path: str | None = None, *, annotate: bool = False,
            tags: dict | None = None,
            max_events: int | None = None) -> None:
        """Start recording. ``annotate=True`` additionally opens a
        ``jax.profiler.TraceAnnotation`` per span (use with
        ``--profile_dir`` so host spans appear on the XLA timeline);
        ``tags`` ride in every event's args; ``max_events`` caps the
        buffer (default ``DEFAULT_MAX_EVENTS``; excess events are
        dropped and counted in the dump's ``nidtDroppedEvents``)."""
        with self._lock:
            self._path = path
            self._annotate = bool(annotate)
            self._tags = dict(tags or {})
            self._epoch_ns = time.perf_counter_ns()
            self._events.clear()
            self._max_events = (self.DEFAULT_MAX_EVENTS
                                if max_events is None
                                else int(max_events))
            self._dropped = 0
            self._armed = True

    def disarm(self) -> None:
        with self._lock:
            self._armed = False
            self._annotate = False

    # ---- recording ----

    def span(self, name: str, **args: Any):
        """Context manager for one host span. Disarmed: a shared no-op
        (no allocation, no clock read)."""
        if not self._armed:
            return _NULL
        return _Span(self, name, args)

    def instant(self, name: str, **args: Any) -> None:
        """Zero-duration marker (Chrome "i" instant event)."""
        if not self._armed:
            return
        ts = (time.perf_counter_ns() - self._epoch_ns) / 1e3
        with self._lock:
            if not self._armed:
                return
            if len(self._events) >= self._max_events:
                self._dropped += 1
                return
            self._events.append({
                "name": name, "ph": "i", "s": "t",
                "ts": ts, "pid": os.getpid(),
                "tid": threading.get_ident(),
                "args": {**self._tags, **args}})

    def _record(self, name: str, t0_ns: int, t1_ns: int,
                args: dict) -> None:
        ev = {
            "name": name, "ph": "X",
            "ts": (t0_ns - self._epoch_ns) / 1e3,
            "dur": (t1_ns - t0_ns) / 1e3,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": {**self._tags, **args},
        }
        with self._lock:
            if not self._armed:
                return
            if len(self._events) >= self._max_events:
                self._dropped += 1
                return
            self._events.append(ev)

    def flow(self, name: str, flow_id: int, phase: str,
             **args: Any) -> None:
        """One flow event (ISSUE 13): ``phase`` is "s" (start), "t"
        (step) or "f" (end). Perfetto binds each to the "X" slice
        enclosing its timestamp on that (pid, tid) and draws the arrow
        chain through slices sharing ``flow_id`` — emit INSIDE a live
        span. Flow ends carry ``bp: "e"`` (bind to enclosing slice)."""
        if not self._armed:
            return
        if phase not in FLOW_PHASES:
            raise ValueError(f"flow phase must be one of {FLOW_PHASES}, "
                             f"got {phase!r}")
        ts = (time.perf_counter_ns() - self._epoch_ns) / 1e3
        ev = {"name": name, "ph": phase, "cat": "flow",
              "id": int(flow_id), "ts": ts, "pid": os.getpid(),
              "tid": threading.get_ident(),
              "args": {**self._tags, **args}}
        if phase == "f":
            ev["bp"] = "e"
        with self._lock:
            if not self._armed:
                return
            if len(self._events) >= self._max_events:
                self._dropped += 1
                return
            self._events.append(ev)

    # ---- output ----

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def events_from(self, start: int) -> tuple[list[dict], int]:
        """Incremental read for periodic shipping (obs/fanin.py):
        events recorded since index ``start`` plus the new watermark.
        ``arm()`` clears the buffer, so shippers must reset their
        watermark when they re-arm."""
        with self._lock:
            evs = list(self._events[start:])
            return evs, start + len(evs)

    def dump(self, path: str | None = None) -> str | None:
        """Write the Chrome trace JSON; returns the path written (None
        when no path was armed or given, OR when the write failed —
        every caller dumps from a ``finally``, and an unwritable
        ``--trace_out`` must neither mask the run's real exception nor
        fail a successful run at exit; flight.dump keeps the same
        contract). Safe to call repeatedly — the buffer is kept, so a
        mid-run dump is a prefix of the final."""
        with self._lock:
            out = path or self._path
            if not out:
                return None
            doc = {"traceEvents": list(self._events),
                   "displayTimeUnit": "ms"}
            if self._dropped:
                # Perfetto ignores unknown top-level keys; the count
                # keeps a truncated long run honest
                doc["nidtDroppedEvents"] = self._dropped
        try:
            d = os.path.dirname(out)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(out, "w") as f:
                json.dump(doc, f)
        except OSError:
            return None
        return out


#: the process-global tracer every instrumentation site records into
TRACER = SpanTracer()

#: module-level conveniences (the instrumentation-site spelling:
#: ``from neuroimagedisttraining_tpu.obs import trace`` then
#: ``with trace.span("eval", round=r): ...``)
span = TRACER.span
instant = TRACER.instant
flow = TRACER.flow
arm = TRACER.arm
disarm = TRACER.disarm
dump = TRACER.dump
