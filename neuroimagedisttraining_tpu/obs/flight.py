"""Flight recorder: a bounded ring of structured control-plane events.

A chaos run that goes wrong leaves logs measured in megabytes and a
stack trace measured in one frame. This recorder keeps the LAST N
control-plane decisions — accept / drop / strike / quarantine /
deadline / rejoin / EF-reset / superseded-in-buffer / action /
action_dry_run (the reflex plane's rule->action dispatches,
obs/actions.py, each carrying its firing rule as provenance) — as
structured
records in a bounded ring (``collections.deque(maxlen=N)``), so the
post-mortem question "what did the server decide in the 30 seconds
before it died?" has a machine-readable answer.

Dump triggers:

- ``utils/profiling.failure_context`` — any fatal escape dumps the ring
  next to the traceback before re-raising;
- ``asyncfl.BufferedFedAvgServer.upload_audit`` — a red accounting
  audit dumps the ring (the frames the audit cannot reconcile are
  exactly the decisions the ring recorded);
- end-of-run on the cross-silo servers when ``--flight_out`` is set
  (the chaos smoke asserts this dump exists and parses).

Cheap by construction: one dict build + deque append under a lock per
event; recording is always on (the ring is the whole cost). Events
carry both clocks — ``t_mono`` (monotonic, orders events within the
process) and ``t_wall`` (epoch, joins across processes).

HOST-BOUNDARY RULE: ``record()`` reads clocks — never call it inside a
jitted body (nidtlint ``obs-discipline``).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any

__all__ = ["FlightRecorder", "FLIGHT", "record", "dump", "configure",
           "clear", "events"]

DEFAULT_CAPACITY = 256


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 path: str = ""):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=int(capacity))
        self._dropped = 0  # events the ring evicted (bounded-ring honesty)
        self._seq = 0  # monotone per-event id (cross-process shipping)
        self._path = path

    def configure(self, capacity: int | None = None,
                  path: str | None = None) -> None:
        """Re-arm: ``capacity`` resizes the ring (keeping the newest
        events), ``path`` sets the default dump destination."""
        with self._lock:
            if capacity is not None and \
                    int(capacity) != self._ring.maxlen:
                old = list(self._ring)
                self._ring = collections.deque(old[-int(capacity):],
                                               maxlen=int(capacity))
            if path is not None:
                self._path = path

    @property
    def path(self) -> str:
        return self._path

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    @property
    def evicted(self) -> int:
        """Events the bounded ring has dropped (the honesty counter a
        merged dump must carry forward — obs/fanin.py)."""
        with self._lock:
            return self._dropped

    def record(self, kind: str, **fields: Any) -> None:
        """Append one event. ``fields`` must be JSON-serializable
        scalars/lists (the callers only pass ids, counts, reasons)."""
        ev = {"kind": kind, "t_mono": round(time.monotonic(), 6),
              "t_wall": round(time.time(), 6), **fields}
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def events_from(self, after_seq: int) -> tuple[list[dict], int]:
        """Incremental read for periodic shipping (obs/fanin.py): ring
        events with ``seq > after_seq`` plus the new watermark. Events
        the bounded ring already evicted between reads are gone — the
        same honesty contract as the ring itself (``evicted`` counts
        them in the dump)."""
        with self._lock:
            evs = [e for e in self._ring if e["seq"] > int(after_seq)]
            return evs, self._seq

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0
            self._seq = 0

    def dump(self, path: str | None = None, *,
             reason: str = "") -> str | None:
        """Write ``{"reason", "capacity", "evicted", "events": [...]}``
        to ``path`` (or the configured default). Returns the path
        written, or None when neither is set — dumping must never be
        the thing that crashes the failure path, so I/O errors are
        swallowed into the return value too."""
        with self._lock:
            out = path or self._path
            if not out:
                return None
            doc = {"reason": reason, "capacity": self._ring.maxlen,
                   "evicted": self._dropped,
                   "events": list(self._ring)}
        try:
            d = os.path.dirname(out)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(out, "w") as f:
                json.dump(doc, f, default=str)
        except OSError:
            return None
        return out


#: the process-global recorder every control-plane site records into
FLIGHT = FlightRecorder()

#: module-level conveniences (instrumentation-site spelling)
record = FLIGHT.record
dump = FLIGHT.dump
configure = FLIGHT.configure
clear = FLIGHT.clear
events = FLIGHT.events
