"""Compute-plane observability: the dispatch-boundary profiler (ISSUE 14).

PRs 9/13 gave the CONTROL plane spans, a merged ``/metrics`` and a bench
regression gate; every COMPUTE-plane claim (the 0.25-MFU bf16 thesis,
the fused kernel's HBM win, the 7.7x cohort slope) still rested on
hand-timed ``device_get`` probes. This module instruments the dispatch
boundary itself — the host<->XLA seam Frostig et al. 2018 (PAPERS.md,
JAX/SysML) define as the only place host observability is meaningful
under asynchronous dispatch — with ZERO added device syncs:

- **per-dispatch wall** (``nidt_dispatch_ms{engine, program, phase}``
  histogram): ``time.perf_counter`` around each compiled-program
  invocation in ``engines/program.py``. Under async dispatch this
  measures the HOST side (trace + compile on the first call, enqueue
  thereafter) — the ``phase`` label carries the compile-vs-execute
  split, and a steady-state "execute" sample that suddenly reads
  compile-scale is itself the recompile signal.
- **recompile accounting** (``nidt_compiles_total{engine, program}``
  counter): every program build increments it — the same increment
  that feeds ``RoundProgram.built``, one measurement, not a second
  bookkeeping path (tests/test_program.py re-asserts the
  one-compiled-program-per-window pins through this counter). A
  rebuild of the SAME cache key mid-run (LRU thrash, a shape leak) is
  a recompile STORM: warning-logged (capped) and flight-recorded.
- **MFU / sustained-TFLOPs gauges** (``nidt_mfu{engine}``,
  ``nidt_sustained_tflops{engine}``): dispatched work is accumulated
  per dispatch as analytic training FLOPs (``ops/flops.py`` — exact
  for fixed shapes, free: one abstract ``eval_shape``) and divided by
  the wall between HOST BOUNDARIES (``publish_stat_info``, where the
  driver already blocks on device results) — never by enqueue time,
  which the async dispatch model makes meaningless, and never via an
  added sync. The MFU denominator is :func:`peak_flops_estimate`
  (device-kind table x local device count; ``NIDT_PEAK_FLOPS``
  overrides; unknown backends publish TFLOPs only).
- **XLA accounting reconciliation** (``nidt_xla_flops``,
  ``nidt_flops_parity_ratio``, ``nidt_hbm_peak_bytes{kind}``):
  :func:`analyze_train_step` AOT-lowers ONE training step at abstract
  shapes (``LocalTrainer.lower_train_step`` — nothing materialized,
  nothing executed), reads ``cost_analysis()`` FLOPs off the
  unoptimized HLO and reconciles them against the analytic counter;
  ``compile=True`` additionally compiles the step for
  ``memory_analysis()`` temp/argument/output bytes. Deliberately NOT
  on the hot path (the probe driver and the parity test call it).

The per-dispatch timing is always on, like the flight ring — two clock
reads and one histogram observe per dispatch is the whole cost, pinned
inside the ±2% ``obs_overhead`` acceptance (bench.py) — and the armed
vs disarmed round is bitwise-identical by construction: nothing here
touches a device buffer (tests/test_compute.py pins it).

``/healthz`` gains a ``compute`` block from :meth:`ComputeProfiler
.health` (last dispatch age, last MFU sample, compile/recompile
counts), so a WEDGED-dispatch federation (dispatch age grows, rounds
stall) is distinguishable from a merely slow one at the liveness probe.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any

from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics

__all__ = [
    "ComputeProfiler", "PROFILER", "note_compile", "note_dispatch",
    "boundary", "arm_model", "health", "compiles_total",
    "peak_flops_estimate", "analyze_train_step", "analytic_sample_flops",
]

log = logging.getLogger("neuroimagedisttraining_tpu.obs")

#: per-chip dense-matmul peaks (bf16/MXU for TPUs) by ``device_kind``
#: prefix — the MFU denominator. Per CHIP, multiplied by the local
#: device count at estimate time; ``NIDT_PEAK_FLOPS`` (total, flop/s)
#: overrides the table outright (and is the only route on CPU, where
#: no honest peak exists).
PEAK_FLOPS_BY_DEVICE_KIND: tuple[tuple[str, float], ...] = (
    ("TPU v2", 45e12),
    ("TPU v3", 123e12),
    ("TPU v4", 275e12),
    ("TPU v5 lite", 197e12),
    ("TPU v5e", 197e12),
    ("TPU v5p", 459e12),
    ("TPU v5", 459e12),
    ("TPU v6 lite", 918e12),
    ("TPU v6e", 918e12),
)

#: ``nidt_dispatch_ms`` buckets (milliseconds): sub-ms enqueues through
#: multi-minute flagship compiles
DISPATCH_MS_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                       100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
                       10000.0, 30000.0, 120000.0)

#: recompile warnings are capped per process (a storm should not also
#: be a log flood); the counter and flight ring keep the full count
_MAX_STORM_WARNINGS = 8


def peak_flops_estimate() -> float:
    """Total peak flop/s of the local devices for the MFU denominator:
    ``NIDT_PEAK_FLOPS`` env override (total, not per chip), else the
    device-kind table x local device count, else 0.0 (unknown backend —
    CPU harness — the MFU gauge stays unpublished and sustained TFLOPs
    carry the evidence)."""
    env = os.environ.get("NIDT_PEAK_FLOPS", "")
    if env:
        try:
            return float(env)
        except ValueError:
            log.warning("NIDT_PEAK_FLOPS=%r is not a number; ignoring",
                        env)
    try:
        import jax

        devs = jax.local_devices()
        kind = getattr(devs[0], "device_kind", "") or ""
    except Exception:  # noqa: BLE001 — no backend is a valid state here
        return 0.0
    for prefix, per_chip in PEAK_FLOPS_BY_DEVICE_KIND:
        if kind.startswith(prefix):
            return per_chip * len(devs)
    return 0.0


class ComputeProfiler:
    """Per-process dispatch-boundary accounting. One instance
    (:data:`PROFILER`) is fed by ``engines/program.py``'s dispatch
    wrappers and drained at engine host boundaries
    (``FederatedEngine.publish_stat_info``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        """Back to cold state (tests; never called by shipped code)."""
        with getattr(self, "_lock", threading.Lock()):
            self._armed_engine: str | None = None
            self._flops_per_round = 0.0
            self._peak_flops = 0.0
            self._peak_override = 0.0
            self._total_compiles = 0
            self._total_recompiles = 0
            self._total_dispatches = 0
            self._storm_warnings = 0
            self._last_dispatch_mono: float | None = None
            self._last_compile_s: float | None = None
            self._boundary_mono: float | None = None
            self._rounds_pending = 0
            self._dispatch_s_pending = 0.0
            self._last_mfu: float | None = None
            self._last_tflops: float | None = None

    # ---------- arming (analytic FLOPs + peak) ----------

    def arm_model(self, engine: str, flops_per_round: float,
                  peak_flops: float | None = None) -> None:
        """Arm MFU accounting for ``engine``: ``flops_per_round`` is the
        analytic training-FLOPs estimate of ONE round at the nominal
        cohort (``FederatedEngine._arm_compute_profiler`` derives it
        from ``ops/flops.py``); ``peak_flops`` defaults to
        :func:`peak_flops_estimate`. Re-arming (a second engine in the
        same process) overwrites — the gauges are per-engine-labeled,
        the accumulator window is whoever armed last."""
        with self._lock:
            self._armed_engine = engine
            self._flops_per_round = float(flops_per_round)
            if peak_flops is not None:
                self._peak_flops = float(peak_flops)
            elif self._peak_override > 0:
                self._peak_flops = self._peak_override
            else:
                self._peak_flops = peak_flops_estimate()
            self._boundary_mono = time.monotonic()
            self._rounds_pending = 0
            self._dispatch_s_pending = 0.0
            # a fresh arm starts a fresh measurement: stale samples from
            # the PREVIOUS armed engine must not be read as this one's
            # (the probe driver snapshots after every probe — a probe
            # that never closes a boundary reports None, not its
            # predecessor's throughput)
            self._last_mfu = None
            self._last_tflops = None

    def clear_samples(self) -> None:
        """Drop the last MFU/TFLOPs samples without disarming — the
        probe driver calls this before each probe so a probe that never
        closes a boundary (arming failed, run too short) reports None
        instead of its predecessor's throughput."""
        with self._lock:
            self._last_mfu = None
            self._last_tflops = None

    def set_peak_flops(self, peak_flops: float) -> None:
        """CLI override (``--peak_flops``): sticks across later
        ``arm_model`` calls; 0 keeps the device-kind estimate."""
        if peak_flops and peak_flops > 0:
            with self._lock:
                self._peak_override = float(peak_flops)
                self._peak_flops = self._peak_override

    # ---------- the dispatch boundary (engines/program.py) ----------

    def note_compile(self, engine: str, program: str,
                     recompile: bool = False) -> None:
        """One program build. ``recompile=True`` marks a rebuild of the
        SAME cache key mid-run — the storm signal: counted, flight-
        recorded, warning-logged (capped). The counter increment IS the
        measurement ``RoundProgram.built`` mirrors (one bookkeeping
        path; tests/test_program.py pins them equal)."""
        obs_metrics.counter(
            "nidt_compiles_total",
            "compiled round-program builds by engine and program "
            "variant (engines/program.py); a variant compiling more "
            "than once mid-run is a recompile storm",
            labelnames=("engine", "program")).labels(
            engine=engine, program=program).inc()
        with self._lock:
            self._total_compiles += 1
            if recompile:
                self._total_recompiles += 1
                warn = self._storm_warnings < _MAX_STORM_WARNINGS
                self._storm_warnings += 1
                n = self._total_recompiles
        if recompile:
            # scrapeable storm evidence (ISSUE 15): the recompile-storm
            # anomaly rule (obs/rules.py) judges this counter — the
            # health() block alone is not a metric series a rule or a
            # Prometheus alert can watch
            obs_metrics.counter(
                "nidt_recompiles_total",
                "mid-run rebuilds of an already-built program variant "
                "(plan-cache thrash / shape leak — the recompile "
                "storm)",
                labelnames=("engine", "program")).labels(
                engine=engine, program=program).inc()
            obs_flight.record("recompile", engine=engine,
                              program=program, total=n)
            if warn:
                log.warning(
                    "compute: program %s/%s RECOMPILED mid-run "
                    "(recompile #%d this process) — a plan-cache "
                    "eviction or shape leak is paying a fresh XLA "
                    "compile on the hot path (nidt_compiles_total; "
                    "flight ring has the event)", engine, program, n)

    def note_dispatch(self, engine: str, program: str, dur_s: float,
                      rounds: int = 1, phase: str = "execute") -> None:
        """One compiled-program invocation: ``dur_s`` is host wall
        around the call (trace+compile on ``phase="compile"``, enqueue
        on ``"execute"`` — never device time, never a sync), ``rounds``
        the federated rounds the dispatch carries (K for fused
        windows) — the MFU numerator accumulates
        ``rounds * flops_per_round`` until the next boundary."""
        obs_metrics.histogram(
            "nidt_dispatch_ms",
            "host wall per compiled-program invocation at the dispatch "
            "boundary (obs/compute.py): trace+compile on "
            "phase=\"compile\", enqueue on phase=\"execute\" (async "
            "dispatch — device time lives on the XLA timeline)",
            labelnames=("engine", "program", "phase"),
            buckets=DISPATCH_MS_BUCKETS).labels(
            engine=engine, program=program, phase=phase).observe(
            dur_s * 1e3)
        with self._lock:
            self._total_dispatches += 1
            self._last_dispatch_mono = time.monotonic()
            if phase == "compile":
                self._last_compile_s = float(dur_s)
            if engine == self._armed_engine:
                self._rounds_pending += int(rounds)
                self._dispatch_s_pending += float(dur_s)

    def boundary(self, engine: str) -> float | None:
        """Close one boundary-to-boundary window and publish the
        derived gauges. Called from ``publish_stat_info`` — a host
        point where the driver ALREADY blocked on device results, so
        every dispatch accumulated since the last boundary has
        finished and ``flops / wall`` is an honest sustained rate.
        Returns the MFU sample (None when unarmed / unknown peak /
        empty window)."""
        now = time.monotonic()
        with self._lock:
            if engine != self._armed_engine or self._boundary_mono is None:
                return None
            wall = now - self._boundary_mono
            rounds = self._rounds_pending
            self._boundary_mono = now
            self._rounds_pending = 0
            self._dispatch_s_pending = 0.0
            if rounds <= 0 or wall <= 0 or self._flops_per_round <= 0:
                return None
            flops_s = rounds * self._flops_per_round / wall
            self._last_tflops = flops_s / 1e12
            mfu = (flops_s / self._peak_flops
                   if self._peak_flops > 0 else None)
            self._last_mfu = mfu
        obs_metrics.gauge(
            "nidt_sustained_tflops",
            "sustained analytic training TFLOP/s over the last host-"
            "boundary window (ops/flops.py numerator / synced wall)",
            labelnames=("engine",)).labels(engine=engine).set(
            self._last_tflops)
        if mfu is not None:
            obs_metrics.gauge(
                "nidt_mfu",
                "model FLOPs utilization over the last host-boundary "
                "window: analytic training FLOP/s over the device "
                "peak (obs/compute.peak_flops_estimate; "
                "NIDT_PEAK_FLOPS / --peak_flops override)",
                labelnames=("engine",)).labels(engine=engine).set(mfu)
        return mfu

    # ---------- liveness (the /healthz compute block) ----------

    def health(self) -> dict:
        """The ``/healthz`` ``compute`` block: a wedged-dispatch
        federation shows a growing ``last_dispatch_age_s`` with stalled
        dispatch/compile counts; a slow one keeps the age bounded."""
        with self._lock:
            age = (None if self._last_dispatch_mono is None
                   else round(time.monotonic() - self._last_dispatch_mono,
                              3))
            return {
                "last_dispatch_age_s": age,
                "dispatches": self._total_dispatches,
                "compiles": self._total_compiles,
                "recompiles": self._total_recompiles,
                "last_compile_s": self._last_compile_s,
                "last_mfu": self._last_mfu,
                "last_sustained_tflops": self._last_tflops,
                "peak_flops": self._peak_flops or None,
                "armed_engine": self._armed_engine,
            }

    def snapshot(self) -> dict:
        """Artifact-facing state (the profile-session driver records
        it per probe)."""
        h = self.health()
        h.pop("last_dispatch_age_s", None)
        return h


#: the process-global profiler every dispatch wrapper feeds
PROFILER = ComputeProfiler()

#: module-level conveniences (instrumentation-site spelling)
note_compile = PROFILER.note_compile
note_dispatch = PROFILER.note_dispatch
boundary = PROFILER.boundary
arm_model = PROFILER.arm_model
health = PROFILER.health


def compiles_total(engine: str | None = None,
                   program: str | None = None) -> float:
    """Sum of ``nidt_compiles_total`` cells matching the filters — the
    single-measurement read the compiled-programs-per-window pins use
    (tests/test_program.py)."""
    snap = obs_metrics.REGISTRY.snapshot().get("nidt_compiles_total")
    if not snap:
        return 0.0
    total = 0.0
    for cell in snap["values"]:
        lb = cell["labels"]
        if engine is not None and lb.get("engine") != engine:
            continue
        if program is not None and lb.get("program") != program:
            continue
        total += float(cell["value"])
    return total


# ---------------------------------------------------------------------------
# XLA cost/memory accounting (AOT — the probe driver and parity test)
# ---------------------------------------------------------------------------


def _flops_sample_struct(trainer, input_shape: tuple[int, ...]):
    """Abstract ``[1, *spatial(, C)]`` sample at the shape the model
    applies (mirrors ``LocalTrainer._prep``'s channel completion
    without touching a real array)."""
    import jax
    import jax.numpy as jnp

    shape = (1, *input_shape)
    rank = getattr(trainer.model, "input_rank", None)
    if rank is not None and len(shape) == rank - 1:
        shape = shape + (1,)
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def analytic_sample_flops(trainer, input_shape: tuple[int, ...],
                          mask_density: dict | None = None) -> float:
    """Analytic training FLOPs per sample (``ops/flops.py``: 3x
    inference, exact for fixed shapes) — computed fully abstractly:
    params come from an ``eval_shape`` of the model init, so nothing is
    materialized even at the flagship 121x145x121 volume."""
    import jax
    import jax.numpy as jnp

    from neuroimagedisttraining_tpu.ops import flops as flops_ops

    cs = jax.eval_shape(
        trainer.init_client_state, jax.random.key(0),
        jax.ShapeDtypeStruct((1, *input_shape), jnp.float32))
    return flops_ops.count_training_flops_per_sample(
        trainer.model, cs.params, _flops_sample_struct(trainer,
                                                       input_shape),
        mask_density=mask_density)


def analyze_train_step(trainer, input_shape: tuple[int, ...],
                       batch_size: int, *, compile: bool = False,
                       publish: bool = True) -> dict:
    """XLA's own accounting of ONE training step, reconciled against
    the analytic counter. AOT and abstract: ``cost_analysis()`` reads
    the unoptimized HLO of ``LocalTrainer.lower_train_step`` (no
    params, no compile, no execution — safe at flagship shape on the
    CPU harness); ``compile=True`` additionally compiles the step and
    reads ``memory_analysis()`` temp/argument/output bytes (the
    working set the remat policy trades against — backend-best-effort,
    None where unsupported).

    Returns ``{"xla_flops", "analytic_flops", "parity_ratio",
    "batch_size", "memory"}`` and (``publish=True``) mirrors them as
    ``nidt_xla_flops`` / ``nidt_flops_parity_ratio`` /
    ``nidt_hbm_peak_bytes{kind}`` gauges. The discrepancy is RECORDED,
    not resolved: the analytic 3x-inference convention undercounts
    backward-pass transpose convs at flagship shape (~1.1x there) and
    overcounts dense-dominated tiny shapes (~0.9x) — the profile
    artifact carries the ratio so neither counter is silently
    trusted."""
    lowered = trainer.lower_train_step(input_shape, batch_size)
    xla_flops = None
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        xla_flops = float(ca.get("flops", 0.0)) or None
    except Exception as e:  # noqa: BLE001 — backend-best-effort surface
        log.info("compute: cost_analysis unavailable (%s)", e)
    analytic = analytic_sample_flops(trainer, input_shape) * batch_size
    ratio = (xla_flops / analytic
             if xla_flops and analytic > 0 else None)
    mem: dict[str, int] | None = None
    if compile:
        try:
            ma = lowered.compile().memory_analysis()
            mem = {
                "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
                "argument_bytes": int(
                    getattr(ma, "argument_size_in_bytes", 0)),
                "output_bytes": int(
                    getattr(ma, "output_size_in_bytes", 0)),
            }
            mem["peak_bytes"] = (mem["temp_bytes"]
                                 + mem["argument_bytes"]
                                 + mem["output_bytes"])
        except Exception as e:  # noqa: BLE001 — backend-best-effort
            log.info("compute: memory_analysis unavailable (%s)", e)
            mem = None
    out = {
        "batch_size": int(batch_size),
        "xla_flops": xla_flops,
        "analytic_flops": analytic,
        "parity_ratio": round(ratio, 4) if ratio is not None else None,
        "memory": mem,
    }
    if publish:
        if xla_flops is not None:
            obs_metrics.gauge(
                "nidt_xla_flops",
                "XLA cost_analysis FLOPs of one lowered training step "
                "(obs/compute.analyze_train_step)").set(xla_flops)
        if ratio is not None:
            obs_metrics.gauge(
                "nidt_flops_parity_ratio",
                "XLA cost_analysis FLOPs over the analytic "
                "ops/flops.py count for one training step (the "
                "recorded-not-trusted reconciliation)").set(ratio)
        if mem is not None:
            g = obs_metrics.gauge(
                "nidt_hbm_peak_bytes",
                "XLA memory_analysis bytes of one compiled training "
                "step by kind (temp = activation working set, the "
                "number remat trades against)",
                labelnames=("kind",))
            for kind in ("temp_bytes", "argument_bytes",
                         "output_bytes", "peak_bytes"):
                g.labels(kind=kind.removesuffix("_bytes")).set(
                    mem[kind])
    return out
