"""Declarative anomaly-rule engine: metric streams -> verdicts (ISSUE 15).

The telemetry stack measures everything and judges nothing: a diverging
federation, a recompile storm, or a staleness runaway is visible only
to a human reading ``/metrics``. This module closes that gap with rules
as DATA — each rule is a metric selector (name + label match), a window
aggregation over the last N observations, a comparator, a threshold, a
severity, and a ``for_rounds`` debounce — evaluated at host boundaries
against registry snapshots (the per-process registry on engines and
servers; the fan-in-MERGED snapshot on the sharded ingest root, so a
rule can fire on a worker's labeled series).

Outcomes of one evaluation:

- ``nidt_alert{rule, severity}`` gauge per rule — 1 while firing, 0
  otherwise (the series EXISTS from the first evaluation either way,
  which is what the chaos smoke's mid-run scrape asserts);
- a flight-ring ``alert`` event on every rising edge (``alert_clear``
  on the fall) — the post-mortem timeline;
- a ``health`` block for ``/healthz``: ``ok`` / ``degraded`` (a warn
  rule firing) / ``critical``;
- a machine-readable end-of-run ``verdict()`` — what ``--health_gate``
  exits nonzero on and ``analysis/run_report.py`` joins;
- a REFLEX dispatch (ISSUE 20) on every rising edge of a rule that
  declares an ``action``: the name resolves against the registry in
  ``obs/actions.py`` at startup and dispatches through the armed
  action bus — gated by ``--actions {off,dry_run,on}``.

Validation is a STARTUP contract (the health-rule-discipline
satellite): every rule's metric must be in the declared-name set
(``obs/names.py DECLARED``); an unknown name — built-in or JSON-loaded
via ``--health_rules`` — raises immediately with the known-names list,
never mid-run as a silently-never-firing rule.

Semantics worth pinning down:

- comparator vs NaN: every comparison with NaN is False, so a poisoned
  gauge never FIRES a rule — the non-finite upload guard carries that
  failure mode separately;
- a rule whose metric has no samples yet simply does not evaluate that
  boundary (and its debounce counter resets): absence of evidence is
  not an anomaly;
- histogram cells evaluate as their p99 (interpolated from the
  cumulative buckets) — the staleness-runaway rule's spelling;
- multiple label cells matching one selector reduce with the rule's
  ``agg`` (max by default: "any silo over threshold" semantics).

HOST-BOUNDARY RULE: evaluation reads clocks and mutates the registry —
never call from inside a traced body (nidtlint ``obs-discipline``).
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
from collections import deque
from typing import Any, Iterable, Mapping

from neuroimagedisttraining_tpu.obs import actions as obs_actions
from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as N

__all__ = [
    "HealthRule", "RuleEngine", "builtin_rules", "load_rules",
    "configure", "disarm", "active", "observe_boundary", "health_block",
    "OPS", "WINDOWS", "SEVERITIES",
]

#: comparators a rule may name (NaN fails them all — see module doc)
OPS = (">", ">=", "<", "<=", "==", "!=")
#: window aggregations over the last ``n`` observations
WINDOWS = ("last", "mean", "max", "min", "delta")
SEVERITIES = ("warn", "critical")
#: label-cell reductions when one selector matches several series
AGGS = ("max", "min", "sum")

#: timeline ring bound (evictions are counted, never silent)
TIMELINE_CAP = 512


@dataclasses.dataclass(frozen=True)
class HealthRule:
    """One rule-as-data row. ``labels`` is a subset match: a cell fires
    the selector when every named label equals the cell's value (extra
    cell labels — ``worker`` on fan-in-merged snapshots — are
    ignored, which is exactly how a root rule fires on a worker's
    series)."""

    name: str
    metric: str
    op: str
    threshold: float
    labels: tuple[tuple[str, str], ...] = ()
    window: str = "last"
    n: int = 1
    severity: str = "warn"
    for_rounds: int = 1
    agg: str = "max"
    description: str = ""
    #: optional flight-ring event kind recorded on every RISING edge in
    #: addition to the standard ``alert`` event — how a rule names the
    #: operator action it recommends (the autotuner's mfu-below-recipe
    #: rule records ``retune_recommended``; tune/recipe.py)
    on_fire_event: str = ""
    #: optional REFLEX action (obs/actions.py BUILTIN_ACTIONS)
    #: dispatched through the armed action bus on every rising edge —
    #: how a rule DOES something instead of only alerting (ISSUE 20);
    #: gated by ``--actions {off,dry_run,on}``, validated at startup
    action: str = ""

    def validate(self, known: frozenset[str]) -> None:
        if self.metric not in known:
            raise ValueError(
                f"health rule {self.name!r} references unknown metric "
                f"{self.metric!r}; declared metric names "
                f"(obs/names.py): {sorted(known)}")
        if self.op not in OPS:
            raise ValueError(
                f"health rule {self.name!r}: unknown comparator "
                f"{self.op!r} (have {OPS})")
        if self.window not in WINDOWS:
            raise ValueError(
                f"health rule {self.name!r}: unknown window "
                f"{self.window!r} (have {WINDOWS})")
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"health rule {self.name!r}: unknown severity "
                f"{self.severity!r} (have {SEVERITIES})")
        if self.agg not in AGGS:
            raise ValueError(
                f"health rule {self.name!r}: unknown cell aggregation "
                f"{self.agg!r} (have {AGGS})")
        if self.n < 1 or self.for_rounds < 1:
            raise ValueError(
                f"health rule {self.name!r}: window n and for_rounds "
                f"must be >= 1 (got n={self.n}, "
                f"for_rounds={self.for_rounds})")
        if self.window == "delta" and self.n < 2:
            raise ValueError(
                f"health rule {self.name!r}: window 'delta' needs "
                f"n >= 2 (last - first of the window)")
        if not math.isfinite(float(self.threshold)):
            raise ValueError(
                f"health rule {self.name!r}: threshold must be finite")
        if self.action and self.action not in obs_actions.BUILTIN_ACTIONS:
            raise ValueError(
                f"health rule {self.name!r}: unknown action "
                f"{self.action!r}; registered actions "
                f"(obs/actions.py BUILTIN_ACTIONS): "
                f"{sorted(obs_actions.BUILTIN_ACTIONS)}")


def _hist_p99(cell: Mapping[str, Any]) -> float | None:
    """p99 from a snapshot histogram cell (per-bucket counts keyed by
    formatted upper bound + '+Inf'), linearly interpolated inside the
    crossing bucket; the +Inf bucket evaluates as its lower edge."""
    count = int(cell.get("count", 0))
    if count <= 0:
        return None
    buckets = dict(cell.get("buckets", {}))
    inf = int(buckets.pop("+Inf", 0))
    edges = sorted((float(k), int(v)) for k, v in buckets.items())
    target = 0.99 * count
    acc = 0
    lo = 0.0
    for edge, n_in in edges:
        if acc + n_in >= target and n_in > 0:
            frac = (target - acc) / n_in
            return lo + frac * (edge - lo)
        acc += n_in
        lo = edge
    # crossing lands in +Inf: report the last finite edge (the honest
    # "at least this much" answer a bounded histogram can give)
    return lo if (edges or inf) else None


def _cell_value(kind: str, value: Any) -> float | None:
    if kind == "histogram":
        if isinstance(value, Mapping):
            return _hist_p99(value)
        return None
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


def _compare(op: str, v: float, thr: float) -> bool:
    # NaN: every comparison below is False, including == (and != is
    # deliberately evaluated the same guarded way)
    if math.isnan(v):
        return False
    if op == ">":
        return v > thr
    if op == ">=":
        return v >= thr
    if op == "<":
        return v < thr
    if op == "<=":
        return v <= thr
    if op == "==":
        return v == thr
    return v != thr


class _RuleState:
    __slots__ = ("window", "consec", "firing", "fires", "last_value",
                 "last_round")

    def __init__(self, n: int):
        self.window: deque = deque(maxlen=n)
        self.consec = 0
        self.firing = False
        self.fires = 0
        self.last_value: float | None = None
        self.last_round: int | None = None


class RuleEngine:
    """Holds the rule set + per-rule evaluation state. Thread-safe:
    dispatch threads evaluate boundaries while HTTP scrape threads read
    ``health_block()``."""

    def __init__(self, rules: Iterable[HealthRule],
                 known: frozenset[str] = N.DECLARED):
        rules = list(rules)
        seen: set[str] = set()
        for r in rules:
            r.validate(known)
            if r.name in seen:
                raise ValueError(
                    f"health rule {r.name!r} declared twice — rule "
                    "names are the alert label and must be unique")
            seen.add(r.name)
        self.rules = tuple(rules)
        self._by_name = {r.name: r for r in self.rules}
        self._lock = threading.Lock()
        self._state = {r.name: _RuleState(r.n) for r in rules}
        self._rounds_evaluated = 0
        self._last_round: int | None = None
        self._worst = "ok"
        self._timeline: deque = deque(maxlen=TIMELINE_CAP)
        self._timeline_evicted = 0
        self._alert_gauge = obs_metrics.gauge(
            N.ALERT,
            "anomaly-rule verdicts (obs/rules.py): 1 while the rule's "
            "debounced condition holds, 0 otherwise",
            labelnames=("rule", "severity"))

    # ---- evaluation (host boundaries) ----

    def observe(self, round_idx: int, snapshot: dict | None = None
                ) -> list[dict]:
        """Evaluate every rule against ``snapshot`` (default: the
        process registry) at boundary ``round_idx``. Re-observing an
        already-evaluated round is a no-op (the engine flush path and
        ``publish_stat_info`` may both land on the same boundary).
        Returns the edge events of this evaluation."""
        snap = (snapshot if snapshot is not None
                else obs_metrics.REGISTRY.snapshot())
        edges: list[dict] = []
        with self._lock:
            if self._last_round is not None \
                    and round_idx <= self._last_round:
                return []
            self._last_round = int(round_idx)
            self._rounds_evaluated += 1
            for rule in self.rules:
                st = self._state[rule.name]
                v = self._select(rule, snap)
                if v is None:
                    # no samples yet: not an anomaly, and the debounce
                    # restarts when evidence reappears
                    st.consec = 0
                    self._settle(rule, st, round_idx, edges,
                                 firing=False)
                    continue
                st.window.append(v)
                st.last_value = v
                st.last_round = int(round_idx)
                wv = self._window_value(rule, st)
                breach = _compare(rule.op, wv, float(rule.threshold))
                st.consec = st.consec + 1 if breach else 0
                self._settle(rule, st, round_idx, edges,
                             firing=st.consec >= rule.for_rounds,
                             value=wv)
        for e in edges:
            obs_flight.record(e["kind"], rule=e["rule"],
                              severity=e["severity"], round=e["round"],
                              value=e.get("value"))
            r = self._by_name.get(e["rule"])
            if e["kind"] == "alert" and r is not None and r.on_fire_event:
                obs_flight.record(r.on_fire_event, rule=e["rule"],
                                  round=e["round"],
                                  value=e.get("value"))
            if e["kind"] == "alert" and r is not None and r.action:
                # reflex dispatch (ISSUE 20): the rising edge DOES
                # something through the armed action bus (a no-op when
                # none is armed; dry_run only logs). Outside the lock —
                # handlers may re-enter observability paths.
                obs_actions.on_alert(r.action, rule=r.name,
                                     severity=r.severity,
                                     round_idx=e["round"],
                                     value=e.get("value"))
        return edges

    def _select(self, rule: HealthRule, snap: dict) -> float | None:
        m = snap.get(rule.metric)
        if not m:
            return None
        want = dict(rule.labels)
        vals: list[float] = []
        for cell in m.get("values", ()):
            lb = cell.get("labels", {})
            if any(lb.get(k) != v for k, v in want.items()):
                continue
            cv = _cell_value(m.get("kind", "gauge"), cell.get("value"))
            if cv is not None:
                vals.append(cv)
        if not vals:
            return None
        if rule.agg == "min":
            return min(vals)
        if rule.agg == "sum":
            return float(sum(vals))
        return max(vals)

    @staticmethod
    def _window_value(rule: HealthRule, st: _RuleState) -> float:
        w = list(st.window)
        if rule.window == "mean":
            return float(sum(w) / len(w))
        if rule.window == "max":
            return max(w)
        if rule.window == "min":
            return min(w)
        if rule.window == "delta":
            return w[-1] - w[0]
        return w[-1]

    def _settle(self, rule: HealthRule, st: _RuleState, round_idx: int,
                edges: list[dict], firing: bool,
                value: float | None = None) -> None:
        self._alert_gauge.labels(rule=rule.name,
                                 severity=rule.severity).set(
            1.0 if firing else 0.0)
        if firing and not st.firing:
            st.fires += 1
            if rule.severity == "critical":
                self._worst = "critical"
            elif self._worst == "ok":
                self._worst = "degraded"
            edges.append({"kind": "alert", "rule": rule.name,
                          "severity": rule.severity,
                          "round": int(round_idx), "value": value})
        elif st.firing and not firing:
            edges.append({"kind": "alert_clear", "rule": rule.name,
                          "severity": rule.severity,
                          "round": int(round_idx), "value": value})
        st.firing = firing
        if edges and edges[-1]["round"] == int(round_idx) \
                and edges[-1]["rule"] == rule.name:
            if len(self._timeline) == self._timeline.maxlen:
                self._timeline_evicted += 1
            self._timeline.append(dict(edges[-1]))

    # ---- reports ----

    def status(self) -> str:
        with self._lock:
            return self._status_locked()

    def _status_locked(self) -> str:
        worst_now = "ok"
        for rule in self.rules:
            if self._state[rule.name].firing:
                if rule.severity == "critical":
                    return "critical"
                worst_now = "degraded"
        return worst_now

    def health_block(self) -> dict:
        """The ``/healthz`` ``health`` block."""
        with self._lock:
            firing = {r.name: r.severity for r in self.rules
                      if self._state[r.name].firing}
            return {"status": self._status_locked(),
                    "worst_status": self._worst,
                    "firing": firing,
                    "rules": len(self.rules),
                    "rounds_evaluated": self._rounds_evaluated}

    def verdict(self) -> dict:
        """The machine-readable end-of-run document ``--health_gate``
        judges (``worst_status`` — a recovered run still failed its
        gate) and ``analysis/run_report.py`` joins (the timeline)."""
        with self._lock:
            rules = []
            for r in self.rules:
                st = self._state[r.name]
                rules.append({
                    "name": r.name, "metric": r.metric,
                    "severity": r.severity, "op": r.op,
                    "threshold": r.threshold, "window": r.window,
                    "n": r.n, "for_rounds": r.for_rounds,
                    "firing": st.firing, "fires": st.fires,
                    "last_value": st.last_value,
                    "last_round": st.last_round,
                    "description": r.description,
                    "action": r.action,
                })
            return obs_metrics._json_safe({
                "status": self._status_locked(),
                "worst_status": self._worst,
                "rounds_evaluated": self._rounds_evaluated,
                "alerts_total": sum(r["fires"] for r in rules),
                "rules": rules,
                "timeline": list(self._timeline),
                "timeline_evicted": self._timeline_evicted,
            })


# ---------------------------------------------------------------------------
# built-in manifest
# ---------------------------------------------------------------------------


def builtin_rules(dp_epsilon_budget: float = 0.0, comm_round: int = 200,
                  max_staleness: int = 20) -> list[HealthRule]:
    """The shipped rule manifest — one rule per failure mode the
    motivation names. Thresholds are deliberately conservative (verdict
    tripwires, not noise detectors); ``--health_rules`` JSON manifests
    extend or replace them."""
    rules = [
        HealthRule(
            name="client-divergence", metric=N.HEALTH_COSINE_MIN,
            op="<", threshold=-0.2, severity="critical",
            action="quarantine_silo",
            description=(
                "a client update points AGAINST the aggregated update "
                "(sign-flip Byzantine, or non-IID divergence past what "
                "FedProx-style proximal terms absorb)")),
        HealthRule(
            name="defense-escalation", metric=N.HEALTH_COSINE_MIN,
            op="<", threshold=-0.5, severity="warn",
            action="escalate_defense",
            description=(
                "a strongly anti-aligned client update (cosine < -0.5) "
                "is an attack signature, not non-IID drift — escalate "
                "the robust-aggregation ladder one rung (none -> "
                "norm_diff_clipping -> trimmed_mean)")),
        HealthRule(
            name="update-norm-collapse",
            metric=N.HEALTH_UPDATE_NORM_MED, op="<", threshold=1e-7,
            for_rounds=2, severity="warn",
            description=(
                "median client update norm ~ 0: local training is a "
                "no-op (lr underflow, dead data feed, all-masked "
                "params)")),
        HealthRule(
            name="update-norm-blowup", metric=N.HEALTH_DIVERGENCE,
            op=">", threshold=50.0, for_rounds=2, severity="warn",
            action="freeze_rollback",
            description=(
                "max/median client update-norm dispersion: one silo's "
                "update dwarfs the cohort (diverging optimizer or "
                "scale attack below the non-finite guard)")),
        HealthRule(
            name="dead-mask", metric=N.HEALTH_MASK_DENSITY, op="<",
            threshold=0.01, severity="critical",
            description=(
                "a salientgrads/dispfl/subavg mask lost (nearly) every "
                "weight — the NaN-poisoned fire/regrow footprint")),
        HealthRule(
            name="recompile-storm", metric=N.RECOMPILES_TOTAL, op=">=",
            threshold=3, severity="warn",
            description=(
                "the same compiled program rebuilt mid-run 3+ times "
                "(plan-cache thrash / shape leak) — every hot-path "
                "dispatch is paying a fresh XLA compile")),
        HealthRule(
            name="mfu-floor", metric=N.MFU, op="<", threshold=0.02,
            for_rounds=3, severity="warn",
            description=(
                "sustained MFU under 2% for 3 boundaries: the chips "
                "are idling (host-bound feed, serialized dispatch); "
                "no samples off-chip, so the rule is TPU-only by "
                "construction")),
        HealthRule(
            name="staleness-runaway", metric=N.ASYNC_STALENESS, op=">",
            threshold=max(1.0, 0.8 * float(max_staleness)),
            for_rounds=2, severity="warn", action="adapt_buffer",
            description=(
                "p99 accepted-upload staleness near the admission "
                "bound: the buffered server is aggregating history")),
        HealthRule(
            name="region-staleness-runaway", metric=N.REGION_STALENESS,
            op=">", threshold=max(1.0, 0.8 * float(max_staleness)),
            for_rounds=2, severity="warn", action="adapt_buffer",
            description=(
                "a regional sub-aggregator's batch staleness near the "
                "admission bound for 2 boundaries: that region is "
                "shipping history — its workers are wedged, its uplink "
                "is backed up, or its client population stalled "
                "(ISSUE 18; any-region-over semantics via the max "
                "cell aggregation)")),
        HealthRule(
            name="quarantine-burst", metric=N.BYZ_QUARANTINES,
            op=">=", threshold=2, window="delta", n=5, severity="warn",
            action="escalate_defense",
            description=(
                "2+ quarantines entered within 5 boundaries — a "
                "coordinated anomaly, not one flaky silo")),
        # -- serving plane (ISSUE 17): evaluated at the engine's
        #    dispatch boundary inside each serve worker --
        HealthRule(
            name="serve-p99-latency", metric=N.SERVE_LATENCY_MS,
            op=">", threshold=1000.0,
            labels=(("stage", "dispatch"),), for_rounds=2,
            severity="warn",
            description=(
                "p99 dispatch-stage serving latency above 1s for 2 "
                "boundaries: the compiled forward no longer keeps up "
                "with the offered load (bucket misconfiguration, "
                "recompile storm, or host contention)")),
        HealthRule(
            name="serve-queue-runaway", metric=N.SERVE_QUEUE_DEPTH,
            op=">", threshold=512.0, for_rounds=2, severity="warn",
            description=(
                "512+ requests queued behind the micro-batcher for 2 "
                "boundaries: arrival rate exceeds dispatch throughput "
                "and waiters are compounding (/predict is about to "
                "time out)")),
    ]
    if dp_epsilon_budget > 0:
        rules.append(HealthRule(
            name="dp-budget-exceeded", metric=N.DP_EPSILON, op=">=",
            threshold=float(dp_epsilon_budget), severity="critical",
            description=(
                "the RDP ledger crossed --dp_epsilon_budget: every "
                "further round spends privacy the run was not "
                "budgeted for")))
        rules.append(HealthRule(
            name="dp-burn-rate", metric=N.DP_EPSILON_PER_ROUND, op=">",
            threshold=2.0 * float(dp_epsilon_budget)
            / max(1, int(comm_round)),
            for_rounds=3, severity="warn",
            description=(
                "per-round epsilon burn exceeds 2x the uniform "
                "budget/comm_round rate for 3 boundaries — the run "
                "will cross the budget early")))
    return rules


def load_rules(path: str) -> list[HealthRule]:
    """``--health_rules`` JSON manifest: a list of rule objects with
    the :class:`HealthRule` field names (``labels`` as an object).
    Schema errors and unknown metric names raise at startup."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, list):
        raise ValueError(
            f"health-rule manifest {path}: expected a JSON list of "
            f"rule objects, got {type(doc).__name__}")
    fields = {f.name for f in dataclasses.fields(HealthRule)}
    out = []
    for i, row in enumerate(doc):
        if not isinstance(row, dict):
            raise ValueError(
                f"health-rule manifest {path}[{i}]: expected an "
                f"object, got {type(row).__name__}")
        unknown = set(row) - fields
        if unknown:
            raise ValueError(
                f"health-rule manifest {path}[{i}]: unknown fields "
                f"{sorted(unknown)} (have {sorted(fields)})")
        missing = {"name", "metric", "op", "threshold"} - set(row)
        if missing:
            raise ValueError(
                f"health-rule manifest {path}[{i}]: missing required "
                f"fields {sorted(missing)}")
        labels = row.get("labels", {})
        if not isinstance(labels, dict):
            raise ValueError(
                f"health-rule manifest {path}[{i}]: labels must be an "
                "object")
        row = dict(row, labels=tuple(sorted(
            (str(k), str(v)) for k, v in labels.items())))
        out.append(HealthRule(**row))
    return out


# ---------------------------------------------------------------------------
# the process-global engine (armed by the CLIs; tests build their own)
# ---------------------------------------------------------------------------

_ACTIVE: RuleEngine | None = None
_ACTIVE_LOCK = threading.Lock()


def configure(rules: Iterable[HealthRule] | None = None, *,
              manifest_path: str = "", dp_epsilon_budget: float = 0.0,
              comm_round: int = 200, max_staleness: int = 20,
              extra_rules: Iterable[HealthRule] | None = None
              ) -> RuleEngine:
    """Arm the process-global rule engine: the built-in manifest
    (parameterized by the run's budget/schedule), plus — or replaced
    by — an explicit rule list / ``--health_rules`` JSON manifest
    (manifest rules EXTEND the built-ins; same-named rules override).
    ``extra_rules`` are programmatic additions merged AFTER the
    built-ins and BEFORE the manifest (a recipe's drift rule — the
    operator's JSON still wins)."""
    global _ACTIVE
    base = {r.name: r for r in (rules if rules is not None
                                else builtin_rules(
                                    dp_epsilon_budget=dp_epsilon_budget,
                                    comm_round=comm_round,
                                    max_staleness=max_staleness))}
    for r in (extra_rules or ()):
        base[r.name] = r
    if manifest_path:
        for r in load_rules(manifest_path):
            base[r.name] = r
    eng = RuleEngine(base.values())
    with _ACTIVE_LOCK:
        _ACTIVE = eng
    return eng


def disarm() -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = None


def active() -> RuleEngine | None:
    with _ACTIVE_LOCK:
        return _ACTIVE


def observe_boundary(round_idx: int, snapshot: dict | None = None
                     ) -> list[dict]:
    """Evaluate the armed engine at a host boundary; a no-op (empty
    edge list) when no engine is armed — instrumentation sites call
    this unconditionally."""
    eng = active()
    return eng.observe(round_idx, snapshot) if eng is not None else []


def health_block() -> dict:
    """The ``/healthz`` ``health`` block — ``{"status": "unarmed"}``
    when no rule engine is configured."""
    eng = active()
    return (eng.health_block() if eng is not None
            else {"status": "unarmed"})
