"""Training-health publication: the host side of the in-dispatch
federation statistics (ISSUE 15).

The statistics themselves are COMPUTED inside the jitted round body —
``engines/program.py`` emits a small stats pytree as trailing round
outputs (per-client update L2 norms, cosine of each client update
against the aggregated update, update-norm dispersion, global
param/aggregate-update norms, mask density/overlap/churn for the
masked engines), threaded through the fused-K scan exactly like
``loss``/``n_bad``. The driver queues the device arrays per dispatch
(``FederatedEngine._note_health``) and drains them in the SAME batched
``device_get`` as the non-finite counts at the existing
``_flush_nonfinite`` host boundary — zero added device syncs, the PR 14
discipline.

This module is what happens AFTER the fetch: each drained round's host
scalars become ``nidt_health_*`` gauges (and the per-client norm
histogram), labeled by engine, at the host boundary where the driver
already blocked. The name constants live in ``obs/names.py`` (the
declared set the rule engine validates against); the anomaly rules that
consume these series live in ``obs/rules.py``.

HOST-BOUNDARY RULE: everything here mutates the registry — never call
from inside a traced body (nidtlint ``obs-discipline``). The traced
half deliberately lives in ``engines/program.py``: a jnp helper in this
package would trip the same lint that protects it.
"""

from __future__ import annotations

import math
from typing import Any, Iterable, Mapping

import numpy as np

from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as N

__all__ = [
    "UPDATE_STAT_NAMES", "MASK_STAT_NAMES", "publish_round_stats",
    "publish_mask_density", "fallback_block", "health_gauge",
    "UPDATE_NORM_BUCKETS",
]

#: stats the builder's default leg emits per round for engines whose
#: carry holds the global model (``{"params", "batch_stats"}``); order
#: is the flattened-output order (engines/program.py appends them after
#: the declared outputs and the EF tail, and the dispatch wrapper
#: strips them back off before the legacy-arity drivers see the tuple)
UPDATE_STAT_NAMES: tuple[str, ...] = (
    "h_up_norms",    # [C] per-client update L2 norms vs the broadcast
    "h_up_max",      # max over clients
    "h_up_med",      # median over clients
    "h_cos_min",     # min leave-one-out cosine: client update vs the
                     # aggregate minus its own weighted contribution
                     # (self-mass would flip a sign-flipper back to +)
    "h_cos_mean",    # mean leave-one-out cosine over the cohort
    "h_disp",        # dispersion: max norm / median norm
    "h_gnorm",       # L2 norm of the NEW global params
    "h_agg_up",      # L2 norm of the aggregated update (the round's
                     # pseudo-gradient — "global grad norm" at the
                     # server, where per-example grads never exist)
    "h_cos",         # [C] per-client leave-one-out cosine vector —
                     # no gauge of its own (publish_round_stats skips
                     # unknown keys); the reflex plane's quarantine
                     # handler reads it host-side to ATTRIBUTE a
                     # client-divergence alert to the offending
                     # sampled client (engines/base.py, ISSUE 20)
)

#: stats a masked engine's ``RoundStages.health`` hook emits
#: (salientgrads/subavg declare exactly these names)
MASK_STAT_NAMES: tuple[str, ...] = (
    "h_mask_density",   # mean kept fraction over clients
    "h_mask_overlap",   # round-over-round kept-weight overlap
    "h_mask_churn",     # 1 - overlap
)

#: buckets for the per-client update-norm histogram: spans collapsed
#: (~1e-6) through diverged (~1e3) updates on the flagship models
UPDATE_NORM_BUCKETS = (1e-6, 1e-4, 1e-3, 1e-2, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0, 25.0, 100.0, 1000.0)

#: stat-name -> (metric name, help) for the scalar gauges
_GAUGE_OF: dict[str, tuple[str, str]] = {
    "h_up_max": (N.HEALTH_UPDATE_NORM_MAX,
                 "max per-client update L2 norm of the round"),
    "h_up_med": (N.HEALTH_UPDATE_NORM_MED,
                 "median per-client update L2 norm of the round"),
    "h_cos_min": (N.HEALTH_COSINE_MIN,
                  "min leave-one-out cosine: each client's update vs "
                  "the aggregated update minus its own contribution "
                  "(a sign-flipping silo reads strongly negative "
                  "here; self-inclusion would mask it)"),
    "h_cos_mean": (N.HEALTH_COSINE_MEAN,
                   "mean leave-one-out cosine of client updates to "
                   "the aggregated update"),
    "h_disp": (N.HEALTH_DIVERGENCE,
               "update-norm dispersion: max / median client update "
               "norm (non-IID divergence blows this up before the "
               "loss shows it)"),
    "h_gnorm": (N.HEALTH_PARAM_NORM,
                "L2 norm of the aggregated global params"),
    "h_agg_up": (N.HEALTH_AGG_UPDATE_NORM,
                 "L2 norm of the aggregated update (the server-side "
                 "pseudo-gradient)"),
    "h_mask_density": (N.HEALTH_MASK_DENSITY,
                       "mean kept fraction of the engine's "
                       "pruning/saliency masks"),
    "h_mask_overlap": (N.HEALTH_MASK_OVERLAP,
                       "round-over-round kept-weight overlap of the "
                       "engine's masks"),
    "h_mask_churn": (N.HEALTH_MASK_CHURN,
                     "round-over-round mask churn (1 - overlap); a "
                     "NaN-poisoned fire/regrow shows as a churn spike "
                     "then a dead mask"),
}


def health_gauge(name: str, help: str) -> obs_metrics.Gauge:
    """An engine-labeled health gauge (idempotent registration)."""
    return obs_metrics.gauge(name, help, labelnames=("engine",))


def _finite(v: Any) -> float | None:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


def publish_round_stats(engine: str, round_idx: int,
                        stats: Mapping[str, Any]) -> None:
    """Publish ONE drained round's host-side stats into the registry.

    ``stats`` maps stat names (``h_*``) to host numpy values — scalars
    for the gauges, the ``[C]`` per-client norm vector for the
    histogram. Values that came back non-finite (a diverged round) are
    still published: NaN in a gauge is itself the signal the
    ``update-norm-collapse``/divergence rules react to via their
    comparator semantics (NaN fails every comparison, so a rule never
    fires ON NaN — the non-finite guard's ``n_bad`` path carries that
    story instead)."""
    for key, v in stats.items():
        if key == "h_up_norms":
            h = obs_metrics.histogram(
                N.HEALTH_UPDATE_NORM,
                "per-client update L2 norms vs the round's broadcast "
                "model (one observe per client per round)",
                labelnames=("engine",), buckets=UPDATE_NORM_BUCKETS)
            for x in np.ravel(np.asarray(v)):
                fx = _finite(x)
                if fx is not None:
                    h.labels(engine=engine).observe(fx)
            continue
        meta = _GAUGE_OF.get(key)
        if meta is None:
            continue  # engine-private stat without a declared gauge
        f = _finite(v)
        health_gauge(*meta).labels(engine=engine).set(
            f if f is not None else float("nan"))
    obs_metrics.gauge(
        N.HEALTH_ROUND,
        "last round whose in-dispatch health stats were published",
        labelnames=("engine",)).labels(engine=engine).set(int(round_idx))


def publish_mask_density(engine: str, round_idx: int,
                         density: float) -> None:
    """Mask density for engines whose masks evolve OUTSIDE a declared
    round body (dispfl's chunked host driver): published from the
    already-existing ``warn_if_masks_collapsed`` host boundary — the
    nnz fetch that call makes anyway is the measurement."""
    f = _finite(density)
    health_gauge(*_GAUGE_OF["h_mask_density"]).labels(
        engine=engine).set(f if f is not None else float("nan"))
    obs_metrics.gauge(
        N.HEALTH_ROUND,
        "last round whose in-dispatch health stats were published",
        labelnames=("engine",)).labels(engine=engine).set(int(round_idx))


def fallback_block(snapshot: dict | None = None) -> dict:
    """The ``/healthz`` fast-path-coverage block (ISSUE 15 satellite):
    ``nidt_fallback_total{plane, engine, reason}`` totals next to the
    PR 14 compute block — a silently-degraded run (everything falling
    back to K=1 unsharded) reads differently from a healthy one at the
    probe. ``snapshot`` defaults to the process registry; pass a
    fan-in-merged snapshot on the sharded ingest root."""
    snap = (snapshot if snapshot is not None
            else obs_metrics.REGISTRY.snapshot())
    m = snap.get(N.FALLBACK_TOTAL) or {}
    rows: list[dict] = []
    by_plane: dict[str, float] = {}
    for cell in m.get("values", ()):
        lb = cell.get("labels", {})
        n = float(cell.get("value", 0.0))
        rows.append({"plane": lb.get("plane", ""),
                     "engine": lb.get("engine", ""),
                     "reason": lb.get("reason", ""), "count": n})
        by_plane[lb.get("plane", "")] = (
            by_plane.get(lb.get("plane", ""), 0.0) + n)
    return {"total": sum(by_plane.values()), "by_plane": by_plane,
            "announcements": rows}


def stat_names_for(carry: Iterable[str],
                   extra: tuple[str, ...] = ()) -> tuple[str, ...]:
    """The health-output name tuple the builder appends for a declared
    round: the default update-stats leg arms when the carry holds the
    global model (the engines whose train stage produces an upload to
    measure), plus the engine's declared extra stat names (mask
    health). Engines without a global model in the carry (local,
    dpsgd's per-client consensus) get only their declared extras —
    there is no broadcast reference to measure updates against."""
    names: tuple[str, ...] = ()
    if {"params", "batch_stats"} <= set(carry):
        names = UPDATE_STAT_NAMES
    return names + tuple(extra)
