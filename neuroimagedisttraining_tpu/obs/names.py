"""The declared metric-name table: every ``nidt_*`` series, one home.

ISSUE 15 (health-rule-discipline): the anomaly-rule engine
(obs/rules.py) turns metric names into VERDICTS, so a typo'd name in a
rule manifest must fail at startup against a known-names list — which
only works if the list actually covers every name the tree publishes.
This module is that list. Each metric name is declared here ONCE as a
constant; instrumentation sites outside ``obs/`` spell the constant,
never the string (nidtlint ``health-metric-literal`` fences the
literal spelling), so a name cannot drift out of the declared set
without the lint catching it.

Registration (kind, labels, help) stays at the instrumentation site —
this table owns NAMES, not schemas: the registry's idempotent
``counter/gauge/histogram`` calls already police kind/label collisions
per process, and centralizing help strings here would put the
documentation a package away from the measurement.
"""

from __future__ import annotations

# -- control-plane transports (distributed/comm.py) --
COMM_BYTES_SENT = "nidt_comm_bytes_sent_total"
COMM_BYTES_RECV = "nidt_comm_bytes_recv_total"
COMM_FRAMES_SENT = "nidt_comm_frames_sent_total"
COMM_FRAMES_RECV = "nidt_comm_frames_recv_total"

# -- synchronous cross-silo server (distributed/cross_silo.py) --
SYNC_UPLOADS = "nidt_sync_uploads_total"
SYNC_ROUND_WALL = "nidt_sync_round_wall_seconds"
SYNC_QUORUM_WAIT = "nidt_sync_quorum_wait_seconds"
SERVER_ROUND = "nidt_server_round"
SERVER_SUSPECTS = "nidt_server_suspects"
BYZ_STRIKES = "nidt_byz_strikes_total"
BYZ_QUARANTINES = "nidt_byz_quarantines_total"
DP_EPSILON_SILO = "nidt_dp_epsilon_silo"

# -- async buffered server (asyncfl/server.py) --
ASYNC_UPLOADS = "nidt_async_uploads_total"
ASYNC_STALENESS = "nidt_async_staleness"
ASYNC_BUFFER_OCCUPANCY = "nidt_async_buffer_occupancy"
ASYNC_BUFFER_K_EFF = "nidt_async_buffer_k_eff"

# -- selector socket core (asyncfl/loop.py) --
SELECTOR_CONNECTIONS = "nidt_selector_connections"
SELECTOR_WRITE_QUEUE = "nidt_selector_write_queue_frames"
BACKPRESSURE_STALLS = "nidt_backpressure_stalls_total"

# -- sharded ingest plane (asyncfl/ingest.py) --
INGEST_HEARTBEATS_SUPPRESSED = "nidt_ingest_heartbeats_suppressed"
INGEST_PENDING_UPLOADS = "nidt_ingest_pending_uploads"
INGEST_WORKERS_LIVE = "nidt_ingest_workers_live"
INGEST_PARTIALS = "nidt_ingest_partials_total"
INGEST_WORKER_UPLOADS = "nidt_ingest_worker_uploads_total"

# -- hierarchical aggregation tier (asyncfl/region.py, ISSUE 18) --
REGION_STALENESS = "nidt_region_staleness"
REGION_PARTIAL_AGE = "nidt_region_partial_age_s"

# -- telemetry fan-in (obs/fanin.py) --
UPLOAD_STAGE_MS = "nidt_upload_stage_ms"
CLIENT_RTT_MS = "nidt_client_rtt_ms"
OBS_WORKER_SNAPSHOT_AGE = "nidt_obs_worker_snapshot_age_s"
OBS_WORKER_ALIVE = "nidt_obs_worker_alive"

# -- compute-plane profiler (obs/compute.py) --
COMPILES_TOTAL = "nidt_compiles_total"
RECOMPILES_TOTAL = "nidt_recompiles_total"
DISPATCH_MS = "nidt_dispatch_ms"
SUSTAINED_TFLOPS = "nidt_sustained_tflops"
MFU = "nidt_mfu"
XLA_FLOPS = "nidt_xla_flops"
FLOPS_PARITY_RATIO = "nidt_flops_parity_ratio"
HBM_PEAK_BYTES = "nidt_hbm_peak_bytes"

# -- engine host boundaries (engines/base.py, engines/program.py) --
STAT = "nidt_stat"
DP_EPSILON = "nidt_dp_epsilon"
DP_EPSILON_PER_ROUND = "nidt_dp_epsilon_per_round"
ENGINE_ROUND = "nidt_engine_round"
FALLBACK_TOTAL = "nidt_fallback_total"

# -- experiment metrics (utils/logging.py) --
EXP_METRIC = "nidt_exp_metric"
EXP_ROUND = "nidt_exp_round"

# -- streamed feed (data/stream.py) --
STREAM_TRANSFER = "nidt_stream_transfer"

# -- training-health plane (ISSUE 15: obs/health.py publishes, the
#    stats are computed inside the round body by engines/program.py) --
HEALTH_UPDATE_NORM = "nidt_health_update_norm"
HEALTH_UPDATE_NORM_MAX = "nidt_health_update_norm_max"
HEALTH_UPDATE_NORM_MED = "nidt_health_update_norm_med"
HEALTH_COSINE_MIN = "nidt_health_cosine_min"
HEALTH_COSINE_MEAN = "nidt_health_cosine_mean"
HEALTH_DIVERGENCE = "nidt_health_divergence"
HEALTH_PARAM_NORM = "nidt_health_param_norm"
HEALTH_AGG_UPDATE_NORM = "nidt_health_agg_update_norm"
HEALTH_MASK_DENSITY = "nidt_health_mask_density"
HEALTH_MASK_OVERLAP = "nidt_health_mask_overlap"
HEALTH_MASK_CHURN = "nidt_health_mask_churn"
HEALTH_ROUND = "nidt_health_round"

# -- serving plane (serve/engine.py, serve/worker.py, serve/server.py) --
SERVE_LATENCY_MS = "nidt_serve_latency_ms"
SERVE_BATCH_OCCUPANCY = "nidt_serve_batch_occupancy"
SERVE_QUEUE_DEPTH = "nidt_serve_queue_depth"
SERVE_REQUESTS = "nidt_serve_requests_total"
SERVE_WORKERS_LIVE = "nidt_serve_workers_live"
SERVE_WORKER_REQUESTS = "nidt_serve_worker_requests_total"

# -- anomaly-rule engine (obs/rules.py) --
ALERT = "nidt_alert"

# -- reflex plane (obs/actions.py, ISSUE 20): rule->action dispatches
#    by action name and outcome status (applied / dry_run / unhandled /
#    skipped / error) --
ACTIONS_TOTAL = "nidt_actions_total"

# -- autotuner recipes (tune/recipe.py): the loaded recipe's recorded
#    score, published so the mfu-below-recipe drift rule's threshold is
#    scrapeable next to the live nidt_mfu it is compared against --
RECIPE_SCORE = "nidt_recipe_score"

#: every declared metric name — the set obs/rules.py validates rule
#: manifests against at startup (unknown names fail with this list)
DECLARED: frozenset[str] = frozenset(
    v for v in list(globals().values())
    if isinstance(v, str) and v.startswith("nidt_"))
