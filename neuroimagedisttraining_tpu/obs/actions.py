"""Reflex plane: the action registry rule verdicts resolve against (ISSUE 20).

The anomaly-rule engine (obs/rules.py) turns metric streams into
verdicts; this module turns verdicts into ACTS. A rule may declare an
``action`` — a name from :data:`BUILTIN_ACTIONS` — and every rising
alert edge of that rule dispatches through the process-global
:class:`ActionBus`:

- ``--actions off``: nothing is dispatched or logged;
- ``--actions dry_run`` (the default): the bus records what WOULD fire
  — an ``action_dry_run`` flight event and an action-log entry — but
  no handler runs, so behavior never changes silently;
- ``--actions on``: the registered handler for the action runs. A
  plane without a handler for the action (``adapt_buffer`` on an
  in-process engine run, ``shrink_mesh`` on a server) logs the
  dispatch as ``unhandled``; a handler that raises logs ``error`` —
  a reflex must never be the thing that kills training.

Handlers are registered by the plane that can realize the action: the
engines register quarantine/escalation/rollback at ``train()`` start
(engines/base.py ``_register_reflexes``), the cross-silo server
registers ``quarantine_silo``, the async buffered server registers
``adapt_buffer`` (distributed/run.py). Registration is latest-wins, so
a driver restart re-arms cleanly.

Every dispatch is flight-recorded with the firing rule as PROVENANCE
and counted in ``nidt_actions_total{action, status}``. The action log
itself is deliberately timestamp-free: two runs of the same seeded
chaos scenario must produce byte-identical logs (the replay
determinism the chaos harness asserts) — the flight ring carries the
clocks separately.

The name table :data:`BUILTIN_ACTIONS` is a pure dict literal, parsed
by nidtlint's ``action-discipline`` rules the same way the autotuner's
``RECIPE_KEYS`` table is: every ``action:`` in a rule manifest must
resolve here, and every name here must be reachable from some rule or
documented in ARCHITECTURE.md.

HOST-BOUNDARY RULE: dispatch mutates the registry and the flight ring
— never call from inside a traced body (nidtlint ``obs-discipline``).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable

from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import names as N

__all__ = [
    "BUILTIN_ACTIONS", "MODES", "ActionBus", "configure", "disarm",
    "active", "register", "on_alert", "record_action", "actions_block",
]

#: every action a rule may declare -> what firing it does. A PURE dict
#: literal: nidtlint's ``action-discipline`` family AST-parses this
#: table (the ``RECIPE_KEYS`` closure pattern), so computed keys would
#: break the startup-validation contract.
BUILTIN_ACTIONS: dict = {
    "quarantine_silo": (
        "quarantine the client/silo whose update diverges most from "
        "the cohort (min leave-one-out cosine) via the PR 5 strike "
        "machinery — dropped from sampling/aggregation for "
        "--quarantine_rounds rounds"),
    "escalate_defense": (
        "step the robust-aggregation ladder one rung: none -> "
        "norm_diff_clipping -> trimmed_mean (round programs re-plan "
        "with the escalated defense)"),
    "adapt_buffer": (
        "adapt the async server's concurrency to the measured arrival "
        "process: halve buffer_k (floor 1) and raise staleness_alpha "
        "(the FedBuff runtime-knob reading of staleness runaway)"),
    "freeze_rollback": (
        "freeze the current (blown-up) state and roll back to the "
        "last healthy pinned state at the next host boundary, "
        "zeroing the codec error-feedback accumulators"),
    "shrink_mesh": (
        "re-plan the client mesh over the surviving devices after a "
        "device loss / preemption and resume from the last "
        "donation-safe checkpoint (elastic compute plane)"),
}

#: ``--actions`` gate values (off = no dispatch at all; dry_run logs
#: what WOULD fire; on runs registered handlers)
MODES = ("off", "dry_run", "on")

#: bounded action-log ring (evictions counted, never silent)
LOG_CAP = 256

#: dispatch outcomes the counter/log can carry
STATUSES = ("applied", "dry_run", "unhandled", "skipped", "error")


class ActionBus:
    """Holds the mode, the registered handlers, and the bounded
    deterministic action log. Thread-safe: server ingest threads
    dispatch while HTTP scrape threads read ``actions_block()``."""

    def __init__(self, mode: str = "dry_run", log_cap: int = LOG_CAP):
        if mode not in MODES:
            raise ValueError(
                f"--actions must be one of {MODES}, got {mode!r}")
        self.mode = mode
        self._lock = threading.Lock()
        self._handlers: dict[str, Callable[..., dict | None]] = {}
        self._log: deque = deque(maxlen=int(log_cap))
        self._evicted = 0
        self._total = 0
        self._counter = obs_metrics.counter(
            N.ACTIONS_TOTAL,
            "reflex-plane action dispatches (obs/actions.py), by "
            "action name and outcome status",
            labelnames=("action", "status"))

    # ---- registration (the planes that can realize an action) ----

    def register(self, action: str,
                 fn: Callable[..., dict | None]) -> None:
        """Register ``fn(rule=..., round_idx=..., value=...) ->
        detail-dict|None`` as the realization of ``action`` on this
        plane. Latest wins (a restarted driver re-arms cleanly);
        unknown action names fail loudly — registration happens at
        plane startup, where failing is cheap."""
        if action not in BUILTIN_ACTIONS:
            raise ValueError(
                f"cannot register handler for unknown action "
                f"{action!r}; registered actions (obs/actions.py "
                f"BUILTIN_ACTIONS): {sorted(BUILTIN_ACTIONS)}")
        with self._lock:
            self._handlers[action] = fn

    # ---- dispatch ----

    def _append(self, entry: dict) -> None:
        with self._lock:
            self._total += 1
            if len(self._log) == self._log.maxlen:
                self._evicted += 1
            self._log.append(entry)

    def on_alert(self, action: str, *, rule: str, severity: str = "",
                 round_idx: int | None = None,
                 value: float | None = None) -> dict | None:
        """Dispatch one rising alert edge's declared action. Returns
        the action-log entry (None in ``off`` mode). NEVER raises: a
        handler exception becomes an ``error`` entry — reflexes must
        not kill the training they protect."""
        if self.mode == "off":
            return None
        entry: dict[str, Any] = {
            "action": action, "rule": rule, "severity": severity,
            "round": None if round_idx is None else int(round_idx),
            "value": None if value is None else float(value),
            "dry_run": self.mode != "on",
        }
        if action not in BUILTIN_ACTIONS:
            # rule validation makes this unreachable for engine-built
            # rules; guard anyway so a hand-built RuleEngine cannot
            # crash a boundary through the bus
            entry.update(status="error",
                         detail={"error": f"unknown action {action!r}"})
        elif self.mode == "dry_run":
            entry["status"] = "dry_run"
        else:
            with self._lock:
                fn = self._handlers.get(action)
            if fn is None:
                # this plane has no realization of the action (e.g.
                # adapt_buffer on an in-process engine run)
                entry["status"] = "unhandled"
            else:
                try:
                    detail = fn(rule=rule, round_idx=round_idx,
                                value=value)
                    detail = dict(detail or {})
                    entry["status"] = detail.pop("status", "applied")
                    if detail:
                        entry["detail"] = detail
                except Exception as e:  # noqa: BLE001 — reflex
                    # containment: an acting handler must never
                    # propagate into the host boundary that fired it
                    entry["status"] = "error"
                    entry["detail"] = {"error": str(e)}
        self._counter.labels(action=action,
                             status=entry["status"]).inc()
        obs_flight.record(
            "action_dry_run" if entry["dry_run"] else "action",
            action=action, rule=rule, status=entry["status"],
            round=entry["round"], value=entry["value"])
        self._append(entry)
        return entry

    def record_action(self, action: str, *, rule: str,
                      round_idx: int | None = None,
                      status: str = "applied",
                      detail: dict | None = None) -> dict:
        """Record a plane-initiated action (no firing rule edge): the
        elastic-mesh shrink is driven by the device-loss event itself,
        not a metric rule, so it records here with its provenance
        string (``rule="device-loss"``) and is NOT mode-gated — an
        explicit injected fault always leaves its trace."""
        entry: dict[str, Any] = {
            "action": action, "rule": rule, "severity": "",
            "round": None if round_idx is None else int(round_idx),
            "value": None, "dry_run": False, "status": status,
        }
        if detail:
            entry["detail"] = dict(detail)
        self._counter.labels(action=action, status=status).inc()
        obs_flight.record("action", action=action, rule=rule,
                          status=status, round=entry["round"],
                          value=None)
        self._append(entry)
        return entry

    # ---- reports ----

    def actions_block(self, last: int = 50) -> dict:
        """The ``/healthz`` / verdict ``actions`` block: mode, which
        actions have registered handlers on this plane, totals, and
        the last ``last`` log entries (rule provenance + dry_run flag
        on each — the operator audit the satellite asks for)."""
        with self._lock:
            log = list(self._log)[-int(last):]
            return {"mode": self.mode,
                    "registered": sorted(self._handlers),
                    "total": self._total,
                    "evicted": self._evicted,
                    "log": log}


# ---------------------------------------------------------------------------
# the process-global bus (armed by the CLIs; tests build their own)
# ---------------------------------------------------------------------------

_ACTIVE: ActionBus | None = None
_ACTIVE_LOCK = threading.Lock()


def configure(mode: str = "dry_run", log_cap: int = LOG_CAP
              ) -> ActionBus:
    """Arm the process-global action bus at ``--actions`` mode. Returns
    the bus — CLIs keep the handle so end-of-run reports can read the
    log after :func:`disarm`."""
    global _ACTIVE
    bus = ActionBus(mode, log_cap=log_cap)
    with _ACTIVE_LOCK:
        _ACTIVE = bus
    return bus


def disarm() -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = None


def active() -> ActionBus | None:
    with _ACTIVE_LOCK:
        return _ACTIVE


def register(action: str, fn: Callable[..., dict | None]) -> None:
    """Register a handler on the armed bus; a no-op when no bus is
    armed (tests and library callers run engines without the CLI)."""
    bus = active()
    if bus is not None:
        bus.register(action, fn)


def on_alert(action: str, *, rule: str, severity: str = "",
             round_idx: int | None = None,
             value: float | None = None) -> dict | None:
    """Dispatch through the armed bus; None when unarmed —
    instrumentation sites (obs/rules.py) call this unconditionally."""
    bus = active()
    if bus is None:
        return None
    return bus.on_alert(action, rule=rule, severity=severity,
                        round_idx=round_idx, value=value)


def record_action(action: str, *, rule: str,
                  round_idx: int | None = None,
                  status: str = "applied",
                  detail: dict | None = None) -> dict | None:
    """Record a plane-initiated action on the armed bus (None when
    unarmed)."""
    bus = active()
    if bus is None:
        return None
    return bus.record_action(action, rule=rule, round_idx=round_idx,
                             status=status, detail=detail)


def actions_block(last: int = 50) -> dict:
    """The ``actions`` block for probes/verdicts —
    ``{"mode": "unarmed"}`` when no bus is configured."""
    bus = active()
    return (bus.actions_block(last) if bus is not None
            else {"mode": "unarmed"})
