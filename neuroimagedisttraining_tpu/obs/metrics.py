"""Metrics registry: labeled Counters / Gauges / Histograms, one home.

Before ISSUE 9 the system's numbers were fragmented across ad-hoc
surfaces — ``stat_info`` dicts (engines/base.py), ``byte_stats()``
(distributed/comm.py), ``upload_audit()`` (asyncfl/server.py),
``dp_report()`` (cross_silo.py), free-form JSONL (utils/logging.py).
Those surfaces all still exist (they are API contracts tests pin); this
registry is where they now ALSO publish, so one scrape (``/metrics``,
obs/http.py), one ``snapshot()``, or one JSONL line carries the whole
system's state. The parity contract — registry values equal the legacy
surfaces' values, no double counting — is pinned in tests/test_obs.py.

Design:

- dependency-free, thread-safe (one registry lock; mutations are a dict
  lookup + float add under it — cheap enough for the per-frame comm
  counters).
- idempotent registration: ``counter(name, ...)`` returns the existing
  metric when the name is already registered (servers and engines are
  constructed many times per process; re-registration must never throw
  or shadow live values). Re-registering with a different kind is a
  programming error and raises.
- labels: ``c.labels(rank="0").inc()`` or the shorthand
  ``c.inc(5, rank="0")``. Unlabeled metrics use the empty label set.
- exposition: Prometheus text format 0.0.4 (``prometheus_text()``),
  structured ``snapshot()``, and an append-only JSONL sink
  (``dump_jsonl``) for offline analysis.
- ``disable()``/``enable()``: process-wide arm switch for A/B overhead
  measurement (bench.py ``obs_overhead`` cell); disabled mutations are
  a single attribute test.

HOST-BOUNDARY RULE: never mutate a metric inside a jitted/vmapped body
(nidtlint ``obs-discipline``) — the mutation would run once at trace
time and never again, silently freezing the metric at its trace value.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Iterable, Mapping

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "counter", "gauge", "histogram", "snapshot", "prometheus_text",
    "reset", "enable", "disable",
]

#: default histogram buckets (seconds-flavored, Prometheus defaults)
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0)


def _escape(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _fmt(v: float) -> str:
    """Prometheus value formatting: integers without the trailing .0,
    canonical NaN/+Inf/-Inf spellings (repr's 'nan'/'inf' are not valid
    exposition tokens)."""
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    if isinstance(v, float) and math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _json_safe(obj):
    """Non-finite floats -> canonical strings: json.dumps would emit
    bare NaN/Infinity tokens that strict JSON parsers refuse, and a NaN
    train_loss IS reachable (the non-finite guards exist because losses
    diverge)."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return _fmt(obj)
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_json_safe(v) for v in obj]
    return obj


class _Bound:
    """A metric bound to one label-value tuple."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "_Metric", key: tuple):
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._metric._inc(self._key, amount)

    def set(self, value: float) -> None:
        self._metric._set(self._key, value)

    def observe(self, value: float) -> None:
        self._metric._observe(self._key, value)

    def get(self):
        return self._metric._get(self._key)


class _Metric:
    """Shared label machinery; subclasses define the value cell."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labelnames: tuple[str, ...]):
        self._registry = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._cells: dict[tuple, Any] = {}

    # -- label plumbing --

    def _key_of(self, labels: Mapping[str, Any]) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(labels)}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def labels(self, **labels: Any) -> _Bound:
        return _Bound(self, self._key_of(labels))

    # -- unlabeled shorthands (labels may also ride as kwargs) --

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        self._inc(self._key_of(labels), amount)

    def set(self, value: float, **labels: Any) -> None:
        self._set(self._key_of(labels), value)

    def observe(self, value: float, **labels: Any) -> None:
        self._observe(self._key_of(labels), value)

    def get(self, **labels: Any):
        return self._get(self._key_of(labels))

    # -- cell ops (subclass) --

    def _inc(self, key: tuple, amount: float) -> None:
        raise TypeError(f"{self.kind} {self.name!r} does not support inc()")

    def _set(self, key: tuple, value: float) -> None:
        raise TypeError(f"{self.kind} {self.name!r} does not support set()")

    def _observe(self, key: tuple, value: float) -> None:
        raise TypeError(
            f"{self.kind} {self.name!r} does not support observe()")

    def _get(self, key: tuple):
        # value materialized UNDER the lock: a histogram cell is mutable
        # (counts list + sum + count), and snapshotting it unlocked
        # could tear against a concurrent observe
        with self._registry._lock:
            return self._cell_value(self._cells.get(key))

    def _cell_value(self, cell):
        return 0.0 if cell is None else cell

    # -- exposition (under the registry lock) --

    def _label_str(self, key: tuple, extra: str = "") -> str:
        parts = [f'{n}="{_escape(v)}"'
                 for n, v in zip(self.labelnames, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def _expose(self) -> Iterable[str]:
        for key in sorted(self._cells):
            yield (f"{self.name}{self._label_str(key)} "
                   f"{_fmt(self._cells[key])}")

    def _snapshot_cell(self, cell):
        return cell


class Counter(_Metric):
    kind = "counter"

    def _inc(self, key: tuple, amount: float) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        reg = self._registry
        if not reg.enabled:
            return
        with reg._lock:
            self._cells[key] = self._cells.get(key, 0.0) + float(amount)


class Gauge(_Metric):
    kind = "gauge"

    def _set(self, key: tuple, value: float) -> None:
        reg = self._registry
        if not reg.enabled:
            return
        with reg._lock:
            self._cells[key] = float(value)

    def _inc(self, key: tuple, amount: float) -> None:
        reg = self._registry
        if not reg.enabled:
            return
        with reg._lock:
            self._cells[key] = self._cells.get(key, 0.0) + float(amount)


class _HistCell:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # last = overflow (+Inf)
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    """Fixed-bucket histogram. ``buckets`` are upper bounds (le); the
    implicit +Inf bucket always exists. Exposition renders CUMULATIVE
    bucket counts plus ``_sum``/``_count`` (Prometheus histogram
    semantics); ``snapshot()`` carries the per-bucket (non-cumulative)
    counts too — the bucket math is pinned in tests/test_obs.py."""

    kind = "histogram"

    def __init__(self, registry, name, help, labelnames,
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        super().__init__(registry, name, help, labelnames)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = bs

    def _observe(self, key: tuple, value: float) -> None:
        reg = self._registry
        if not reg.enabled:
            return
        v = float(value)
        with reg._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = _HistCell(len(self.buckets))
            i = len(self.buckets)  # +Inf by default
            for j, b in enumerate(self.buckets):
                if v <= b:
                    i = j
                    break
            cell.counts[i] += 1
            cell.sum += v
            cell.count += 1

    def _cell_value(self, cell):
        if cell is None:
            return {"count": 0, "sum": 0.0,
                    "buckets": {_fmt(b): 0 for b in self.buckets}}
        return self._snapshot_cell(cell)

    def _expose(self) -> Iterable[str]:
        for key in sorted(self._cells):
            cell = self._cells[key]
            acc = 0
            for b, n in zip(self.buckets, cell.counts):
                acc += n
                le = self._label_str(key, f'le="{_fmt(b)}"')
                yield f"{self.name}_bucket{le} {acc}"
            le = self._label_str(key, 'le="+Inf"')
            yield f"{self.name}_bucket{le} {cell.count}"
            yield (f"{self.name}_sum{self._label_str(key)} "
                   f"{_fmt(cell.sum)}")
            yield (f"{self.name}_count{self._label_str(key)} "
                   f"{cell.count}")

    def _snapshot_cell(self, cell: _HistCell):
        out = {"count": cell.count, "sum": cell.sum, "buckets": {}}
        for b, n in zip(self.buckets, cell.counts):
            out["buckets"][_fmt(b)] = n
        out["buckets"]["+Inf"] = cell.counts[-1]
        return out


class MetricsRegistry:
    """One process's metric namespace. ``REGISTRY`` below is the global
    default every shipped instrumentation site publishes into; tests
    construct private registries or ``reset()`` the global one."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self.enabled = True

    # ---- registration (idempotent) ----

    def _register(self, kind: str, name: str, help: str,
                  labelnames: tuple[str, ...], **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}, not {kind}")
                if m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered with "
                        f"labels {m.labelnames}, not {tuple(labelnames)}")
                if kind == "histogram":
                    want = tuple(sorted(float(b)
                                        for b in kw["buckets"]))
                    if m.buckets != want:
                        # silently keeping the first registration's
                        # buckets would collapse the second caller's
                        # range into +Inf with no signal
                        raise ValueError(
                            f"histogram {name!r} already registered "
                            f"with buckets {m.buckets}, not {want}")
                return m
            m = self._KINDS[kind](self, name, help, tuple(labelnames),
                                  **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: tuple[str, ...] = ()) -> Counter:
        return self._register("counter", name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: tuple[str, ...] = ()) -> Gauge:
        return self._register("gauge", name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: tuple[str, ...] = (),
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._register("histogram", name, help, labelnames,
                              buckets=buckets)

    # ---- arm switch (overhead A/B) ----

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        """Disarm every mutation (one attribute test per call site) —
        the disarmed leg of the obs_overhead bench cell."""
        self.enabled = False

    def reset(self) -> None:
        """Drop every metric (tests; never called by shipped code)."""
        with self._lock:
            self._metrics.clear()

    # ---- output ----

    def snapshot(self) -> dict:
        """``{name: {"kind", "help", "values": [{"labels", "value"}]}}``
        — histograms' value is ``{count, sum, buckets}``."""
        with self._lock:
            out = {}
            for name, m in sorted(self._metrics.items()):
                vals = []
                for key in sorted(m._cells):
                    vals.append({
                        "labels": dict(zip(m.labelnames, key)),
                        "value": m._snapshot_cell(m._cells[key])})
                out[name] = {"kind": m.kind, "help": m.help,
                             "values": vals}
            return out

    def prometheus_text(self) -> str:
        """Text exposition format 0.0.4 (what ``/metrics`` serves)."""
        with self._lock:
            lines = []
            for name, m in sorted(self._metrics.items()):
                if m.help:
                    lines.append(f"# HELP {name} {m.help}")
                lines.append(f"# TYPE {name} {m.kind}")
                lines.extend(m._expose())
            return "\n".join(lines) + "\n"

    def dump_jsonl(self, path: str, **extra: Any) -> None:
        """Append one ``{"t": wall, "metrics": snapshot, **extra}`` line
        — the offline sink (scrapeless runs, post-hoc analysis)."""
        rec = _json_safe({"t": round(time.time(), 3), **extra,
                          "metrics": self.snapshot()})
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(rec, default=str) + "\n")


#: the process-global registry every shipped instrumentation site uses
REGISTRY = MetricsRegistry()

#: module-level conveniences (instrumentation-site spelling)
counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
snapshot = REGISTRY.snapshot
prometheus_text = REGISTRY.prometheus_text
reset = REGISTRY.reset
enable = REGISTRY.enable
disable = REGISTRY.disable
