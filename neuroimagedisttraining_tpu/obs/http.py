"""``/metrics`` + ``/healthz``: a stdlib-only scrape endpoint.

``--metrics_port N`` on either server (and the main CLI) starts this —
a ``ThreadingHTTPServer`` on its own daemon thread serving

- ``GET /metrics``  -> Prometheus text exposition of a registry
  (``text/plain; version=0.0.4``), scrape-compatible with any
  Prometheus/VictoriaMetrics/agent collector;
- ``GET /healthz``  -> one JSON object ``{"ok": true, "uptime_s": ...}``
  plus whatever live health the caller's probe reports (round/version,
  buffer occupancy) — the liveness endpoint a k8s-style deployment
  points its probe at.

Scrapes run on the HTTP server's threads and only take the registry
lock for the duration of one text render — they never touch the
dispatch thread, the selector loop, or any jitted program. Port 0 asks
the kernel for a free port (tests); the bound port is on ``.port``.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from neuroimagedisttraining_tpu.obs.metrics import REGISTRY, MetricsRegistry

__all__ = ["MetricsServer", "start_metrics_server"]

log = logging.getLogger("neuroimagedisttraining_tpu.obs")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Owns the HTTP server + its thread; ``close()`` is idempotent."""

    def __init__(self, port: int, registry: MetricsRegistry | None = None,
                 health_probe: Callable[[], dict] | None = None,
                 host: str = "0.0.0.0"):
        registry = registry if registry is not None else REGISTRY
        t0 = time.monotonic()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = registry.prometheus_text().encode()
                    self._reply(200, CONTENT_TYPE, body)
                elif path == "/healthz":
                    health = {"ok": True,
                              "uptime_s": round(time.monotonic() - t0, 3)}
                    if health_probe is not None:
                        try:
                            health.update(health_probe())
                        except Exception as e:  # noqa: BLE001 — a probe
                            # bug must degrade the health report, not
                            # kill the scrape thread
                            health["ok"] = False
                            health["probe_error"] = str(e)
                    self._reply(200 if health["ok"] else 503,
                                "application/json",
                                json.dumps(health).encode())
                else:
                    self._reply(404, "text/plain", b"not found\n")

            def _reply(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # scrapes are periodic —
                log.debug("metrics http: " + fmt, *args)  # keep stdout clean

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="nidt-metrics-http")
        self._thread.start()
        log.info("metrics endpoint on :%d (/metrics, /healthz)", self.port)

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


def start_metrics_server(port: int,
                         registry: MetricsRegistry | None = None,
                         health_probe: Callable[[], dict] | None = None,
                         host: str = "0.0.0.0"
                         ) -> MetricsServer | None:
    """``--metrics_port`` entry point: 0 (the CLI default) means OFF and
    returns None; tests wanting an ephemeral port construct
    ``MetricsServer(0)`` directly. Callers hold the returned handle and
    ``close()`` it on shutdown."""
    if not port or int(port) <= 0:
        return None
    return MetricsServer(int(port), registry=registry,
                         health_probe=health_probe, host=host)
