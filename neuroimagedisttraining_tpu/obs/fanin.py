"""Federation-wide telemetry fan-in (ISSUE 13).

PR 12 made the control plane multi-process: ``--ingest_workers N``
selector worker processes own every client socket while the root merges
their partial aggregates. The PR 9 telemetry plane, however, is strictly
per-process — each worker's metrics registry, span buffer and flight
ring die with its interpreter, and the root's ``/metrics`` sees workers
only as batched verdict counters. This module is the missing layer:

- **worker side** (``WorkerObsShipper``): periodically package the
  process's registry snapshot, the span buffer's NEW events (capped
  chunk), and the flight ring's NEW events into one pipe payload. The
  payload rides the existing verdict pipe as a single ``("obs", ...)``
  message — BATCHED like the verdict events (nidtlint
  ``obs-pipe-per-upload`` fences per-upload telemetry sends), and
  ordering-independent of the audit invariant (verdict batches still
  flush strictly before the partial containing their uploads; telemetry
  merely shares the FIFO).
- **root side** (``TelemetryFanIn``): keep each worker's LAST snapshot
  (plus its age — a SIGKILLed worker's numbers stay visible, marked
  stale, instead of vanishing), accumulate its spans and flight events,
  and render three merged artifacts:

  * ONE Prometheus exposition — the root registry's samples unchanged,
    every worker sample re-labeled with ``worker="N"``, plus the
    synthesized ``nidt_obs_worker_snapshot_age_s`` /
    ``nidt_obs_worker_alive`` staleness gauges;
  * ONE Chrome trace — root events as recorded, worker events rebased
    onto the root's clock via the spawn-time ping/pong handshake
    (``estimate_clock_offset``: offset = t_worker − midpoint(t0, t1),
    uncertainty = rtt/2), with per-process ``process_name`` metadata so
    Perfetto lays workers out as distinct tracks;
  * ONE flight dump where every worker event carries ``worker``
    provenance, merged with the root ring in wall-clock order.

The upload-lifecycle stage histogram also lives here
(``nidt_upload_stage_ms{stage=queue|decode|admit|fold|merge|aggregate}``)
— the instrument that replaces the ingest bench's hand-timed latency
attribution. Worker processes observe queue/decode/admit/fold; the root
observes merge/aggregate; the merged exposition shows all of them,
worker-labeled.

Bounded by construction: span accumulation per worker is capped
(dropped counts surface in the merged trace's ``nidtDroppedEvents``),
flight accumulation is a deque ring, and one snapshot per worker is
kept — never a history.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Any

from neuroimagedisttraining_tpu.obs import flight as obs_flight
from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
from neuroimagedisttraining_tpu.obs import trace as obs_trace
from neuroimagedisttraining_tpu.obs.metrics import _escape, _fmt

__all__ = ["WorkerObsShipper", "TelemetryFanIn", "estimate_clock_offset",
           "suffixed_path", "stage_histogram", "rtt_histogram",
           "linked_flow_ids", "OBS_SHIP_INTERVAL_S", "UPLOAD_STAGES"]

log = logging.getLogger("neuroimagedisttraining_tpu.obs")

#: how often a worker ships its telemetry payload over the pipe — one
#: message per interval per worker, NEVER per upload (the batching
#: discipline the verdict events established; at 1k uploads/s a
#: per-upload telemetry send would double the pipe fan-in cost)
OBS_SHIP_INTERVAL_S = 0.5
#: span events per shipped chunk (a payload is one pickle over the
#: pipe; past the cap the chunk truncates and counts the drop)
SPAN_CHUNK_MAX = 4096
#: per-worker span accumulation cap at the root (the merged trace keeps
#: the PREFIX of each worker's timeline, the span buffer's own rule)
WORKER_SPAN_CAP = 1 << 16
#: per-worker flight ring at the root
WORKER_FLIGHT_CAP = 512

#: the upload lifecycle (ARCHITECTURE.md "Observability" glossary).
#: queue/decode/admit/fold are per-UPLOAD stages observed in the worker
#: process; merge/aggregate are per-AGGREGATION stages observed at the
#: root (they cover the whole harvested buffer, not one upload).
UPLOAD_STAGES = ("queue", "decode", "admit", "fold", "merge", "aggregate")

#: ms buckets for the stage histogram (sub-ms decode up to multi-second
#: stalls; the ingest bench's syscall hunt lived in the 0.5-5 ms band)
STAGE_BUCKETS_MS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                    50.0, 100.0, 250.0, 1000.0)

#: ms buckets for the client-observed RTT histogram (loadgen satellite:
#: the percentiles that used to live only in ingest_bench.json notes)
RTT_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                  1000.0, 2500.0, 5000.0, 10000.0)


def stage_histogram(registry: obs_metrics.MetricsRegistry | None = None
                    ) -> obs_metrics.Histogram:
    """The per-stage upload-lifecycle latency histogram — registered
    idempotently in whichever process observes a stage."""
    reg = registry if registry is not None else obs_metrics.REGISTRY
    return reg.histogram(
        "nidt_upload_stage_ms",
        "upload-lifecycle latency per stage (ms): queue/decode/admit/"
        "fold per upload in the worker, merge/aggregate per "
        "aggregation at the root",
        labelnames=("stage",), buckets=STAGE_BUCKETS_MS)


def rtt_histogram(registry: obs_metrics.MetricsRegistry | None = None
                  ) -> obs_metrics.Histogram:
    """Client-observed upload->sync round trip (ms), published by the
    load harness (asyncfl/loadgen.py)."""
    reg = registry if registry is not None else obs_metrics.REGISTRY
    return reg.histogram(
        "nidt_client_rtt_ms",
        "client-observed upload->sync round-trip latency (ms), sampled "
        "by the load harness fleet",
        buckets=RTT_BUCKETS_MS)


def suffixed_path(path: str, wid: int) -> str:
    """Per-worker-process artifact path: ``trace.json`` -> \
``trace.w0.json`` (the root keeps the BARE path for the merged
    artifact, which is the primary one). Fixes the ``--trace_out``/
    ``--flight_out`` clobber under ``--ingest_workers N``: N processes
    inheriting one path used to be N writers of one file."""
    if not path:
        return ""
    root, ext = os.path.splitext(path)
    return f"{root}.w{int(wid)}{ext}" if ext else f"{path}.w{int(wid)}"


def estimate_clock_offset(t0_ns: int, t_worker_ns: int, t1_ns: int
                          ) -> tuple[int, int]:
    """Spawn-time clock handshake: the root sends its ``perf_counter``
    reading ``t0``, the worker replies with its own reading, the root
    receives at ``t1``. The worker's clock at the pipe's midpoint is
    the best estimate of "the same instant", so

        offset = t_worker - (t0 + t1) / 2      (worker clock − root)

    with uncertainty bounded by half the round trip. Returns
    ``(offset_ns, uncertainty_ns)``; a worker timestamp ``t_w`` maps to
    root time as ``t_w - offset``."""
    mid = (int(t0_ns) + int(t1_ns)) // 2
    return int(t_worker_ns) - mid, max(0, (int(t1_ns) - int(t0_ns)) // 2)


def linked_flow_ids(events: list[dict]) -> dict[str, set]:
    """Group flow-event ids by the phases seen: ``{"s": {...}, "t":
    {...}, "f": {...}, "linked": {...}}`` where ``linked`` holds ids
    with a start AND a step AND an end — a fully client->worker->root
    causally-linked upload (the acceptance probe and the roundtrip
    test's oracle)."""
    by_phase: dict[str, set] = {"s": set(), "t": set(), "f": set()}
    for e in events:
        if e.get("ph") in by_phase and "id" in e:
            by_phase[e["ph"]].add(e["id"])
    by_phase["linked"] = by_phase["s"] & by_phase["t"] & by_phase["f"]
    return by_phase


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------


class WorkerObsShipper:
    """One worker process's telemetry packager. ``payload()`` returns a
    pipe-ready dict at most every ``interval_s`` (or always when
    ``force=True`` — the pre-bye final ship), containing the registry
    snapshot plus the span/flight events NEW since the last ship."""

    def __init__(self, interval_s: float = OBS_SHIP_INTERVAL_S,
                 registry: obs_metrics.MetricsRegistry | None = None,
                 tracer: obs_trace.SpanTracer | None = None,
                 flight: obs_flight.FlightRecorder | None = None,
                 span_chunk_max: int = SPAN_CHUNK_MAX):
        self.interval_s = float(interval_s)
        self.registry = (registry if registry is not None
                         else obs_metrics.REGISTRY)
        self.tracer = tracer if tracer is not None else obs_trace.TRACER
        self.flight = (flight if flight is not None
                       else obs_flight.FLIGHT)
        self.span_chunk_max = int(span_chunk_max)
        self._span_idx = 0
        self._flight_seq = 0
        self._last_ship = 0.0

    def payload(self, force: bool = False) -> dict | None:
        now = time.monotonic()
        if not force and now - self._last_ship < self.interval_s:
            return None
        self._last_ship = now
        spans: list[dict] = []
        spans_dropped = 0
        if self.tracer.armed:
            spans, self._span_idx = self.tracer.events_from(
                self._span_idx)
            if len(spans) > self.span_chunk_max:
                spans_dropped = len(spans) - self.span_chunk_max
                spans = spans[:self.span_chunk_max]
        fl, self._flight_seq = self.flight.events_from(self._flight_seq)
        return {
            "metrics": self.registry.snapshot(),
            "spans": spans,
            "spans_dropped": spans_dropped,
            "flight": fl,
            "epoch_ns": self.tracer.epoch_ns,
            "t_ns": time.perf_counter_ns(),
            "t_wall": time.time(),
            "pid": os.getpid(),
        }


# ---------------------------------------------------------------------------
# root side
# ---------------------------------------------------------------------------


class _WorkerTelemetry:
    """Per-worker accumulation at the root. ``wid`` is the fan-in KEY:
    a tuple with one element per label tier — ``(3,)`` on a flat root,
    ``(region, worker)`` under the hierarchical tier (ISSUE 18)."""

    __slots__ = ("wid", "alive", "pid", "offset_ns", "offset_err_ns",
                 "epoch_ns", "snapshot", "snap_mono", "snap_wall",
                 "spans", "spans_dropped", "flight", "flight_evicted")

    def __init__(self, wid: tuple):
        self.wid = tuple(wid)
        self.alive = True
        self.pid: int | None = None
        self.offset_ns = 0
        self.offset_err_ns: int | None = None
        self.epoch_ns: int | None = None
        self.snapshot: dict | None = None
        self.snap_mono: float | None = None
        self.snap_wall: float | None = None
        self.spans: list[dict] = []
        self.spans_dropped = 0
        self.flight: collections.deque = collections.deque(
            maxlen=WORKER_FLIGHT_CAP)
        self.flight_evicted = 0


class _MergedMetricsView:
    """Duck-typed registry for ``obs.http.MetricsServer``: a scrape of
    the merged exposition instead of one process's registry."""

    def __init__(self, fanin: "TelemetryFanIn"):
        self._fanin = fanin

    def prometheus_text(self) -> str:
        return self._fanin.prometheus_text()


class TelemetryFanIn:
    """The root's merge point. Thread-safe: the ingest event loop calls
    ``ingest``/``note_clock``/``mark_dead`` under the server lock while
    HTTP scrape threads call ``prometheus_text`` — everything here
    takes only this object's own lock."""

    def __init__(self,
                 registry: obs_metrics.MetricsRegistry | None = None,
                 tracer: obs_trace.SpanTracer | None = None,
                 flight: obs_flight.FlightRecorder | None = None,
                 labelnames: tuple[str, ...] = ("worker",)):
        self._lock = threading.Lock()
        self._workers: dict[tuple, _WorkerTelemetry] = {}
        #: one label per key tier (ISSUE 18): ``("worker",)`` on a flat
        #: root, ``("region", "worker")`` under the hierarchical tier —
        #: keys are same-length tuples, ints accepted as 1-tuples
        self.labelnames = tuple(labelnames)
        self.registry = (registry if registry is not None
                         else obs_metrics.REGISTRY)
        self.tracer = tracer if tracer is not None else obs_trace.TRACER
        self.flight = (flight if flight is not None
                       else obs_flight.FLIGHT)

    # ---- key helpers ----

    def _key(self, wid) -> tuple:
        if isinstance(wid, tuple):
            return tuple(int(x) for x in wid)
        return (int(wid),)

    def _labels(self, key: tuple) -> dict[str, str]:
        return {n: str(v) for n, v in zip(self.labelnames, key)}

    def _name(self, key: tuple) -> str:
        return (str(key[0]) if len(key) == 1
                else "/".join(str(v) for v in key))

    # ---- worker lifecycle / ingestion ----

    def register_worker(self, wid) -> None:
        key = self._key(wid)
        with self._lock:
            self._workers.setdefault(key, _WorkerTelemetry(key))

    def note_clock(self, wid, t0_ns: int, t_worker_ns: int,
                   t1_ns: int) -> None:
        off, err = estimate_clock_offset(t0_ns, t_worker_ns, t1_ns)
        key = self._key(wid)
        with self._lock:
            w = self._workers.setdefault(key, _WorkerTelemetry(key))
            w.offset_ns, w.offset_err_ns = off, err

    def mark_dead(self, wid) -> None:
        """A dead worker's LAST snapshot stays visible — the staleness
        gauge, not deletion, is how its death reads on a scrape. A key
        PREFIX shorter than the label tiers marks the whole subtree
        (a dead REGION marks every ``(region, *)`` worker)."""
        key = self._key(wid)
        with self._lock:
            if len(key) < len(self.labelnames):
                for k, w in self._workers.items():
                    if k[:len(key)] == key:
                        w.alive = False
                return
            w = self._workers.get(key)
            if w is not None:
                w.alive = False

    def ingest(self, wid, payload: dict) -> None:
        """One ``("obs", wid, payload)`` pipe message."""
        key = self._key(wid)
        with self._lock:
            w = self._workers.setdefault(key, _WorkerTelemetry(key))
            snap = payload.get("metrics")
            if snap is not None:
                w.snapshot = snap
                w.snap_mono = time.monotonic()
                w.snap_wall = payload.get("t_wall", time.time())
            if payload.get("pid"):
                w.pid = int(payload["pid"])
            if payload.get("epoch_ns") is not None:
                w.epoch_ns = int(payload["epoch_ns"])
            spans = payload.get("spans") or []
            room = WORKER_SPAN_CAP - len(w.spans)
            if len(spans) > room:
                w.spans_dropped += len(spans) - max(0, room)
                spans = spans[:max(0, room)]
            w.spans.extend(spans)
            w.spans_dropped += int(payload.get("spans_dropped") or 0)
            for ev in payload.get("flight") or ():
                if len(w.flight) == w.flight.maxlen:
                    w.flight_evicted += 1
                w.flight.append(ev)

    def summary(self) -> dict:
        """Machine-readable fan-in state (loadgen result / tests)."""
        with self._lock:
            now = time.monotonic()
            return {self._name(w.wid): {
                "alive": w.alive,
                "has_metrics": w.snapshot is not None,
                "snapshot_age_s": (round(now - w.snap_mono, 3)
                                   if w.snap_mono is not None else None),
                "spans": len(w.spans),
                "flight_events": len(w.flight),
                "clock_offset_ns": w.offset_ns,
                "clock_uncertainty_ns": w.offset_err_ns,
            } for w in self._workers.values()}

    def metrics_view(self) -> _MergedMetricsView:
        return _MergedMetricsView(self)

    def merged_snapshot(self) -> dict:
        """Snapshot-form merge (the ``registry.snapshot()`` schema):
        root cells unchanged, worker cells re-labeled with
        ``worker="N"`` — what the anomaly-rule engine (obs/rules.py)
        evaluates on the sharded ingest root, so a rule's label-subset
        selector fires on a WORKER's labeled series exactly as it
        would on a local one."""
        merged: dict[str, dict] = {}

        def _fold(snapshot: dict, extra: dict[str, str]) -> None:
            for name, m in snapshot.items():
                slot = merged.setdefault(
                    name, {"kind": m["kind"], "help": m["help"],
                           "values": []})
                if slot["kind"] != m["kind"]:
                    continue  # version skew — same rule as the text merge
                for v in m["values"]:
                    slot["values"].append(
                        {"labels": {**v["labels"], **extra},
                         "value": v["value"]})

        _fold(self.registry.snapshot(), {})
        with self._lock:
            for w in self._workers.values():
                if w.snapshot is not None:
                    _fold(w.snapshot, self._labels(w.wid))
        return merged

    # ---- merged Prometheus exposition ----

    def prometheus_text(self) -> str:
        """ONE exposition: root samples unchanged, worker samples with
        a ``worker`` label, one HELP/TYPE block per metric name, plus
        the synthesized worker-staleness gauges."""
        merged: dict[str, dict] = {}

        def _fold(snapshot: dict, extra: dict[str, str]) -> None:
            for name, m in snapshot.items():
                slot = merged.setdefault(
                    name, {"kind": m["kind"], "help": m["help"],
                           "rows": []})
                if slot["kind"] != m["kind"]:
                    # same codebase on both ends — a mismatch means
                    # version skew; skip rather than emit invalid text
                    log.warning("fanin: metric %s kind mismatch (%s vs "
                                "%s); skipping one source", name,
                                slot["kind"], m["kind"])
                    continue
                for v in m["values"]:
                    slot["rows"].append(({**v["labels"], **extra},
                                         v["value"]))

        _fold(self.registry.snapshot(), {})
        with self._lock:
            workers = list(self._workers.values())
            for w in workers:
                if w.snapshot is not None:
                    _fold(w.snapshot, self._labels(w.wid))
            # synthesized staleness plane: how old each worker's last
            # snapshot is (a SIGKILLed worker's age grows forever) and
            # whether the root still believes the process alive
            now = time.monotonic()
            age_rows = [(self._labels(w.wid),
                         round(now - w.snap_mono, 3))
                        for w in workers if w.snap_mono is not None]
            alive_rows = [(self._labels(w.wid), 1.0 if w.alive
                           else 0.0) for w in workers]
        if age_rows:
            merged["nidt_obs_worker_snapshot_age_s"] = {
                "kind": "gauge",
                "help": "seconds since this worker's last telemetry "
                        "snapshot reached the root (stale = dead or "
                        "wedged worker)",
                "rows": age_rows}
        if alive_rows:
            merged["nidt_obs_worker_alive"] = {
                "kind": "gauge",
                "help": "1 while the root believes the worker process "
                        "is alive",
                "rows": alive_rows}
        lines: list[str] = []
        for name in sorted(merged):
            m = merged[name]
            if m["help"]:
                lines.append(f"# HELP {name} {m['help']}")
            lines.append(f"# TYPE {name} {m['kind']}")
            for labels, value in m["rows"]:
                lines.extend(_render_sample(name, m["kind"], labels,
                                            value))
        return "\n".join(lines) + "\n"

    # ---- merged Chrome trace ----

    def merged_trace_events(self) -> list[dict]:
        """Root events as recorded; worker events rebased onto the
        root's clock: a worker event at ``ts`` µs past its epoch
        happened at absolute worker-clock ``epoch_w + ts``, which is
        root-clock ``epoch_w + ts - offset``, i.e. root-relative
        ``ts + (epoch_w - offset - epoch_root)``."""
        events = list(self.tracer.events())
        root_pid = os.getpid()
        meta = [{"name": "process_name", "ph": "M", "pid": root_pid,
                 "tid": 0, "args": {"name": "ingest-root"}}]
        root_epoch = self.tracer.epoch_ns
        with self._lock:
            for w in self._workers.values():
                if not w.spans:
                    continue
                shift_us = ((int(w.epoch_ns or root_epoch)
                             - int(w.offset_ns) - root_epoch) / 1e3)
                pid = w.pid
                for e in w.spans:
                    e2 = dict(e)
                    e2["ts"] = float(e.get("ts", 0.0)) + shift_us
                    events.append(e2)
                    if pid is None:
                        pid = e.get("pid")
                if pid is not None:
                    if len(w.wid) == 1:
                        pname = f"ingest-worker-{w.wid[0]}"
                    else:
                        pname = "ingest-" + "-".join(
                            f"{n}{v}" for n, v in
                            zip(self.labelnames, w.wid))
                    meta.append({"name": "process_name", "ph": "M",
                                 "pid": pid, "tid": 0,
                                 "args": {"name": pname}})
        return meta + events

    def merged_trace_doc(self) -> dict:
        doc = {"traceEvents": self.merged_trace_events(),
               "displayTimeUnit": "ms"}
        with self._lock:
            dropped = sum(w.spans_dropped
                          for w in self._workers.values())
        if dropped:
            doc["nidtDroppedEvents"] = dropped
        return doc

    def dump_trace(self, path: str) -> str | None:
        """Write the MERGED Chrome trace (the primary ``--trace_out``
        artifact under ``--ingest_workers``; per-worker local dumps are
        the ``.wN``-suffixed secondaries). Same never-crash contract as
        ``SpanTracer.dump``."""
        if not path:
            return None
        doc = self.merged_trace_doc()
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                json.dump(doc, f)
        except OSError:
            return None
        return path

    # ---- merged flight dump ----

    def merged_flight_doc(self, reason: str = "") -> dict:
        """Root ring events with ``proc: "root"``, worker events with
        ``proc: "worker<N>"`` + ``worker`` provenance, ordered by wall
        clock (the cross-process join key both rings record)."""
        events = [{**e, "proc": "root"} for e in self.flight.events()]
        with self._lock:
            for w in self._workers.values():
                if len(w.wid) == 1:
                    tag, prov = f"worker{w.wid[0]}", {"worker": w.wid[0]}
                else:
                    tag = "-".join(f"{n}{v}" for n, v in
                                   zip(self.labelnames, w.wid))
                    prov = {n: v for n, v in
                            zip(self.labelnames, w.wid)}
                events.extend({**e, "proc": tag, **prov}
                              for e in w.flight)
            workers = {self._name(w.wid): {"alive": w.alive,
                                           "events": len(w.flight),
                                           "evicted": w.flight_evicted}
                       for w in self._workers.values()}
            evicted = sum(w.flight_evicted
                          for w in self._workers.values())
        events.sort(key=lambda e: e.get("t_wall", 0.0))
        # bounded-ring honesty carried forward: the root ring's own
        # eviction count plus every per-worker accumulation drop — a
        # reader must never believe a truncated merge is complete
        return {"reason": reason, "capacity": self.flight.capacity,
                "evicted": self.flight.evicted + evicted,
                "workers": workers, "events": events}

    def dump_flight(self, path: str, reason: str = "") -> str | None:
        if not path:
            return None
        doc = self.merged_flight_doc(reason=reason)
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                json.dump(doc, f, default=str)
        except OSError:
            return None
        return path


def _render_sample(name: str, kind: str, labels: dict,
                   value: Any) -> list[str]:
    """Exposition lines for one sample from SNAPSHOT form. Histogram
    snapshot buckets are per-bucket counts keyed by formatted upper
    bound — rendered here as the CUMULATIVE ``_bucket`` series plus
    ``_sum``/``_count`` (Prometheus histogram semantics, matching
    ``Histogram._expose``)."""

    def label_str(extra: str = "") -> str:
        parts = [f'{k}="{_escape(v)}"'
                 for k, v in sorted(labels.items())]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    if kind != "histogram":
        return [f"{name}{label_str()} {_fmt(value)}"]
    buckets = dict(value.get("buckets", {}))
    inf = buckets.pop("+Inf", 0)
    out, acc = [], 0
    for le in sorted(buckets, key=float):
        acc += int(buckets[le])
        le_attr = 'le="' + str(le) + '"'
        out.append(f"{name}_bucket{label_str(le_attr)} {acc}")
    inf_attr = 'le="+Inf"'
    out.append(f"{name}_bucket{label_str(inf_attr)} "
               f"{int(value.get('count', acc + inf))}")
    out.append(f"{name}_sum{label_str()} {_fmt(value.get('sum', 0.0))}")
    out.append(f"{name}_count{label_str()} {int(value.get('count', 0))}")
    return out
