"""DARTS differentiable architecture search — TPU-native (Flax/NHWC).

Covers the reference's DARTS NAS suite (SURVEY §2.5, ~2k LoC of upstream
FedNAS baggage), re-designed rather than translated:

- candidate operations: reference operations.py:4-107 (sep/dil convs,
  pools, skip, zero, factorized reduce). NHWC, depthwise via
  ``feature_group_count``; avg-pool replicates torch's
  ``count_include_pad=False`` denominator.
- search network: softmax-mixed ops over a DAG cell
  (model_search.py:10-246). Architecture logits live in the SAME flax
  param tree as weights (``alphas_normal``/``alphas_reduce``) and are
  split off by name for the bilevel optimizers — no special Parameter
  class, no ``arch_parameters()`` accessors.
- GDAS variant (model_search_gdas.py): straight-through Gumbel-softmax
  hard op selection per edge, ``gumbel=True`` + a ``gumbel`` RNG stream
  (one fused program; the reference builds a second model class).
- genotype constants + derivation: genotypes.py:1-91,
  model_search.py:258-291 (top-2 incoming edges by best non-'none'
  weight).
- evaluation network from a fixed genotype with drop-path + auxiliary
  head: model.py:9-160.
- bilevel architect (architect.py): the torch version approximates the
  second-order term of the unrolled objective with finite differences
  (architect.py:121-180). Here the inner SGD step is differentiated
  EXACTLY — ``jax.grad`` through ``w' = w - eta*(mu*buf + dL_tr/dw +
  wd*w)`` — and XLA compiles the whole bilevel step into one program.
  The FedNAS first-order variant (``step_v2``, architect.py:57-104:
  g_val + lambda*g_train on arch params) is ``arch_grad_regularized``.

BatchNorm during search runs in batch-stats mode with no running-average
tracking (torch keeps train-mode BN whose running stats are never
consumed, operations.py affine=False) — the search step stays purely
functional. The fixed evaluation network tracks ``batch_stats`` like the
rest of the zoo.
"""

from __future__ import annotations

from collections import namedtuple
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

Genotype = namedtuple("Genotype", "normal normal_concat reduce reduce_concat")

PRIMITIVES = (
    "none",
    "max_pool_3x3",
    "avg_pool_3x3",
    "skip_connect",
    "sep_conv_3x3",
    "sep_conv_5x5",
    "dil_conv_3x3",
    "dil_conv_5x5",
)

# Published architecture constants (genotypes.py:16-91).
DARTS_V1 = Genotype(
    normal=[("sep_conv_3x3", 1), ("sep_conv_3x3", 0), ("skip_connect", 0),
            ("sep_conv_3x3", 1), ("skip_connect", 0), ("sep_conv_3x3", 1),
            ("sep_conv_3x3", 0), ("skip_connect", 2)],
    normal_concat=[2, 3, 4, 5],
    reduce=[("max_pool_3x3", 0), ("max_pool_3x3", 1), ("skip_connect", 2),
            ("max_pool_3x3", 0), ("max_pool_3x3", 0), ("skip_connect", 2),
            ("skip_connect", 2), ("avg_pool_3x3", 0)],
    reduce_concat=[2, 3, 4, 5])
DARTS_V2 = Genotype(
    normal=[("sep_conv_3x3", 0), ("sep_conv_3x3", 1), ("sep_conv_3x3", 0),
            ("sep_conv_3x3", 1), ("sep_conv_3x3", 1), ("skip_connect", 0),
            ("skip_connect", 0), ("dil_conv_3x3", 2)],
    normal_concat=[2, 3, 4, 5],
    reduce=[("max_pool_3x3", 0), ("max_pool_3x3", 1), ("skip_connect", 2),
            ("max_pool_3x3", 1), ("max_pool_3x3", 0), ("skip_connect", 2),
            ("skip_connect", 2), ("max_pool_3x3", 1)],
    reduce_concat=[2, 3, 4, 5])
FedNAS_V1 = Genotype(
    normal=[("sep_conv_3x3", 1), ("sep_conv_3x3", 0), ("sep_conv_3x3", 2),
            ("sep_conv_5x5", 0), ("sep_conv_3x3", 1), ("sep_conv_5x5", 3),
            ("dil_conv_5x5", 3), ("sep_conv_3x3", 4)],
    normal_concat=list(range(2, 6)),
    reduce=[("max_pool_3x3", 0), ("skip_connect", 1), ("max_pool_3x3", 0),
            ("max_pool_3x3", 2), ("max_pool_3x3", 0), ("dil_conv_5x5", 1),
            ("max_pool_3x3", 0), ("dil_conv_5x5", 2)],
    reduce_concat=list(range(2, 6)))
DARTS = DARTS_V2


# ---------------------------------------------------------------------------
# candidate operations (operations.py:4-107)
# ---------------------------------------------------------------------------


def _pair(v: int) -> tuple[int, int]:
    return (v, v)


def _pad(k: int, dilation: int = 1) -> Sequence[tuple[int, int]]:
    p = dilation * (k - 1) // 2
    return [(p, p), (p, p)]


def avg_pool_3x3(x: jax.Array, stride: int) -> jax.Array:
    """3x3 avg pool, pad 1, torch ``count_include_pad=False``: divide each
    window sum by the number of REAL (unpadded) elements in the window."""
    pad = [(1, 1), (1, 1)]
    s = nn.pooling.pool(x, 0.0, jax.lax.add, (3, 3), _pair(stride), pad)
    ones = jnp.ones((1,) + x.shape[1:3] + (1,), x.dtype)
    cnt = nn.pooling.pool(ones, 0.0, jax.lax.add, (3, 3), _pair(stride), pad)
    return s / cnt


def max_pool_3x3(x: jax.Array, stride: int) -> jax.Array:
    return nn.max_pool(x, (3, 3), _pair(stride), [(1, 1), (1, 1)])


class _BN(nn.Module):
    """Normalization in two modes. Search mode (``track=False``): per-batch
    statistics, stateless — no ``batch_stats`` collection at all, so the
    bilevel step stays purely functional (the torch search net also never
    consumes its running stats: train-mode BN, affine=False,
    operations.py). Fixed-net mode (``track=True``): standard tracked
    BatchNorm honoring train/eval."""

    affine: bool = True
    track: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.track:
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                epsilon=1e-5, use_scale=self.affine,
                                use_bias=self.affine, dtype=self.dtype)(x)
        mean = jnp.mean(x, axis=(0, 1, 2))
        var = jnp.var(x, axis=(0, 1, 2))
        y = (x - mean) * jax.lax.rsqrt(var + 1e-5)
        if self.affine:
            c = x.shape[-1]
            y = (y * self.param("scale", nn.initializers.ones, (c,))
                 + self.param("bias", nn.initializers.zeros, (c,)))
        return y.astype(self.dtype)


class ReLUConvBN(nn.Module):
    c_out: int
    kernel: int
    stride: int
    affine: bool = True
    track: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.relu(x)
        x = nn.Conv(self.c_out, _pair(self.kernel), _pair(self.stride),
                    padding=_pad(self.kernel), use_bias=False,
                    dtype=self.dtype)(x)
        return _BN(self.affine, self.track, self.dtype)(x, train)


class SepConv(nn.Module):
    """Two stacked depthwise-separable convs (operations.py:55-71)."""

    c_out: int
    kernel: int
    stride: int
    affine: bool = True
    track: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c_in = x.shape[-1]
        x = nn.relu(x)
        x = nn.Conv(c_in, _pair(self.kernel), _pair(self.stride),
                    padding=_pad(self.kernel), feature_group_count=c_in,
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.Conv(c_in, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = _BN(self.affine, self.track, self.dtype)(x, train)
        x = nn.relu(x)
        x = nn.Conv(c_in, _pair(self.kernel), (1, 1),
                    padding=_pad(self.kernel), feature_group_count=c_in,
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.Conv(self.c_out, (1, 1), use_bias=False, dtype=self.dtype)(x)
        return _BN(self.affine, self.track, self.dtype)(x, train)


class DilConv(nn.Module):
    c_out: int
    kernel: int
    stride: int
    dilation: int = 2
    affine: bool = True
    track: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        c_in = x.shape[-1]
        x = nn.relu(x)
        x = nn.Conv(c_in, _pair(self.kernel), _pair(self.stride),
                    padding=_pad(self.kernel, self.dilation),
                    kernel_dilation=_pair(self.dilation),
                    feature_group_count=c_in, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.Conv(self.c_out, (1, 1), use_bias=False, dtype=self.dtype)(x)
        return _BN(self.affine, self.track, self.dtype)(x, train)


class FactorizedReduce(nn.Module):
    """Stride-2 channel-preserving reduce: two offset 1x1/s2 convs,
    concatenated (operations.py:95-107)."""

    c_out: int
    affine: bool = True
    track: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.relu(x)
        a = nn.Conv(self.c_out // 2, (1, 1), (2, 2), padding="VALID",
                    use_bias=False, dtype=self.dtype)(x)
        b = nn.Conv(self.c_out // 2, (1, 1), (2, 2), padding="VALID",
                    use_bias=False, dtype=self.dtype)(x[:, 1:, 1:, :])
        out = jnp.concatenate([a, b], axis=-1)
        return _BN(self.affine, self.track, self.dtype)(out, train)


class Conv7x1_1x7(nn.Module):
    """Factorized 7x7 (operations.py:14-19); used by the NASNet genotype."""

    c_out: int
    stride: int
    affine: bool = True
    track: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.relu(x)
        x = nn.Conv(self.c_out, (1, 7), (1, self.stride),
                    padding=[(0, 0), (3, 3)], use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.Conv(self.c_out, (7, 1), (self.stride, 1),
                    padding=[(3, 3), (0, 0)], use_bias=False,
                    dtype=self.dtype)(x)
        return _BN(self.affine, self.track, self.dtype)(x, train)


def _zero(x: jax.Array, stride: int) -> jax.Array:
    if stride == 1:
        return jnp.zeros_like(x)
    return jnp.zeros_like(x[:, ::stride, ::stride, :])


class _Op(nn.Module):
    """One primitive by name (OPS table, operations.py:4-20). In search
    mode (``bn_after_pool=True``) pooling ops get a trailing affine-less
    BN (model_search.py:17-18)."""

    prim: str
    c: int
    stride: int
    affine: bool = True
    track: bool = False
    bn_after_pool: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        n, s = self.prim, self.stride
        kw = dict(affine=self.affine, track=self.track, dtype=self.dtype)
        if n == "none":
            return _zero(x, s)
        if n == "skip_connect":
            return x if s == 1 else FactorizedReduce(self.c, **kw)(x, train)
        if n in ("max_pool_3x3", "avg_pool_3x3"):
            y = (max_pool_3x3(x, s) if n.startswith("max")
                 else avg_pool_3x3(x, s))
            if self.bn_after_pool:
                y = _BN(False, self.track, self.dtype)(y, train)
            return y
        if n.startswith("sep_conv"):
            k = int(n[-1])
            return SepConv(self.c, k, s, **kw)(x, train)
        if n.startswith("dil_conv"):
            k = int(n[-1])
            return DilConv(self.c, k, s, 2, **kw)(x, train)
        if n == "conv_7x1_1x7":
            return Conv7x1_1x7(self.c, s, **kw)(x, train)
        raise ValueError(f"unknown primitive {n!r}")


# ---------------------------------------------------------------------------
# search network (model_search.py)
# ---------------------------------------------------------------------------


def num_edges(steps: int) -> int:
    return sum(2 + i for i in range(steps))


class MixedOp(nn.Module):
    """Softmax-weighted sum over all primitives (model_search.py:10-23)."""

    c: int
    stride: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, weights, train: bool = True):
        outs = [_Op(p, self.c, self.stride, affine=False,
                    bn_after_pool=True, dtype=self.dtype)(x, train)
                for p in PRIMITIVES]
        return sum(w * o for w, o in zip(weights, outs))


class SearchCell(nn.Module):
    """DAG cell: 2 preprocessed inputs + ``steps`` intermediate nodes, each
    the weighted sum of mixed ops over all predecessors
    (model_search.py:26-60)."""

    c: int
    steps: int
    multiplier: int
    reduction: bool
    reduction_prev: bool
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, s0, s1, weights, train: bool = True):
        pre = dict(affine=False, dtype=self.dtype)
        if self.reduction_prev:
            s0 = FactorizedReduce(self.c, **pre)(s0, train)
        else:
            s0 = ReLUConvBN(self.c, 1, 1, **pre)(s0, train)
        s1 = ReLUConvBN(self.c, 1, 1, **pre)(s1, train)
        states = [s0, s1]
        offset = 0
        for _ in range(self.steps):
            s = sum(
                MixedOp(self.c,
                        2 if self.reduction and j < 2 else 1,
                        dtype=self.dtype)(h, weights[offset + j], train)
                for j, h in enumerate(states))
            offset += len(states)
            states.append(s)
        return jnp.concatenate(states[-self.multiplier:], axis=-1)


def _gumbel_hard(logits: jax.Array, rng: jax.Array, tau: float) -> jax.Array:
    """Straight-through Gumbel-softmax rows (GDAS,
    model_search_gdas.py): hard one-hot forward, soft gradient."""
    g = jax.random.gumbel(rng, logits.shape)
    soft = jax.nn.softmax((logits + g) / tau, axis=-1)
    hard = jax.nn.one_hot(jnp.argmax(soft, -1), logits.shape[-1],
                          dtype=soft.dtype)
    return hard + soft - jax.lax.stop_gradient(soft)


class DartsSearchNet(nn.Module):
    """The over-parameterized search supernet (model_search.py:171-246).

    ``gumbel=True`` switches the edge mixture from softmax to
    straight-through Gumbel-softmax (GDAS) using the ``gumbel`` RNG
    stream and temperature ``tau``.
    """

    c: int = 16
    num_classes: int = 10
    layers: int = 8
    steps: int = 4
    multiplier: int = 4
    stem_multiplier: int = 3
    gumbel: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True, tau: float = 1.0):
        k = num_edges(self.steps)
        init = nn.initializers.normal(stddev=1e-3)
        alphas_normal = self.param("alphas_normal", init,
                                   (k, len(PRIMITIVES)))
        alphas_reduce = self.param("alphas_reduce", init,
                                   (k, len(PRIMITIVES)))
        if self.gumbel and not train:
            # deterministic GDAS eval: noise-free argmax one-hot selection
            w_normal = jax.nn.one_hot(jnp.argmax(alphas_normal, -1),
                                      len(PRIMITIVES))
            w_reduce = jax.nn.one_hot(jnp.argmax(alphas_reduce, -1),
                                      len(PRIMITIVES))
        elif self.gumbel:
            rng = self.make_rng("gumbel")
            rn, rr = jax.random.split(rng)
            w_normal = _gumbel_hard(alphas_normal, rn, tau)
            w_reduce = _gumbel_hard(alphas_reduce, rr, tau)
        else:
            w_normal = jax.nn.softmax(alphas_normal, axis=-1)
            w_reduce = jax.nn.softmax(alphas_reduce, axis=-1)

        c_curr = self.stem_multiplier * self.c
        s = nn.Conv(c_curr, (3, 3), padding=[(1, 1), (1, 1)], use_bias=False,
                    dtype=self.dtype)(x)
        s0 = s1 = _BN(True, False, self.dtype)(s, train)

        c_curr = self.c
        reduction_prev = False
        for i in range(self.layers):
            reduction = i in (self.layers // 3, 2 * self.layers // 3)
            if reduction:
                c_curr *= 2
            cell = SearchCell(c_curr, self.steps, self.multiplier, reduction,
                              reduction_prev, dtype=self.dtype)
            s0, s1 = s1, cell(s0, s1,
                              w_reduce if reduction else w_normal, train)
            reduction_prev = reduction
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype)(out)


def derive_genotype(alphas_normal, alphas_reduce, steps: int = 4,
                    multiplier: int = 4) -> Genotype:
    """Discrete architecture from arch logits (model_search.py:258-291):
    per node keep the 2 incoming edges with the highest best-non-'none'
    weight; per kept edge the best non-'none' op."""

    def _parse(alphas):
        w = np.asarray(jax.nn.softmax(jnp.asarray(alphas), axis=-1))
        none_idx = PRIMITIVES.index("none")
        gene, start = [], 0
        for i in range(steps):
            n = i + 2
            rows = w[start:start + n]
            best = [max(rows[j][k] for k in range(len(PRIMITIVES))
                        if k != none_idx) for j in range(n)]
            edges = sorted(range(n), key=lambda j: -best[j])[:2]
            for j in sorted(edges):
                ks = [k for k in range(len(PRIMITIVES)) if k != none_idx]
                k_best = max(ks, key=lambda k: rows[j][k])
                gene.append((PRIMITIVES[k_best], j))
            start += n
        return gene

    concat = list(range(2 + steps - multiplier, steps + 2))
    return Genotype(normal=_parse(alphas_normal), normal_concat=concat,
                    reduce=_parse(alphas_reduce), reduce_concat=concat)


# ---------------------------------------------------------------------------
# fixed-genotype evaluation network (model.py)
# ---------------------------------------------------------------------------


def _drop_path(x: jax.Array, rng: jax.Array, prob: float) -> jax.Array:
    keep = 1.0 - prob
    mask = jax.random.bernoulli(rng, keep, (x.shape[0],) + (1,) * (x.ndim - 1))
    return x * mask.astype(x.dtype) / keep


class FixedCell(nn.Module):
    """Cell compiled from a genotype (model.py:9-61): per node exactly two
    incoming edges with fixed ops, drop-path on non-identity edges."""

    genotype: Genotype
    c: int
    reduction: bool
    reduction_prev: bool
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, s0, s1, train: bool = True, drop_prob: float = 0.0):
        kw = dict(affine=True, track=True, dtype=self.dtype)
        if self.reduction_prev:
            s0 = FactorizedReduce(self.c, **kw)(s0, train)
        else:
            s0 = ReLUConvBN(self.c, 1, 1, **kw)(s0, train)
        s1 = ReLUConvBN(self.c, 1, 1, **kw)(s1, train)

        gene = self.genotype.reduce if self.reduction else self.genotype.normal
        concat = (self.genotype.reduce_concat if self.reduction
                  else self.genotype.normal_concat)
        names, indices = zip(*gene)
        steps = len(names) // 2

        states = [s0, s1]
        for i in range(steps):
            hs = []
            for slot in (2 * i, 2 * i + 1):
                name, idx = names[slot], indices[slot]
                stride = 2 if self.reduction and idx < 2 else 1
                h = _Op(name, self.c, stride, **kw)(states[idx], train)
                # drop-path exempts only true Identity edges (model.py:52-57)
                # — a stride-2 skip_connect is a FactorizedReduce and IS
                # drop-pathed by the reference. ``drop_prob`` may be a traced
                # scalar (the per-epoch schedule runs inside jit): gate on
                # static facts only, but a STATIC 0.0 skips the rng entirely
                # so plain train-mode applies need no "droppath" stream.
                is_identity = name == "skip_connect" and stride == 1
                # concrete zero (Python scalar OR un-traced array) skips the
                # rng; only a genuinely traced schedule pays drop-path at 0
                static_zero = (not isinstance(drop_prob, jax.core.Tracer)
                               and float(drop_prob) == 0.0)
                if train and not is_identity and not static_zero:
                    h = _drop_path(h, self.make_rng("droppath"), drop_prob)
                hs.append(h)
            states.append(hs[0] + hs[1])
        return jnp.concatenate([states[i] for i in concat], axis=-1)


class AuxiliaryHead(nn.Module):
    """CIFAR auxiliary classifier, assumes 8x8 input (model.py:64-83)."""

    num_classes: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.relu(x)
        x = nn.pooling.pool(x, 0.0, jax.lax.add, (5, 5), (3, 3),
                            "VALID") / 25.0
        x = nn.Conv(128, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = _BN(True, True, self.dtype)(x, train)
        x = nn.relu(x)
        x = nn.Conv(768, (2, 2), padding="VALID", use_bias=False,
                    dtype=self.dtype)(x)
        x = _BN(True, True, self.dtype)(x, train)
        x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(
            x.reshape(x.shape[0], -1))


class DartsNetwork(nn.Module):
    """Evaluation network from a fixed genotype (NetworkCIFAR,
    model.py:113-160). Returns ``(logits, logits_aux)`` like the
    reference (logits_aux is None unless ``auxiliary`` and training)."""

    genotype: Genotype = DARTS_V2
    c: int = 36
    num_classes: int = 10
    layers: int = 20
    auxiliary: bool = False
    stem_multiplier: int = 3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True, drop_path_prob: float = 0.0):
        c_curr = self.stem_multiplier * self.c
        s = nn.Conv(c_curr, (3, 3), padding=[(1, 1), (1, 1)], use_bias=False,
                    dtype=self.dtype)(x)
        s0 = s1 = _BN(True, True, self.dtype)(s, train)

        c_curr = self.c
        reduction_prev = False
        logits_aux = None
        aux_layer = 2 * self.layers // 3
        for i in range(self.layers):
            reduction = i in (self.layers // 3, 2 * self.layers // 3)
            if reduction:
                c_curr *= 2
            cell = FixedCell(self.genotype, c_curr, reduction,
                             reduction_prev, dtype=self.dtype)
            s0, s1 = s1, cell(s0, s1, train, drop_path_prob)
            reduction_prev = reduction
            if i == aux_layer and self.auxiliary:
                # params exist regardless of mode (torch builds the head in
                # __init__); the unused eval-mode branch is DCE'd by XLA
                aux = AuxiliaryHead(self.num_classes, self.dtype)(s1, train)
                logits_aux = aux if train else None
        out = jnp.mean(s1, axis=(1, 2))
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(out)
        return logits, logits_aux


# ---------------------------------------------------------------------------
# bilevel architect (architect.py) — exact unrolled gradient via autodiff
# ---------------------------------------------------------------------------

ARCH_KEYS = ("alphas_normal", "alphas_reduce")


def split_arch(params: dict) -> tuple[dict, dict]:
    """(arch, weights) partition of a search-net param tree by name."""
    arch = {k: params[k] for k in ARCH_KEYS}
    weights = {k: v for k, v in params.items() if k not in ARCH_KEYS}
    return arch, weights


def merge_arch(arch: dict, weights: dict) -> dict:
    return {**weights, **arch}


def arch_grad_unrolled(loss_fn, params: dict, train_batch, val_batch,
                       eta: float, momentum: float = 0.9,
                       weight_decay: float = 3e-4,
                       momentum_buf: dict | None = None) -> dict:
    """Exact gradient of the unrolled objective
    ``L_val(w - eta*(mu*buf + dL_train/dw + wd*w), alpha)`` w.r.t. alpha.

    ``loss_fn(params, batch) -> scalar``. The torch architect builds an
    unrolled model by hand and finite-differences the second-order term
    (architect.py:121-180); autodiff through the inner step gives the
    exact quantity in one compiled program.
    """
    arch, weights = split_arch(params)
    if momentum_buf is None:
        momentum_buf = jax.tree.map(jnp.zeros_like, weights)

    def val_after_inner(a):
        g_w = jax.grad(
            lambda w: loss_fn(merge_arch(a, w), train_batch))(weights)
        w2 = jax.tree.map(
            lambda w, g, m: w - eta * (momentum * m + g + weight_decay * w),
            weights, g_w, momentum_buf)
        return loss_fn(merge_arch(a, w2), val_batch)

    return jax.grad(val_after_inner)(arch)


def arch_grad_regularized(loss_fn, params: dict, train_batch, val_batch,
                          lambda_train: float = 1.0,
                          lambda_valid: float = 1.0) -> dict:
    """FedNAS ``step_v2`` (architect.py:57-104): first-order arch gradient
    ``lambda_valid * dL_val/da + lambda_train * dL_train/da``."""
    arch, weights = split_arch(params)

    def at(a, batch):
        return loss_fn(merge_arch(a, weights), batch)

    g_tr = jax.grad(lambda a: at(a, train_batch))(arch)
    g_val = jax.grad(lambda a: at(a, val_batch))(arch)
    return jax.tree.map(lambda gv, gt: lambda_valid * gv + lambda_train * gt,
                        g_val, g_tr)


def _sgd_momentum_chain(lr: float, total_steps: int, momentum: float,
                        weight_decay: float, grad_clip: float,
                        alpha: float = 0.0):
    """The reference's weight optimizer (train_search.py:24-45 /
    train.py): clip -> L2 -> momentum -> cosine-annealed SGD scale."""
    import optax

    sched = optax.cosine_decay_schedule(lr, total_steps, alpha=alpha)
    return sched, optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.add_decayed_weights(weight_decay),
        optax.trace(decay=momentum, nesterov=False),
        optax.scale_by_schedule(lambda s: -sched(s)))


class DartsSearch:
    """Compact bilevel search driver (train_search.py:240-284 semantics):
    per batch, one architect Adam step on (alphas | val batch) then one
    clipped-SGD-momentum step on (weights | train batch).

    Reference hyperparameters preserved as defaults: weight SGD lr 0.025
    cosine-annealed to 0.001, momentum 0.9, wd 3e-4, grad clip 5; arch
    Adam lr 3e-4, betas (0.5, 0.999), wd 1e-3 (train_search.py:24-45).
    """

    def __init__(self, net: DartsSearchNet, num_classes: int,
                 lr: float = 0.025, lr_min: float = 0.001,
                 momentum: float = 0.9, weight_decay: float = 3e-4,
                 grad_clip: float = 5.0, arch_lr: float = 3e-4,
                 arch_weight_decay: float = 1e-3, unrolled: bool = False,
                 total_steps: int = 1000):
        import optax

        if net.gumbel:
            raise ValueError(
                "DartsSearch drives the softmax supernet; for GDAS apply "
                "the gumbel=True net directly with a 'gumbel' RNG stream")
        self.net = net
        self.unrolled = unrolled
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.lr_sched, self.w_opt = _sgd_momentum_chain(
            lr, total_steps, momentum, weight_decay, grad_clip,
            alpha=lr_min / lr)
        self.a_opt = optax.chain(
            optax.add_decayed_weights(arch_weight_decay),
            optax.scale_by_adam(b1=0.5, b2=0.999),
            optax.scale(-arch_lr))
        self.num_classes = num_classes
        self._step = jax.jit(self._step_impl)

    def loss_fn(self, params, batch):
        from neuroimagedisttraining_tpu.core.losses import softmax_ce

        x, y = batch
        logits = self.net.apply({"params": params}, x, train=True)
        return softmax_ce(logits, y)

    def init(self, rng, sample_input):
        params = self.net.init(rng, sample_input, train=False)["params"]
        arch, weights = split_arch(params)
        return {"params": params, "w_opt": self.w_opt.init(weights),
                "a_opt": self.a_opt.init(arch), "step": jnp.zeros((), jnp.int32)}

    def _step_impl(self, state, train_batch, val_batch):
        params = state["params"]
        arch, weights = split_arch(params)
        eta = self.lr_sched(state["step"])

        if self.unrolled:
            # momentum buffer lives in optax.trace state (index 2 in chain)
            buf = state["w_opt"][2].trace
            g_a = arch_grad_unrolled(self.loss_fn, params, train_batch,
                                     val_batch, eta, self.momentum,
                                     self.weight_decay, buf)
        else:
            g_a = jax.grad(lambda a: self.loss_fn(
                merge_arch(a, weights), val_batch))(arch)
        a_up, a_opt = self.a_opt.update(g_a, state["a_opt"], arch)
        arch = jax.tree.map(lambda p, u: p + u, arch, a_up)

        loss, g_w = jax.value_and_grad(lambda w: self.loss_fn(
            merge_arch(arch, w), train_batch))(weights)
        w_up, w_opt = self.w_opt.update(g_w, state["w_opt"], weights)
        weights = jax.tree.map(lambda p, u: p + u, weights, w_up)

        return {"params": merge_arch(arch, weights), "w_opt": w_opt,
                "a_opt": a_opt, "step": state["step"] + 1}, loss

    def step(self, state, train_batch, val_batch):
        """One jitted bilevel update; returns (new_state, train_loss)."""
        return self._step(state, train_batch, val_batch)

    def genotype(self, state) -> Genotype:
        arch, _ = split_arch(state["params"])
        return derive_genotype(arch["alphas_normal"], arch["alphas_reduce"],
                               self.net.steps, self.net.multiplier)


class DartsTrainer:
    """Evaluation-phase trainer for a fixed-genotype ``DartsNetwork``
    (train.py:80-238 semantics): cross-entropy + ``aux_weight`` x auxiliary
    loss (0.4, train.py:196), global-norm grad clip 5, SGD momentum 0.9
    wd 3e-4, cosine-annealed lr, and drop-path probability scaled linearly
    over training (train.py:180: ``drop_path_prob * epoch / epochs``)."""

    def __init__(self, net: DartsNetwork, num_classes: int,
                 lr: float = 0.025, momentum: float = 0.9,
                 weight_decay: float = 3e-4, grad_clip: float = 5.0,
                 aux_weight: float = 0.4, drop_path_prob: float = 0.2,
                 total_steps: int = 1000):
        import optax

        self.net = net
        self.num_classes = num_classes
        self.aux_weight = aux_weight
        self.drop_path_prob = drop_path_prob
        self.total_steps = total_steps
        _, self.opt = _sgd_momentum_chain(lr, total_steps, momentum,
                                          weight_decay, grad_clip)
        self._step = jax.jit(self._step_impl)

    def init(self, rng, sample_input):
        variables = self.net.init(
            {"params": rng, "droppath": jax.random.fold_in(rng, 1)},
            sample_input, train=False)
        return {"variables": variables,
                "opt": self.opt.init(variables["params"]),
                "step": jnp.zeros((), jnp.int32)}

    def _step_impl(self, state, batch, rng):
        from neuroimagedisttraining_tpu.core.losses import softmax_ce

        x, y = batch
        variables = state["variables"]
        # linear schedule, clamped: stepping past total_steps must not push
        # the drop probability beyond the configured max (keep_prob -> 0
        # would NaN the activations)
        frac = jnp.minimum(
            state["step"].astype(jnp.float32) / self.total_steps, 1.0)  # nidt: allow[precision-upcast] -- int step counter to f32 schedule fraction, not an activation
        dpp = self.drop_path_prob * frac

        def loss_fn(params):
            out, mutated = self.net.apply(
                {**variables, "params": params}, x, train=True,
                drop_path_prob=dpp, rngs={"droppath": rng},
                mutable=["batch_stats"])
            logits, aux = out
            loss = softmax_ce(logits, y)
            if aux is not None:
                loss = loss + self.aux_weight * softmax_ce(aux, y)
            return loss, mutated["batch_stats"]

        (loss, bstats), g = jax.value_and_grad(loss_fn, has_aux=True)(
            variables["params"])
        up, opt = self.opt.update(g, state["opt"], variables["params"])
        params = jax.tree.map(lambda p, u: p + u, variables["params"], up)
        return {"variables": {"params": params, "batch_stats": bstats},
                "opt": opt, "step": state["step"] + 1}, loss

    def step(self, state, batch, rng):
        """One jitted training step; returns (new_state, loss)."""
        return self._step(state, batch, rng)
