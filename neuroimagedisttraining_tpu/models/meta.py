"""Mask-parameterized "meta" models for structured-pruning experiments.

Reference: cnn_meta.py:17-176 (``cnn_cifar10_meta``: a bias-free CIFAR CNN
whose two convs + fc carry external binary masks, plus random mask init
utilities) and ``Meta_net`` (cnn_meta.py:146-176: a hypernetwork MLP that
maps a flattened mask to a conv weight tensor of the same shape). The
reference wires these only into legacy ``set_client.py`` experiments; they
are provided here for zoo parity.

TPU re-design notes: masks are pytree inputs (not monkey-patched module
attributes); the mask-to-weight hypernetwork is a plain Flax MLP applied
per-tensor. The torch mask init draws ``randperm``-without-replacement over
flat indices; here the same marginal density uses a uniform top-k draw
(exact nnz like the reference, cnn_meta.py:58-67).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class CNNCifarMeta(nn.Module):
    """Bias-free masked CNN (cnn_meta.py:83-145): conv5x5(64) -> pool3/2 ->
    conv5x5(64) -> pool3/2 -> fc(64*4*4 -> classes). ``masks`` (optional)
    holds {"meta_conv1", "meta_conv2"} kernels' binary masks, applied as
    ``w * mask`` — the masked-forward semantics the torch version gets by
    multiplying ``module.weight`` in place."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, masks: dict | None = None, train: bool = True):
        def conv_block(name, x):
            kernel = self.param(f"{name}_kernel", nn.initializers.he_uniform(),
                                (5, 5, x.shape[-1], 64), self.dtype)
            if masks is not None and name in masks:
                kernel = kernel * masks[name].astype(kernel.dtype)
            y = jax.lax.conv_general_dilated(
                x.astype(self.dtype), kernel, (1, 1), "VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            y = nn.relu(y)
            return nn.max_pool(y, (3, 3), (2, 2))

        x = conv_block("meta_conv1", x)
        x = conv_block("meta_conv2", x)
        x = x.reshape(x.shape[0], -1)
        w = self.param("meta_fc1_kernel", nn.initializers.he_uniform(),
                       (x.shape[-1], self.num_classes), self.dtype)
        if masks is not None and "meta_fc1" in masks:
            w = w * masks["meta_fc1"].astype(w.dtype)
        return x @ w

    @staticmethod
    def init_masks(rng: jax.Array, params: dict,
                   dense_ratio: float = 0.2) -> dict:
        """Random binary masks at exact per-tensor density for every
        ``meta_*`` tensor (parity with init_masks/init_conv_masks,
        cnn_meta.py:47-67: randperm keeps exactly
        ``int(dense_ratio * numel)`` ones)."""
        masks = {}
        for name, w in params.items():
            if not name.endswith("_kernel"):
                continue
            rng, sub = jax.random.split(rng)
            n = w.size
            nnz = int(dense_ratio * n)
            scores = jax.random.uniform(sub, (n,))
            thr = jnp.sort(scores)[n - nnz] if nnz > 0 else jnp.inf
            masks[name.removesuffix("_kernel")] = (
                (scores >= thr).astype(jnp.float32).reshape(w.shape))
        return masks


class MetaNet(nn.Module):
    """Hypernetwork mask -> conv-weight (Meta_net, cnn_meta.py:146-166):
    flatten -> fc(50) -> relu -> fc(50) -> relu -> fc(size) -> reshape."""

    hidden: int = 50
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, mask: jax.Array) -> jax.Array:
        size = mask.size
        x = mask.reshape(-1).astype(self.dtype)
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype,
                             kernel_init=nn.initializers.he_uniform())(x))
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype,
                             kernel_init=nn.initializers.he_uniform())(x))
        w = nn.Dense(size, dtype=self.dtype,
                     kernel_init=nn.initializers.he_uniform())(x)
        return w.reshape(mask.shape)


# ---------------- slimmable hypernetwork ResNet (resnet_meta analogs) ----

# resnet_meta_2.py:8-10 — 31 width multipliers 0.10 .. 1.00 step 0.03
CHANNEL_SCALE = tuple((10 + i * 3) / 100 for i in range(31))


def _hyper_kernel(self, name: str, scales: jax.Array, shape, hidden=32,
                  dtype=jnp.float32):
    """Scale-conditioned conv kernel (resnet_meta_2.py:32-36, 74-82): the
    kernel is GENERATED per forward by fc(|scales|)->32->relu->fc(prod)
    from the width-scale vector, so one parameter set serves every width."""
    import math

    h = nn.Dense(hidden, dtype=dtype, name=f"{name}_fc1")(
        scales.astype(dtype))
    w = nn.Dense(math.prod(shape), dtype=dtype,
                 name=f"{name}_fc2")(nn.relu(h))
    return w.reshape(shape)


def _width_mask(max_ch: int, scale: jax.Array, dtype) -> jax.Array:
    """Static-shape analog of the reference's ``weight[:oup]`` channel
    slicing (resnet_meta_2.py:84-90): channels past ``round(max*scale)``
    are masked to zero. Keeps every shape static so the whole width sweep
    jits as one program with ``scale`` a traced scalar."""
    active = jnp.round(max_ch * scale).astype(jnp.int32)
    return (jnp.arange(max_ch) < active).astype(dtype)


class SlimBottleneckMeta(nn.Module):
    """Width-slimmable bottleneck with hypernetwork kernels
    (resnet_meta_2.py:60-156 ``Bottleneck``): 1x1 reduce -> 3x3 -> 1x1
    expand, each kernel generated from the (mid, inp, oup) scale vector,
    plus a generated 1x1 downsample on shape-changing blocks.

    Deviations (documented): the reference keeps one affine-less
    BatchNorm per discrete width id (resnet_meta_2.py:83-97); here a
    single affine-less norm runs over the masked activations and inactive
    channels are re-masked after it — statistics over active channels
    match, and there is one compiled program for all widths instead of 31.
    """

    max_inp: int
    max_oup: int
    stride: int = 1
    is_downsample: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, scales: jax.Array, train: bool = True):
        mid_s, inp_s, oup_s = scales[0], scales[1], scales[2]
        max_mid = self.max_oup // 4          # expansion = 4
        dt = self.dtype

        def norm(name):
            return nn.BatchNorm(use_running_average=not train,
                                use_bias=False, use_scale=False,
                                dtype=dt, name=name)

        def conv(h, kernel, stride, mask_in, mask_out):
            kernel = (kernel * mask_in[None, None, :, None]
                      * mask_out[None, None, None, :])
            return jax.lax.conv_general_dilated(
                h.astype(dt), kernel, (stride, stride), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        m_inp = _width_mask(self.max_inp, inp_s, dt)
        m_mid = _width_mask(max_mid, mid_s, dt)
        m_oup = _width_mask(self.max_oup, oup_s, dt)

        k1 = _hyper_kernel(self, "conv1", scales,
                           (1, 1, self.max_inp, max_mid), dtype=dt)
        k2 = _hyper_kernel(self, "conv2", scales,
                           (3, 3, max_mid, max_mid), dtype=dt)
        k3 = _hyper_kernel(self, "conv3", scales,
                           (1, 1, max_mid, self.max_oup), dtype=dt)

        out = conv(x, k1, 1, m_inp, m_mid)
        out = nn.relu(norm("bn1")(out) * m_mid)
        out = conv(out, k2, self.stride, m_mid, m_mid)
        out = nn.relu(norm("bn2")(out) * m_mid)
        out = conv(out, k3, 1, m_mid, m_oup)
        out = norm("bn3")(out) * m_oup

        identity = x
        if self.is_downsample:
            kd = _hyper_kernel(self, "conv_ds", scales,
                               (1, 1, self.max_inp, self.max_oup), dtype=dt)
            identity = conv(x, kd, self.stride, m_inp, m_oup)
            identity = norm("bn_ds")(identity) * m_oup
        return nn.relu(out + identity)


class ResNetMeta(nn.Module):
    """Slimmable hypernetwork ResNet (resnet_meta_2.py:158-195
    ``ResNet20``): a 7x7 stem whose kernel is generated from the stem
    width scale (first_conv_block, resnet_meta_2.py:22-58), three
    bottleneck stages with per-stage width ids into CHANNEL_SCALE, global
    average pool, linear head.

    The reference's in-repo assembly is unrunnable (stage channel
    arithmetic references undefined values); this analog keeps its
    documented contract — forward(x, stage_oup_scale_ids, mid_scale_ids)
    with widths drawn from CHANNEL_SCALE — on a consistent
    16 -> 32 -> 64 -> 64 stage plan. ``resnet_meta.py`` (v1) is the same
    idea with in-place masked convs and is written off in COVERAGE.md.
    """

    num_classes: int = 10
    stage_planes: tuple = (16, 32, 64, 64)
    stage_strides: tuple = (1, 1, 2, 2)
    dtype: Any = jnp.float32
    input_rank = 4

    @nn.compact
    def __call__(self, x, stage_ids=None, mid_ids=None, train: bool = True):
        dt = self.dtype
        n_blocks = len(self.stage_planes) - 1
        if stage_ids is None:  # default: full width everywhere
            stage_ids = jnp.full((n_blocks + 1,), len(CHANNEL_SCALE) - 1,
                                 jnp.int32)
        if mid_ids is None:
            mid_ids = jnp.full((n_blocks,), len(CHANNEL_SCALE) - 1,
                               jnp.int32)
        table = jnp.asarray(CHANNEL_SCALE, dt)

        # stem (first_conv_block): generated 7x7 kernel, width-masked
        stem_s = table[stage_ids[0]]
        k0 = _hyper_kernel(self, "stem", stem_s[None],
                           (7, 7, x.shape[-1], self.stage_planes[0]),
                           dtype=dt)
        m0 = _width_mask(self.stage_planes[0], stem_s, dt)
        h = jax.lax.conv_general_dilated(
            x.astype(dt), k0 * m0[None, None, None, :], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        h = nn.BatchNorm(use_running_average=not train, use_bias=False,
                         use_scale=False, dtype=dt, name="stem_bn")(h)
        h = nn.max_pool(nn.relu(h) * m0, (3, 3), (2, 2), padding="SAME")

        for b in range(n_blocks):
            scales = jnp.stack([table[mid_ids[b]], table[stage_ids[b]],
                                table[stage_ids[b + 1]]])
            h = SlimBottleneckMeta(
                max_inp=self.stage_planes[b],
                max_oup=self.stage_planes[b + 1],
                stride=self.stage_strides[b + 1], is_downsample=True,
                dtype=dt, name=f"block{b}")(h, scales, train=train)

        h = jnp.mean(h, axis=(1, 2))           # adaptive avg pool to 1x1
        return nn.Dense(self.num_classes, dtype=dt, name="fc")(
            h).astype(jnp.float32)
