"""Mask-parameterized "meta" models for structured-pruning experiments.

Reference: cnn_meta.py:17-176 (``cnn_cifar10_meta``: a bias-free CIFAR CNN
whose two convs + fc carry external binary masks, plus random mask init
utilities) and ``Meta_net`` (cnn_meta.py:146-176: a hypernetwork MLP that
maps a flattened mask to a conv weight tensor of the same shape). The
reference wires these only into legacy ``set_client.py`` experiments; they
are provided here for zoo parity.

TPU re-design notes: masks are pytree inputs (not monkey-patched module
attributes); the mask-to-weight hypernetwork is a plain Flax MLP applied
per-tensor. The torch mask init draws ``randperm``-without-replacement over
flat indices; here the same marginal density uses a uniform top-k draw
(exact nnz like the reference, cnn_meta.py:58-67).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class CNNCifarMeta(nn.Module):
    """Bias-free masked CNN (cnn_meta.py:83-145): conv5x5(64) -> pool3/2 ->
    conv5x5(64) -> pool3/2 -> fc(64*4*4 -> classes). ``masks`` (optional)
    holds {"meta_conv1", "meta_conv2"} kernels' binary masks, applied as
    ``w * mask`` — the masked-forward semantics the torch version gets by
    multiplying ``module.weight`` in place."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, masks: dict | None = None, train: bool = True):
        def conv_block(name, x):
            kernel = self.param(f"{name}_kernel", nn.initializers.he_uniform(),
                                (5, 5, x.shape[-1], 64), self.dtype)
            if masks is not None and name in masks:
                kernel = kernel * masks[name].astype(kernel.dtype)
            y = jax.lax.conv_general_dilated(
                x.astype(self.dtype), kernel, (1, 1), "VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            y = nn.relu(y)
            return nn.max_pool(y, (3, 3), (2, 2))

        x = conv_block("meta_conv1", x)
        x = conv_block("meta_conv2", x)
        x = x.reshape(x.shape[0], -1)
        w = self.param("meta_fc1_kernel", nn.initializers.he_uniform(),
                       (x.shape[-1], self.num_classes), self.dtype)
        if masks is not None and "meta_fc1" in masks:
            w = w * masks["meta_fc1"].astype(w.dtype)
        return x @ w

    @staticmethod
    def init_masks(rng: jax.Array, params: dict,
                   dense_ratio: float = 0.2) -> dict:
        """Random binary masks at exact per-tensor density for every
        ``meta_*`` tensor (parity with init_masks/init_conv_masks,
        cnn_meta.py:47-67: randperm keeps exactly
        ``int(dense_ratio * numel)`` ones)."""
        masks = {}
        for name, w in params.items():
            if not name.endswith("_kernel"):
                continue
            rng, sub = jax.random.split(rng)
            n = w.size
            nnz = int(dense_ratio * n)
            scores = jax.random.uniform(sub, (n,))
            thr = jnp.sort(scores)[n - nnz] if nnz > 0 else jnp.inf
            masks[name.removesuffix("_kernel")] = (
                (scores >= thr).astype(jnp.float32).reshape(w.shape))
        return masks


class MetaNet(nn.Module):
    """Hypernetwork mask -> conv-weight (Meta_net, cnn_meta.py:146-166):
    flatten -> fc(50) -> relu -> fc(50) -> relu -> fc(size) -> reshape."""

    hidden: int = 50
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, mask: jax.Array) -> jax.Array:
        size = mask.size
        x = mask.reshape(-1).astype(self.dtype)
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype,
                             kernel_init=nn.initializers.he_uniform())(x))
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype,
                             kernel_init=nn.initializers.he_uniform())(x))
        w = nn.Dense(size, dtype=self.dtype,
                     kernel_init=nn.initializers.he_uniform())(x)
        return w.reshape(mask.shape)
