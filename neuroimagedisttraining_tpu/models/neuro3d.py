"""3D neuroimaging CNNs (the models that matter for ABCD).

Layer-for-layer parity with the reference's torch definitions
(fedml_api/model/cv/salient_models.py:142-191 AlexNet3D_Dropout,
194-246 AlexNet3D_Deeper_Dropout, 248-297 AlexNet3D_Dropout_Regression,
84-139 ResNet_l3, 13-81 BasicBlock/Bottleneck), re-designed for TPU:

- **NDHWC layout** (channels-last) so XLA tiles Conv3D onto the MXU.
- ``dtype`` controls compute precision (bfloat16 on TPU); params stay f32.
- The flatten→Linear boundary is shape-inferred rather than hard-coded
  (the reference hard-codes 256 / 512 / 9216 input features, which silently
  assumes the 121x145x121 ABCD volume; salient_models.py:99,171,227).

Pooling uses VALID windows with floor semantics, matching torch's default
floor_mode MaxPool3d/AvgPool3d.
"""

from __future__ import annotations

import os
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any


def _pool(x, kind: str, k: int, s: int, pad: int = 0):
    dims = (1, k, k, k, 1)
    strides = (1, s, s, s, 1)
    padding = [(0, 0)] + [(pad, pad)] * 3 + [(0, 0)]
    if kind == "max":
        if s == k and pad == 0 and os.environ.get("NIDT_FAST_POOL") == "1":
            # opt-in scatter-free backward for the reference's
            # non-overlapping pools: ~4% faster step but carries extra
            # residual memory — see ops/pooling.py for the measured
            # trade-off and why it is not the default
            from neuroimagedisttraining_tpu.ops.pooling import (
                max_pool_3d_nonoverlap,
            )

            return max_pool_3d_nonoverlap(x, k)
        return nn.max_pool(x, dims[1:4], strides=strides[1:4], padding=padding[1:4])
    return nn.avg_pool(x, dims[1:4], strides=strides[1:4], padding=padding[1:4])


class _StemConv(nn.Module):
    """Drop-in for the stem ``nn.Conv`` (same "conv" param tree: kernel +
    bias) routing through ``ops.stemconv.stem_conv3d`` — the custom
    weight-gradient path. Constructed only when ``NIDT_FAST_STEM=1``."""

    features: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        from neuroimagedisttraining_tpu.ops.stemconv import stem_conv3d

        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (5, 5, 5, 1, self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (self.features,),
                          jnp.float32)
        y = stem_conv3d(x.astype(self.dtype), kernel.astype(self.dtype))
        return y + bias.astype(self.dtype)


class ConvBNReLU3D(nn.Module):
    """Conv3d + BatchNorm3d + ReLU block (salient_models.py:147-149 pattern).

    BatchNorm runs in the block's compute dtype (bf16 on TPU) with f32
    params/stats — keeping the huge early-stage activations half-width so
    the pool backward (select-and-scatter) doesn't blow HBM."""
    features: int
    kernel: int = 3
    stride: int = 1
    pad: int = 0
    dtype: Dtype = jnp.float32
    norm: str = "batch"  # "batch" | "group" (3D GroupNorm option — parity
    # with the functional GroupNorm3d, group_normalization.py:7-118)

    @nn.compact
    def __call__(self, x, train: bool = False):
        fast_stem = (os.environ.get("NIDT_FAST_STEM") == "1"
                     and self.kernel == 5 and self.stride == 2
                     and self.pad == 0 and x.shape[-1] == 1)
        if fast_stem:
            # opt-in Pallas weight-gradient for the C_in=1 stride-2 stem
            # (ops/stemconv.py); same param tree as nn.Conv ("conv")
            x = _StemConv(self.features, dtype=self.dtype, name="conv")(x)
        else:
            x = nn.Conv(self.features, (self.kernel,) * 3,
                        strides=(self.stride,) * 3,
                        padding=[(self.pad, self.pad)] * 3, dtype=self.dtype,
                        name="conv")(x)
        if self.norm == "group":
            x = nn.GroupNorm(num_groups=min(32, self.features),
                             dtype=self.dtype, name="gn")(x)
        else:
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-5, dtype=self.dtype, name="bn")(x)
        return nn.relu(x)


# Rematerialized block: the backward pass recomputes conv/bn activations
# instead of keeping all five feature stages live (HBM is the bottleneck for
# 121^3 volumes; trades ~1.3x FLOPs for ~4x activation memory).
RematConvBNReLU3D = nn.remat(ConvBNReLU3D, static_argnums=(2,))


class AlexNet3D_Dropout(nn.Module):
    """5-conv 3D AlexNet with dropout head; the ABCD flagship (``--model 3DCNN``,
    num_classes=1 + BCE). Parity: salient_models.py:142-191."""
    input_rank = 5  # input ndim incl. batch+channel (unannotated: not a flax field)
    num_classes: int = 2
    dtype: Dtype = jnp.float32
    # Rematerialization policy (HBM vs FLOPs trade; measured on TPU v5e,
    # PROFILE.md): False = none — fastest (+21% over remat) but only fits
    # ~64 samples in flight per chip (e.g. b16 x 4 vmapped clients);
    # "stem" = f0+f1 only (the large activations; costs the same as True
    # since f0's recompute IS the remat tax, but needs less HBM); True =
    # all stages. The harness picks automatically from the federation
    # shape (--remat auto, __main__.build_experiment).
    remat: bool | str = "stem"
    norm: str = "batch"  # "group" => GN3D variant (no running stats)

    def _blk(self, stage: int):
        if self.remat is True or (self.remat == "stem" and stage <= 1):
            return RematConvBNReLU3D
        return ConvBNReLU3D

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = self._blk(0)(64, kernel=5, stride=2, pad=0, dtype=self.dtype,
                         norm=self.norm, name="f0")(x, train)
        x = _pool(x, "max", 3, 3)
        x = self._blk(1)(128, kernel=3, stride=1, pad=0, dtype=self.dtype,
                         norm=self.norm, name="f1")(x, train)
        x = _pool(x, "max", 3, 3)
        x = self._blk(2)(192, kernel=3, pad=1, dtype=self.dtype,
                         norm=self.norm, name="f2")(x, train)
        x = self._blk(3)(192, kernel=3, pad=1, dtype=self.dtype,
                         norm=self.norm, name="f3")(x, train)
        x = self._blk(4)(128, kernel=3, pad=1, dtype=self.dtype,
                         norm=self.norm, name="f4")(x, train)
        x = _pool(x, "max", 3, 3)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(64, dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc2")(x)
        return x.astype(jnp.float32)


class AlexNet3D_Deeper_Dropout(nn.Module):
    """6-conv, 512-dim-flatten variant; returns ``[x, x]`` like the reference
    (salient_models.py:194-246)."""
    input_rank = 5  # input ndim incl. batch+channel (unannotated: not a flax field)
    num_classes: int = 2
    dtype: Dtype = jnp.float32
    remat: bool | str = "stem"  # same policy semantics as AlexNet3D_Dropout

    def _blk(self, stage: int):
        if self.remat is True or (self.remat == "stem" and stage <= 1):
            return RematConvBNReLU3D
        return ConvBNReLU3D

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = self._blk(0)(64, kernel=5, stride=2, pad=0, dtype=self.dtype, name="f0")(x, train)
        x = _pool(x, "max", 3, 3)
        x = self._blk(1)(128, kernel=3, stride=1, pad=0, dtype=self.dtype, name="f1")(x, train)
        x = _pool(x, "max", 3, 3)
        x = self._blk(2)(192, kernel=3, pad=1, dtype=self.dtype, name="f2")(x, train)
        x = self._blk(3)(384, kernel=3, pad=1, dtype=self.dtype, name="f3")(x, train)
        x = self._blk(4)(256, kernel=3, pad=1, dtype=self.dtype, name="f4")(x, train)
        x = self._blk(5)(256, kernel=3, pad=1, dtype=self.dtype, name="f5")(x, train)
        x = _pool(x, "max", 3, 3)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(64, dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc2")(x)
        x = x.astype(jnp.float32)
        return x, x


class AlexNet3D_Dropout_Regression(nn.Module):
    """Regression head; returns ``(pred.squeeze(), feature_map)``
    (salient_models.py:248-297)."""
    input_rank = 5  # input ndim incl. batch+channel (unannotated: not a flax field)
    num_classes: int = 1
    dtype: Dtype = jnp.float32
    remat: bool | str = "stem"  # same policy semantics as AlexNet3D_Dropout

    def _blk(self, stage: int):
        if self.remat is True or (self.remat == "stem" and stage <= 1):
            return RematConvBNReLU3D
        return ConvBNReLU3D

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = self._blk(0)(64, kernel=5, stride=2, pad=0, dtype=self.dtype, name="f0")(x, train)
        x = _pool(x, "max", 3, 3)
        x = self._blk(1)(128, kernel=3, stride=1, pad=0, dtype=self.dtype, name="f1")(x, train)
        x = _pool(x, "max", 3, 3)
        x = self._blk(2)(192, kernel=3, pad=1, dtype=self.dtype, name="f2")(x, train)
        x = self._blk(3)(192, kernel=3, pad=1, dtype=self.dtype, name="f3")(x, train)
        x = self._blk(4)(128, kernel=3, pad=1, dtype=self.dtype, name="f4")(x, train)
        xp = _pool(x, "max", 3, 3)
        x = xp.reshape((xp.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(64, dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc2")(x)
        return jnp.squeeze(x.astype(jnp.float32)), xp.astype(jnp.float32)


class Tiny3DCNN(nn.Module):
    """Small 2-conv 3D CNN for CI/tests on small synthetic volumes — the
    structural miniature of AlexNet3D_Dropout (conv-BN-relu-pool x2 + MLP
    head). Not in the reference zoo; serves its ``--ci`` fast-path role
    (sailentgrads_api.py:260-265) with real Conv3D+BN+Dropout semantics."""
    input_rank = 5  # input ndim incl. batch+channel (unannotated: not a flax field)
    num_classes: int = 1
    width: int = 8
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = ConvBNReLU3D(self.width, kernel=3, dtype=self.dtype, name="f0")(x, train)
        x = _pool(x, "max", 2, 2)
        x = ConvBNReLU3D(self.width * 2, kernel=3, dtype=self.dtype, name="f1")(x, train)
        x = _pool(x, "max", 2, 2)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(32, dtype=self.dtype, name="fc1")(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc2")(x)
        return x.astype(jnp.float32)


class BasicBlock3D(nn.Module):
    """3D residual basic block (salient_models.py:13-42)."""
    planes: int
    stride: int = 1
    downsample: bool = False
    dtype: Dtype = jnp.float32
    expansion: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        out = nn.Conv(self.planes, (3,) * 3, strides=(self.stride,) * 3,
                      padding=[(1, 1)] * 3, use_bias=False, dtype=self.dtype,
                      name="conv1")(x)
        out = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                           dtype=jnp.float32, name="bn1")(out)
        out = nn.relu(out)
        out = nn.Conv(self.planes, (3,) * 3, padding=[(1, 1)] * 3,
                      use_bias=False, dtype=self.dtype, name="conv2")(out)
        out = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                           dtype=jnp.float32, name="bn2")(out)
        if self.downsample:
            residual = nn.Conv(self.planes * self.expansion, (1,) * 3,
                               strides=(self.stride,) * 3, use_bias=False,
                               dtype=self.dtype, name="ds_conv")(x)
            residual = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                    dtype=jnp.float32, name="ds_bn")(residual)
        return nn.relu(out + residual)


class Bottleneck3D(nn.Module):
    """3D bottleneck block, expansion 4 (salient_models.py:45-81)."""
    planes: int
    stride: int = 1
    downsample: bool = False
    dtype: Dtype = jnp.float32
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x

        def bn(name):
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                dtype=jnp.float32, name=name)

        out = nn.relu(bn("bn1")(nn.Conv(self.planes, (1,) * 3, use_bias=False,
                                        dtype=self.dtype, name="conv1")(x)))
        out = nn.relu(bn("bn2")(nn.Conv(self.planes, (3,) * 3,
                                        strides=(self.stride,) * 3,
                                        padding=[(1, 1)] * 3, use_bias=False,
                                        dtype=self.dtype, name="conv2")(out)))
        out = bn("bn3")(nn.Conv(self.planes * 4, (1,) * 3, use_bias=False,
                                dtype=self.dtype, name="conv3")(out))
        if self.downsample:
            residual = nn.Conv(self.planes * self.expansion, (1,) * 3,
                               strides=(self.stride,) * 3, use_bias=False,
                               dtype=self.dtype, name="ds_conv")(x)
            residual = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                    dtype=jnp.float32, name="ds_bn")(residual)
        return nn.relu(out + residual)


class ResNet3D_l3(nn.Module):
    """3-stage 3D ResNet; returns ``(logits, penultimate)``
    (salient_models.py:84-139). ``block`` is "basic" or "bottleneck"."""
    input_rank = 5  # input ndim incl. batch+channel (unannotated: not a flax field)
    layers: Sequence[int] = (1, 1, 1)
    num_classes: int = 2
    block: str = "basic"
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        blk = BasicBlock3D if self.block == "basic" else Bottleneck3D
        expansion = 1 if self.block == "basic" else 4
        x = nn.Conv(64, (3,) * 3, strides=(2,) * 3, padding=[(3, 3)] * 3,
                    use_bias=False, dtype=self.dtype, name="conv1")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=jnp.float32, name="bn1")(x)
        x = nn.relu(x)
        x = _pool(x, "max", 3, 2, pad=1)
        inplanes = 64
        for stage, (planes, blocks) in enumerate(zip((64, 128, 256), self.layers)):
            stride = 1 if stage == 0 else 2
            for i in range(blocks):
                s = stride if i == 0 else 1
                ds = i == 0 and (s != 1 or inplanes != planes * expansion)
                x = blk(planes, stride=s, downsample=ds, dtype=self.dtype,
                        name=f"layer{stage + 1}_{i}")(x, train)
                inplanes = planes * expansion
        x = _pool(x, "avg", 3, 3)
        x = x.reshape((x.shape[0], -1))
        x1 = nn.Dense(512, dtype=self.dtype, name="fc")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc2")(x1)
        return x.astype(jnp.float32), x1.astype(jnp.float32)
