"""2D ResNet-18 family for CIFAR / TinyImageNet.

Parity with fedml_api/model/cv/resnet.py: ``ResNet(BasicBlock, [2,2,2,2])``
with a 3x3 stem and no stem max-pool (CIFAR style, resnet.py:50-63);
``customized_resnet18`` swaps every BatchNorm for GroupNorm(32)
(resnet.py:96-125); ``original_resnet18`` keeps BatchNorm (resnet.py:127-131);
``tiny_resnet18`` adds adaptive average pooling for 64x64 TinyImageNet inputs
(resnet.py:134-213). Layout NHWC.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


class _Norm(nn.Module):
    """BatchNorm, GroupNorm(32), or IP-norm, selected by ``kind``.

    ``ipbn`` = per-batch statistics that are NEVER tracked (the reference's
    resnet_ip "independent personalization" BN, resnet_ip.py:33-359 —
    track_running_stats=False): every forward, train or eval, normalizes by
    the current batch's mean/var; only scale/bias are learnable state."""
    kind: str  # "bn" | "gn" | "ipbn"
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.kind == "gn":
            return nn.GroupNorm(num_groups=32, dtype=jnp.float32, name="norm")(x)
        if self.kind == "ipbn":
            axes = tuple(range(x.ndim - 1))
            x32 = x.astype(jnp.float32)
            mean = jnp.mean(x32, axis=axes, keepdims=True)
            var = jnp.var(x32, axis=axes, keepdims=True)
            y = (x32 - mean) * jax.lax.rsqrt(var + 1e-5)
            scale = self.param("scale", nn.initializers.ones,
                               (x.shape[-1],), jnp.float32)
            bias = self.param("bias", nn.initializers.zeros,
                              (x.shape[-1],), jnp.float32)
            return (y * scale + bias).astype(x.dtype)
        return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                            epsilon=1e-5, dtype=jnp.float32, name="norm")(x)


class BasicBlock2D(nn.Module):
    planes: int
    stride: int = 1
    norm: str = "bn"
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        out = nn.Conv(self.planes, (3, 3), strides=(self.stride,) * 2,
                      padding=[(1, 1)] * 2, use_bias=False, dtype=self.dtype,
                      name="conv1")(x)
        out = nn.relu(_Norm(self.norm, name="bn1")(out, train))
        out = nn.Conv(self.planes, (3, 3), padding=[(1, 1)] * 2, use_bias=False,
                      dtype=self.dtype, name="conv2")(out)
        out = _Norm(self.norm, name="bn2")(out, train)
        if self.stride != 1 or x.shape[-1] != self.planes:
            x = nn.Conv(self.planes, (1, 1), strides=(self.stride,) * 2,
                        use_bias=False, dtype=self.dtype, name="sc_conv")(x)
            x = _Norm(self.norm, name="sc_bn")(x, train)
        return nn.relu(out + x)


class ResNet18(nn.Module):
    """CIFAR-style ResNet-18: 3x3 stem, 4 stages of 2 basic blocks,
    4x4 avg-pool head (resnet.py:42-91). ``adaptive_pool=True`` gives the
    TinyImageNet global-pool variant (resnet.py:134-186)."""
    input_rank = 4  # input ndim incl. batch+channel (unannotated: not a flax field)
    num_classes: int = 10
    norm: str = "bn"
    num_blocks: Sequence[int] = (2, 2, 2, 2)
    adaptive_pool: bool = False
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (3, 3), padding=[(1, 1)] * 2, use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        x = nn.relu(_Norm(self.norm, name="bn1")(x, train))
        for stage, (planes, blocks) in enumerate(
                zip((64, 128, 256, 512), self.num_blocks)):
            for i in range(blocks):
                s = (1 if stage == 0 else 2) if i == 0 else 1
                x = BasicBlock2D(planes, stride=s, norm=self.norm,
                                 dtype=self.dtype,
                                 name=f"layer{stage + 1}_{i}")(x, train)
        if self.adaptive_pool:
            x = jnp.mean(x, axis=(1, 2))
        else:
            x = nn.avg_pool(x, (4, 4), strides=(4, 4))
            x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="linear")(x)
        return x.astype(jnp.float32)


def customized_resnet18(num_classes: int = 10, dtype=jnp.float32) -> ResNet18:
    """GroupNorm ResNet-18 (resnet.py:96-125)."""
    return ResNet18(num_classes=num_classes, norm="gn", dtype=dtype)


def original_resnet18(num_classes: int = 10, dtype=jnp.float32) -> ResNet18:
    """BatchNorm ResNet-18 (resnet.py:127-131)."""
    return ResNet18(num_classes=num_classes, norm="bn", dtype=dtype)


def tiny_resnet18(num_classes: int = 10, dtype=jnp.float32) -> ResNet18:
    """GroupNorm ResNet-18 with global average pooling (resnet.py:188-213)."""
    return ResNet18(num_classes=num_classes, norm="gn", adaptive_pool=True,
                    dtype=dtype)
