"""Small 2D vision models: VGG, CIFAR CNNs, FedAvg-paper CNNs, LeNet-5.

Parity targets in the reference:
- VGG11/16 with optional GroupNorm(32) (fedml_api/model/cv/vgg.py:14-88).
- ``cnn_cifar10``/``cnn_cifar100`` 2-conv + 3-fc nets
  (cnn_cifar10.py:12-52).
- ``CNN_OriginalFedAvg`` (McMahan et al. MNIST CNN) and ``CNN_DropOut``
  (Adaptive Federated Optimization EMNIST CNN) (cnn.py:6-160).
- ``LeNet5`` (Caffe variant, no padding in conv1) and ``LeNet5_cifar``
  (lenet5.py:4-47).

All NHWC; MNIST-family models accept [B, 28, 28] or [B, 28, 28, 1].
"""

from __future__ import annotations

from typing import Any, Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any

VGG_CFG = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
}


class VGG(nn.Module):
    """VGG feature stack + single linear classifier (vgg.py:14-60)."""
    input_rank = 4  # input ndim incl. batch+channel (unannotated: not a flax field)
    cfg: Sequence[Union[int, str]]
    num_classes: int = 10
    group_norm: bool = True
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        i = 0
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), padding=[(1, 1)] * 2,
                            dtype=self.dtype, name=f"conv{i}")(x)
                if self.group_norm:
                    x = nn.GroupNorm(num_groups=32, dtype=jnp.float32,
                                     name=f"gn{i}")(x)
                x = nn.relu(x)
                i += 1
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="classifier")(x)
        return x.astype(jnp.float32)


def vgg11(num_classes: int = 10, dtype=jnp.float32) -> VGG:
    return VGG(VGG_CFG["A"], num_classes=num_classes, dtype=dtype)


def vgg16(num_classes: int = 10, dtype=jnp.float32) -> VGG:
    return VGG(VGG_CFG["D"], num_classes=num_classes, dtype=dtype)


class CNNCifar(nn.Module):
    """2x(conv5 + maxpool2) + fc 384/192/n (cnn_cifar10.py:12-52; the
    cifar100 variant differs only in ``num_classes``)."""
    input_rank = 4  # input ndim incl. batch+channel (unannotated: not a flax field)
    num_classes: int = 10
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(64, (5, 5), padding="VALID", dtype=self.dtype,
                            name="conv1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (5, 5), padding="VALID", dtype=self.dtype,
                            name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(384, dtype=self.dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(192, dtype=self.dtype, name="fc2")(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc3")(x)
        return x.astype(jnp.float32)


class CNNCifarBN(nn.Module):
    """CNNCifar with BatchNorm after each conv — the BN-bearing twin used
    for whole-run BatchNorm federated-parity experiments (VERDICT r4
    missing #2: the flagship AlexNet3D is BN-everywhere,
    salient_models.py:147-168, but both prior parity models were
    norm-free). BN hyperparameters mirror torch.nn.BatchNorm2d defaults
    (momentum 0.1 -> flax momentum 0.9, eps 1e-5); the one KNOWN semantic
    difference vs torch is flax's biased running-variance update (torch
    uses the unbiased n/(n-1) batch variance for the running stat) — the
    parity experiment measures the end-to-end size of that plus the
    partial-batch deviation documented in core/trainer.py."""
    input_rank = 4  # input ndim incl. batch+channel (unannotated: not a flax field)
    num_classes: int = 10
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        norm = lambda name: nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=self.dtype, name=name)
        x = nn.Conv(64, (5, 5), padding="VALID", dtype=self.dtype,
                    name="conv1")(x)
        x = nn.relu(norm("bn1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="VALID", dtype=self.dtype,
                    name="conv2")(x)
        x = nn.relu(norm("bn2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(384, dtype=self.dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(192, dtype=self.dtype, name="fc2")(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc3")(x)
        return x.astype(jnp.float32)


def _ensure_channel(x):
    return x[..., None] if x.ndim == 3 else x


class CNN_OriginalFedAvg(nn.Module):
    """FedAvg-paper MNIST CNN, 1,663,370 params with only_digits (cnn.py:6-74)."""
    input_rank = 4  # input ndim incl. batch+channel (unannotated: not a flax field)
    only_digits: bool = True
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = _ensure_channel(x).astype(self.dtype)
        x = nn.relu(nn.Conv(32, (5, 5), padding=[(2, 2)] * 2, dtype=self.dtype,
                            name="conv1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (5, 5), padding=[(2, 2)] * 2, dtype=self.dtype,
                            name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512, dtype=self.dtype, name="fc1")(x))
        x = nn.Dense(10 if self.only_digits else 62, dtype=self.dtype,
                     name="fc2")(x)
        return x.astype(jnp.float32)


class CNN_DropOut(nn.Module):
    """Adaptive-Federated-Optimization EMNIST CNN (cnn.py:77-160)."""
    input_rank = 4  # input ndim incl. batch+channel (unannotated: not a flax field)
    only_digits: bool = True
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = _ensure_channel(x).astype(self.dtype)
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID", dtype=self.dtype,
                            name="conv1")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", dtype=self.dtype,
                            name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(10 if self.only_digits else 62, dtype=self.dtype,
                     name="fc2")(x)
        return x.astype(jnp.float32)


class LeNet5(nn.Module):
    """Caffe-style LeNet-5, no padding in conv1 (lenet5.py:4-27)."""
    input_rank = 4  # input ndim incl. batch+channel (unannotated: not a flax field)
    num_classes: int = 10
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = _ensure_channel(x).astype(self.dtype)
        x = nn.relu(nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype,
                            name="conv1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(50, (5, 5), padding="VALID", dtype=self.dtype,
                            name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(500, dtype=self.dtype, name="fc3")(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc4")(x)
        return x.astype(jnp.float32)


class LeNet5_cifar(nn.Module):
    """CIFAR LeNet (lenet5.py:29-47)."""
    input_rank = 4  # input ndim incl. batch+channel (unannotated: not a flax field)
    num_classes: int = 10
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(6, (5, 5), padding="VALID", dtype=self.dtype,
                            name="conv1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype,
                            name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=self.dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype, name="fc2")(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc3")(x)
        return x.astype(jnp.float32)
