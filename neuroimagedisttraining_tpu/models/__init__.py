"""Model zoo + registry.

``create_model`` mirrors the reference harness dispatch
(fedml_experiments/standalone/sailentgrads/main_sailentgrads.py:164-178:
``--model 3DCNN`` -> ``AlexNet3D_Dropout(num_classes=1)``), extended with
every model family the reference zoo contains.
"""

from __future__ import annotations

import jax.numpy as jnp

from neuroimagedisttraining_tpu.models.neuro3d import (  # noqa: F401
    AlexNet3D_Dropout,
    AlexNet3D_Deeper_Dropout,
    AlexNet3D_Dropout_Regression,
    BasicBlock3D,
    Bottleneck3D,
    ResNet3D_l3,
    Tiny3DCNN,
)
from neuroimagedisttraining_tpu.models.resnet2d import (  # noqa: F401
    ResNet18,
    customized_resnet18,
    original_resnet18,
    tiny_resnet18,
)
from neuroimagedisttraining_tpu.models.vision2d import (  # noqa: F401
    VGG,
    vgg11,
    vgg16,
    CNNCifar,
    CNN_OriginalFedAvg,
    CNN_DropOut,
    LeNet5,
    LeNet5_cifar,
)


def create_model(name: str, num_classes: int = 1, dtype=jnp.float32):
    """Build a model by its reference CLI name."""
    name = name.lower()
    if name in ("3dcnn", "alexnet3d", "alexnet3d_dropout"):
        return AlexNet3D_Dropout(num_classes=num_classes, dtype=dtype)
    if name in ("3dcnn_deeper", "alexnet3d_deeper_dropout"):
        return AlexNet3D_Deeper_Dropout(num_classes=num_classes, dtype=dtype)
    if name in ("3dcnn_regression", "alexnet3d_dropout_regression"):
        return AlexNet3D_Dropout_Regression(num_classes=num_classes, dtype=dtype)
    if name in ("3dcnn_tiny", "tiny3dcnn"):
        return Tiny3DCNN(num_classes=num_classes, dtype=dtype)
    if name in ("resnet3d", "resnet_l3", "resnet3d_l3"):
        return ResNet3D_l3(num_classes=num_classes, dtype=dtype)
    if name in ("resnet18", "customized_resnet18"):
        return customized_resnet18(num_classes=num_classes, dtype=dtype)
    if name == "original_resnet18":
        return original_resnet18(num_classes=num_classes, dtype=dtype)
    if name == "tiny_resnet18":
        return tiny_resnet18(num_classes=num_classes, dtype=dtype)
    if name == "vgg11":
        return vgg11(num_classes=num_classes, dtype=dtype)
    if name == "vgg16":
        return vgg16(num_classes=num_classes, dtype=dtype)
    if name in ("cnn_cifar10", "cnn_cifar100", "simple-cnn"):
        return CNNCifar(num_classes=num_classes, dtype=dtype)
    if name in ("cnn", "cnn_originalfedavg"):
        return CNN_OriginalFedAvg(only_digits=num_classes <= 10, dtype=dtype)
    if name in ("cnn_dropout", "femnist-cnn"):
        return CNN_DropOut(only_digits=num_classes <= 10, dtype=dtype)
    if name == "lenet5":
        return LeNet5(num_classes=num_classes, dtype=dtype)
    if name == "lenet5_cifar":
        return LeNet5_cifar(num_classes=num_classes, dtype=dtype)
    raise ValueError(f"unknown model: {name!r}")


def primary_logits(out):
    """Some reference models return ``[logits, aux]`` (salient_models.py:139,
    246, 297); normalize to the logits tensor."""
    if isinstance(out, (tuple, list)):
        return out[0]
    return out
