"""Model zoo + registry.

``create_model`` mirrors the reference harness dispatch
(fedml_experiments/standalone/sailentgrads/main_sailentgrads.py:164-178:
``--model 3DCNN`` -> ``AlexNet3D_Dropout(num_classes=1)``), extended with
every model family the reference zoo contains.

Also covered, though vestigial in the reference (constructed by no
main_*.py entry point, SURVEY.md §2.5): the meta/mask models
(``models/meta.py`` — CNNCifarMeta + MetaNet hypernetwork,
cnn_meta.py:17-176) and the DARTS NAS suite (``models/darts.py`` —
search supernet, GDAS, exact-autodiff bilevel architect, genotype
derivation, fixed-genotype evaluation net).

Explicitly SKIPPED:

- ``batchnorm_utils`` sync-BN helpers: torch-DDP-specific; cross-replica
  BN on TPU would be an axis-name mean inside shard_map, unused by every
  reference experiment.
- ``resnet_meta.py``/``resnet_meta_2.py``: the same mask-hypernetwork
  pattern as cnn_meta applied to a ResNet trunk; the pattern is covered
  by models/meta.py (MetaNet is trunk-agnostic), the specific trunks are
  dead code even upstream.

The reference's ``resnet_ip`` per-batch-BN personalization variant IS
covered: ``--model resnet18_ip`` (norm="ipbn", resnet2d._Norm).
"""

from __future__ import annotations

import jax.numpy as jnp

from neuroimagedisttraining_tpu.models.neuro3d import (  # noqa: F401
    AlexNet3D_Dropout,
    AlexNet3D_Deeper_Dropout,
    AlexNet3D_Dropout_Regression,
    BasicBlock3D,
    Bottleneck3D,
    ResNet3D_l3,
    Tiny3DCNN,
)
from neuroimagedisttraining_tpu.models.resnet2d import (  # noqa: F401
    ResNet18,
    customized_resnet18,
    original_resnet18,
    tiny_resnet18,
)
from neuroimagedisttraining_tpu.models.darts import (  # noqa: F401
    DARTS_V1,
    DARTS_V2,
    DartsNetwork,
    DartsSearch,
    DartsSearchNet,
    DartsTrainer,
    FedNAS_V1,
    Genotype,
    PRIMITIVES,
    derive_genotype,
)
from neuroimagedisttraining_tpu.models.meta import (  # noqa: F401
    CNNCifarMeta,
    MetaNet,
    ResNetMeta,
)
from neuroimagedisttraining_tpu.models.vision2d import (  # noqa: F401
    VGG,
    vgg11,
    vgg16,
    CNNCifar,
    CNNCifarBN,
    CNN_OriginalFedAvg,
    CNN_DropOut,
    LeNet5,
    LeNet5_cifar,
)


def create_model(name: str, num_classes: int = 1, dtype=jnp.float32,
                 remat: bool | str | None = None):
    """Build a model by its reference CLI name. ``remat`` (None = model
    default) applies to the 3D family: False | "stem" | True — see
    AlexNet3D_Dropout.remat and PROFILE.md."""
    name = name.lower()
    rkw = {} if remat is None else {"remat": remat}
    if name in ("3dcnn", "alexnet3d", "alexnet3d_dropout"):
        return AlexNet3D_Dropout(num_classes=num_classes, dtype=dtype, **rkw)
    if name in ("3dcnn_gn", "alexnet3d_dropout_gn"):
        return AlexNet3D_Dropout(num_classes=num_classes, dtype=dtype,
                                 norm="group", **rkw)
    if name in ("3dcnn_deeper", "alexnet3d_deeper_dropout"):
        return AlexNet3D_Deeper_Dropout(num_classes=num_classes, dtype=dtype,
                                        **rkw)
    if name in ("3dcnn_regression", "alexnet3d_dropout_regression"):
        return AlexNet3D_Dropout_Regression(num_classes=num_classes,
                                            dtype=dtype, **rkw)
    if name in ("3dcnn_tiny", "tiny3dcnn"):
        return Tiny3DCNN(num_classes=num_classes, dtype=dtype)
    if name in ("resnet3d", "resnet_l3", "resnet3d_l3"):
        return ResNet3D_l3(num_classes=num_classes, dtype=dtype)
    if name in ("resnet18", "customized_resnet18"):
        return customized_resnet18(num_classes=num_classes, dtype=dtype)
    if name == "original_resnet18":
        return original_resnet18(num_classes=num_classes, dtype=dtype)
    if name == "tiny_resnet18":
        return tiny_resnet18(num_classes=num_classes, dtype=dtype)
    if name in ("resnet18_ip", "resnet_ip"):
        return ResNet18(num_classes=num_classes, norm="ipbn", dtype=dtype)
    if name == "vgg11":
        return vgg11(num_classes=num_classes, dtype=dtype)
    if name == "vgg16":
        return vgg16(num_classes=num_classes, dtype=dtype)
    if name in ("cnn_cifar10", "cnn_cifar100", "simple-cnn"):
        return CNNCifar(num_classes=num_classes, dtype=dtype)
    if name in ("cnn_cifar10_bn", "cnn_cifar100_bn"):
        return CNNCifarBN(num_classes=num_classes, dtype=dtype)
    if name in ("cnn", "cnn_originalfedavg"):
        return CNN_OriginalFedAvg(only_digits=num_classes <= 10, dtype=dtype)
    if name in ("cnn_dropout", "femnist-cnn"):
        return CNN_DropOut(only_digits=num_classes <= 10, dtype=dtype)
    if name == "lenet5":
        return LeNet5(num_classes=num_classes, dtype=dtype)
    if name == "lenet5_cifar":
        return LeNet5_cifar(num_classes=num_classes, dtype=dtype)
    if name == "darts_search":
        return DartsSearchNet(num_classes=num_classes, dtype=dtype)
    if name in ("darts", "darts_v2"):
        return DartsNetwork(genotype=DARTS_V2, num_classes=num_classes,
                            dtype=dtype)
    if name == "fednas_v1":
        return DartsNetwork(genotype=FedNAS_V1, num_classes=num_classes,
                            dtype=dtype)
    if name in ("cnn_cifar10_meta", "cnn_meta"):
        return CNNCifarMeta(num_classes=num_classes, dtype=dtype)
    if name in ("resnet_meta", "resnet20_meta"):
        return ResNetMeta(num_classes=num_classes, dtype=dtype)
    raise ValueError(f"unknown model: {name!r}")


def primary_logits(out):
    """Some reference models return ``[logits, aux]`` (salient_models.py:139,
    246, 297); normalize to the logits tensor."""
    if isinstance(out, (tuple, list)):
        return out[0]
    return out
